"""Merged Chrome/Perfetto trace export: every replica's span stream on one
timeline.

Input is the metrics JSONL documented in torchft_tpu/metrics.py (``span``,
``step_summary``, ``fault``, ``drain_*`` records from any number of
replicas, one file or many).  Output is Chrome trace-event JSON — load it
in Perfetto (ui.perfetto.dev) or chrome://tracing — with:

- one **process** per replica group (the stable ``<group>`` prefix of
  ``<group>:<uuid>`` ids) and one **track (thread)** per incarnation, so a
  killed-and-restarted group shows its incarnations stacked in one lane;
  overlapped phases (the donor-side background ``snapshot``) get their own
  sub-track so the main track stays strictly sequential;
- phase **slices** (``X`` events) named by span phase, with ``step`` /
  ``slice_gen`` / ``ok`` in args;
- fault / drain / alert **instant** events, so a kill or a cooperative
  handoff is visible at the exact moment the goodput accounting charges it;
- **clock alignment** via the ``step_summary`` commit barrier: each
  committed step's summaries are written right after the same two-phase
  commit vote on every replica, so the cross-replica median of their wall
  timestamps estimates per-replica clock/write skew; each replica's events
  are shifted by its median offset before merging.  (Within one stream
  this is a no-op; across hosts it removes NTP-level skew without any
  shared clock.)

Span records carry their END timestamp (they are written when the phase
finishes); the slice start is ``ts - duration``.  Slices on one track are
clamped to be non-overlapping (later start wins), which keeps the trace
valid even when the quorum thread and the train thread measured
concurrently.

The CLI wrapper is tools/trace_export.py.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "PHASE_TRACKS",
    "build_trace",
    "validate_trace",
    "synthetic_stream",
    "synthetic_flight_stream",
    "synthetic_hop_stream",
    "hops_to_stream",
    "load_hops_dump",
]

# Track mapping for every registered span phase (obs/spans.py PHASES):
# "main" renders on the incarnation's sequential phase track, "background"
# on its overlapped sub-track (tid+1).  Every PHASES entry must appear
# here — a new phase without a mapping would silently land on the main
# track and could corrupt its non-overlap clamping.  Grep-pinned by
# tests/test_flight.py (static registry check).
PHASE_TRACKS = {
    "quorum": "main",
    "configure": "main",
    "heal": "main",
    "allreduce_d2h": "main",
    "allreduce_h2d": "main",
    "allreduce_merge": "main",
    "commit_vote": "main",
    "snapshot": "background",
    # The semisync engine's fragment rounds run on its worker thread,
    # concurrent with inner compute — same sub-track as the snapshotter.
    "outer_sync": "background",
    # Erasure-shard encode rides the snapshotter thread (background); the
    # reconstruction fallback blocks the healing quorum thread (main).
    "ec_encode": "background",
    "ec_reconstruct": "main",
}

# Events rendered as instant markers on the emitting replica's track (or
# the global track for the bench driver's fault schedule).
_INSTANT_EVENTS = (
    "fault",
    "drain_notice",
    "drain_complete",
    "drain_handoff",
    "drain_donor_exit",
    "alert",
    "link_shaped",
    "link_alert",
    "straggler_injected",
    "heal_start",
    "error",
)


def _group(replica_id: str) -> str:
    return str(replica_id).split(":", 1)[0]


def _median(values: Sequence[float]) -> float:
    ordered = sorted(values)
    return ordered[(len(ordered) - 1) // 2] if ordered else 0.0


def _clock_offsets(events: Sequence[dict]) -> Dict[str, float]:
    """Per-replica wall-clock offset estimated from the step_summary commit
    barrier: all replicas emit the summary for a committed step right after
    the same commit vote, so their timestamps SHOULD agree; the per-replica
    median deviation from the cross-replica median is that replica's skew."""
    by_step: Dict[Tuple[int, int], Dict[str, float]] = {}
    for ev in events:
        if ev.get("event") != "step_summary" or not ev.get("committed"):
            continue
        rid = str(ev.get("replica_id", ""))
        key = (int(ev.get("slice_gen", 0) or 0), int(ev.get("step", -1)))
        # First summary per (step, replica): retried steps re-summarize.
        by_step.setdefault(key, {}).setdefault(rid, float(ev.get("ts", 0.0)))
    deltas: Dict[str, List[float]] = {}
    for _, per_rid in by_step.items():
        if len(per_rid) < 2:
            continue  # no cross-replica barrier to compare against
        ref = _median(list(per_rid.values()))
        for rid, ts in per_rid.items():
            deltas.setdefault(rid, []).append(ts - ref)
    return {rid: _median(ds) for rid, ds in deltas.items()}


def build_trace(events: Sequence[dict], align: bool = True) -> dict:
    """Builds the Chrome trace-event dict from merged metrics events."""
    offsets = _clock_offsets(events) if align else {}

    def corrected(ev: dict) -> float:
        return float(ev.get("ts", 0.0)) - offsets.get(
            str(ev.get("replica_id", "")), 0.0
        )

    spans = [ev for ev in events if ev.get("event") == "span"]
    instants = [ev for ev in events if ev.get("event") in _INSTANT_EVENTS]
    # Data-plane hop records (the ring engines' flight recorder,
    # hops_to_stream / hops_*.json dumps): rendered as per-(tier, lane)
    # tracks inside the replica's process, time-aligned with its phase
    # tracks — the view that shows whether comms actually overlap compute.
    hops = [ev for ev in events if ev.get("event") == "hop"]
    # Control-plane stream (obs/flight.py flight_to_stream): RPC spans and
    # state instants from the native servers' flight recorders, rendered
    # on their own process next to the worker tracks.
    cp_rpcs = [ev for ev in events if ev.get("event") == "cp_rpc"]
    cp_instants = [ev for ev in events if ev.get("event") == "cp_event"]
    if not spans and not instants and not hops and not cp_rpcs and not cp_instants:
        return {"traceEvents": [], "displayTimeUnit": "ms"}

    # Only span- or hop-emitting replicas get tracks; instants from
    # anything else (the bench driver's fault schedule, the launcher)
    # render on the global pid-0 lane instead of minting a phantom replica.
    first_seen: Dict[str, float] = {}
    for ev in spans:
        rid = str(ev.get("replica_id", ""))
        ts = corrected(ev)
        if rid not in first_seen or ts < first_seen[rid]:
            first_seen[rid] = ts
    for ev in hops:
        rid = str(ev.get("replica_id", ""))
        ts = corrected(ev)
        if rid not in first_seen or ts < first_seen[rid]:
            first_seen[rid] = ts
    for ev in instants:
        rid = str(ev.get("replica_id", ""))
        if rid in first_seen:
            first_seen[rid] = min(first_seen[rid], corrected(ev))
    groups = sorted({_group(rid) for rid in first_seen})
    pid_of = {g: i + 1 for i, g in enumerate(groups)}
    tid_of: Dict[str, int] = {}
    for g in groups:
        incarnations = sorted(
            (rid for rid in first_seen if _group(rid) == g),
            key=lambda rid: (first_seen[rid], rid),
        )
        for i, rid in enumerate(incarnations):
            tid_of[rid] = 1 + 2 * i  # odd = phases, even (tid+1) = background

    # Control-plane processes: one pid per source after the worker groups,
    # one track per (RPC method, peer) pair — frames on one CONNECTION are
    # handled strictly sequentially by the server, so per-peer lanes are
    # genuinely non-overlapping, whereas a per-method-only lane is not
    # (two groups' Quorum handlers block through the same formation window
    # concurrently, and the non-overlap clamp would collapse the second
    # span to zero).  tid 0 carries the state-transition instants.
    # Control-plane timestamps use the server's wall clock with no
    # per-replica offset: worker offsets are corrections TOWARD the
    # cross-replica median, which is the same frame a one-host control
    # plane's clock sits in.
    cp_sources = sorted(
        {str(ev.get("source", "control-plane")) for ev in cp_rpcs + cp_instants}
    )
    cp_pid_of = {s: len(groups) + 1 + i for i, s in enumerate(cp_sources)}
    cp_lanes: Dict[str, List[Tuple[str, str]]] = {
        s: sorted(
            {
                (str(ev.get("method", "?")), str(ev.get("peer", "")))
                for ev in cp_rpcs
                if str(ev.get("source", "control-plane")) == s
            }
        )
        for s in cp_sources
    }
    cp_tid_of = {
        (s, m, p): 1 + 2 * i
        for s in cp_sources
        for i, (m, p) in enumerate(cp_lanes[s])
    }

    # Data-plane lanes: one track per (replica, tier, lane) carrying hop
    # slices, tid-spaced far above the phase/background pair so
    # incarnation tids can never collide (odd so the validate rule "odd
    # tids carry their own thread metadata" applies to them directly).
    dp_lanes: Dict[str, List[Tuple[int, int]]] = {}
    for ev in hops:
        rid = str(ev.get("replica_id", ""))
        if rid not in tid_of:
            continue
        key = (int(ev.get("tier", 0) or 0), int(ev.get("lane", 0) or 0))
        lanes = dp_lanes.setdefault(rid, [])
        if key not in lanes:
            lanes.append(key)
    dp_tid_of: Dict[Tuple[str, int, int], int] = {}
    for rid, lanes in dp_lanes.items():
        for i, (tier, lane) in enumerate(sorted(lanes)):
            dp_tid_of[(rid, tier, lane)] = 100 * tid_of[rid] + 1 + 2 * i

    t0 = min(
        min(
            (corrected(ev) - float(ev.get("duration_ms", 0.0)) / 1e3 for ev in spans),
            default=float("inf"),
        ),
        min((corrected(ev) for ev in hops), default=float("inf")),
        min((corrected(ev) for ev in instants), default=float("inf")),
        min(
            (
                float(ev.get("ts", 0.0)) - float(ev.get("duration_ms", 0.0)) / 1e3
                for ev in cp_rpcs
            ),
            default=float("inf"),
        ),
        min((float(ev.get("ts", 0.0)) for ev in cp_instants), default=float("inf")),
    )

    def us(ts: float) -> float:
        return round((ts - t0) * 1e6, 1)

    out: List[dict] = []
    for g in groups:
        out.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid_of[g],
                "tid": 0,
                "args": {"name": f"replica group {g}"},
            }
        )
    out.append(
        {
            "ph": "M",
            "name": "process_name",
            "pid": 0,
            "tid": 0,
            "args": {"name": "bench driver / faults"},
        }
    )
    for s in cp_sources:
        out.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": cp_pid_of[s],
                "tid": 0,
                "args": {"name": f"control plane {s}"},
            }
        )
        for m, p in cp_lanes[s]:
            out.append(
                {
                    "ph": "M",
                    "name": "thread_name",
                    "pid": cp_pid_of[s],
                    "tid": cp_tid_of[(s, m, p)],
                    "args": {"name": f"{s} {m} {p}".rstrip()},
                }
            )
    for rid, tid in sorted(tid_of.items()):
        pid = pid_of[_group(rid)]
        out.append(
            {
                "ph": "M",
                "name": "thread_name",
                "pid": pid,
                "tid": tid,
                "args": {"name": rid},
            }
        )
    _tier_names = {0: "flat", 1: "row", 2: "col"}
    for (rid, tier, lane), tid in sorted(dp_tid_of.items()):
        out.append(
            {
                "ph": "M",
                "name": "thread_name",
                "pid": pid_of[_group(rid)],
                "tid": tid,
                "args": {
                    "name": f"{rid} dp:{_tier_names.get(tier, tier)} lane{lane}"
                },
            }
        )

    # Phase slices, clamped non-overlapping per track.
    per_track: Dict[Tuple[int, int], List[dict]] = {}
    for ev in spans:
        rid = str(ev.get("replica_id", ""))
        if rid not in tid_of:
            continue
        pid = pid_of[_group(rid)]
        phase = str(ev.get("phase", "?"))
        tid = tid_of[rid] + (
            1 if PHASE_TRACKS.get(phase, "main") == "background" else 0
        )
        dur_s = float(ev.get("duration_ms", 0.0)) / 1e3
        end = corrected(ev)
        args = {
            k: ev[k]
            for k in ("step", "slice_gen", "src_rank")
            if ev.get(k) is not None
        }
        if ev.get("ok") is False:
            args["ok"] = False
        per_track.setdefault((pid, tid), []).append(
            {
                "ph": "X",
                "pid": pid,
                "tid": tid,
                "name": phase,
                "cat": "phase",
                "_start": end - dur_s,
                "_end": end,
                "args": args,
            }
        )
    # Data-plane hop slices: one per recorded hop, on the replica's
    # (tier, lane) track.  ``ts`` is the hop START (unlike span records,
    # whose ts is the end); duration is the hop's full wait+combine.
    # Stripes sharing a lane can interleave, so hop slices ride the same
    # non-overlap clamp as phases.
    for ev in hops:
        rid = str(ev.get("replica_id", ""))
        key = (
            rid,
            int(ev.get("tier", 0) or 0),
            int(ev.get("lane", 0) or 0),
        )
        tid = dp_tid_of.get(key)
        if tid is None:
            continue
        pid = pid_of[_group(rid)]
        start = corrected(ev)
        dur_s = (
            float(ev.get("send_s", 0.0))
            + float(ev.get("recv_s", 0.0))
            + float(ev.get("comb_s", 0.0))
        )
        tag = int(ev.get("tag", 0) or 0)
        sub = tag % 8
        phase = {1: "rs", 2: "ag", 3: "gather", 4: "rs", 5: "ag"}.get(sub, "hop")
        per_track.setdefault((pid, tid), []).append(
            {
                "ph": "X",
                "pid": pid,
                "tid": tid,
                "name": f"hop:{phase}",
                "cat": "hop",
                "_start": start,
                "_end": start + dur_s,
                "args": {
                    k: ev[k]
                    for k in ("tag", "send_s", "recv_s", "comb_s", "nbytes")
                    if ev.get(k) is not None
                },
            }
        )
    # Control-plane RPC slices: per (source, method, peer) lane, same
    # clamping (a no-op within a lane — see the lane-layout comment).
    for ev in cp_rpcs:
        s = str(ev.get("source", "control-plane"))
        m = str(ev.get("method", "?"))
        pid = cp_pid_of[s]
        tid = cp_tid_of[(s, m, str(ev.get("peer", "")))]
        dur_s = float(ev.get("duration_ms", 0.0)) / 1e3
        end = float(ev.get("ts", 0.0))
        args = {
            k: ev[k]
            for k in ("trace_id", "peer", "status")
            if ev.get(k) not in (None, "")
        }
        per_track.setdefault((pid, tid), []).append(
            {
                "ph": "X",
                "pid": pid,
                "tid": tid,
                "name": m,
                "cat": "cp_rpc",
                "_start": end - dur_s,
                "_end": end,
                "args": args,
            }
        )
    for (_, _), slices in per_track.items():
        slices.sort(key=lambda s: (s["_start"], s["_end"]))
        prev_end = float("-inf")
        for s in slices:
            start = max(s["_start"], prev_end)
            end = max(s["_end"], start)
            prev_end = end
            s["ts"] = us(start)
            s["dur"] = round((end - start) * 1e6, 1)
            del s["_start"], s["_end"]
            out.append(s)

    # Instant markers.
    for ev in instants:
        rid = str(ev.get("replica_id", ""))
        kind = str(ev.get("event"))
        name = kind
        if kind == "fault":
            name = f"fault:{ev.get('kind', '?')} g{ev.get('group', '?')}"
        args = {
            k: v
            for k, v in ev.items()
            if k
            not in ("ts", "t_mono", "schema", "event", "replica_id")
            and v is not None
        }
        if rid in tid_of:
            out.append(
                {
                    "ph": "i",
                    "s": "t",
                    "pid": pid_of[_group(rid)],
                    "tid": tid_of[rid],
                    "name": name,
                    "cat": "event",
                    "ts": us(corrected(ev)),
                    "args": args,
                }
            )
        else:
            # Driver records (fault schedule) are cluster-scoped.
            out.append(
                {
                    "ph": "i",
                    "s": "g",
                    "pid": 0,
                    "tid": 0,
                    "name": name,
                    "cat": "event",
                    "ts": us(corrected(ev)),
                    "args": args,
                }
            )

    # Control-plane state transitions: instants on the source's tid 0.
    for ev in cp_instants:
        s = str(ev.get("source", "control-plane"))
        args = {
            k: v
            for k, v in ev.items()
            if k not in ("ts", "event", "source", "kind") and v not in (None, "")
        }
        out.append(
            {
                "ph": "i",
                "s": "t",
                "pid": cp_pid_of[s],
                "tid": 0,
                "name": f"cp:{ev.get('kind', '?')}",
                "cat": "cp_event",
                "ts": us(float(ev.get("ts", 0.0))),
                "args": args,
            }
        )

    out.sort(key=lambda ev: (ev.get("ts", 0.0), ev.get("pid", 0), ev.get("tid", 0)))
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "generator": "tpu-ft tools/trace_export.py",
            "replicas": {rid: f"pid {pid_of[_group(rid)]} tid {tid}"
                         for rid, tid in tid_of.items()},
            "control_plane": {s: f"pid {cp_pid_of[s]}" for s in cp_sources},
            "clock_offsets_s": {k: round(v, 6) for k, v in offsets.items()},
        },
    }


def validate_trace(trace: dict) -> List[str]:
    """Structural checks on a Chrome trace-event dict; returns problems
    (empty list = valid).  Pinned by tests/test_obs.py and --quick."""
    problems: List[str] = []
    evs = trace.get("traceEvents")
    if not isinstance(evs, list):
        return ["traceEvents is not a list"]
    tracks: Dict[Tuple[int, int], float] = {}
    thread_names: Dict[Tuple[int, int], str] = {}
    for i, ev in enumerate(evs):
        ph = ev.get("ph")
        if ph not in ("X", "i", "M"):
            problems.append(f"event {i}: unexpected ph {ph!r}")
            continue
        if ph == "M":
            if ev.get("name") == "thread_name":
                key = (ev.get("pid"), ev.get("tid"))
                name = ev.get("args", {}).get("name", "")
                if key in thread_names:
                    problems.append(f"duplicate thread metadata for {key}")
                thread_names[key] = name
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            problems.append(f"event {i} ({ev.get('name')}): bad ts {ts!r}")
            continue
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"event {i} ({ev.get('name')}): bad dur {dur!r}")
                continue
            key = (ev.get("pid"), ev.get("tid"))
            prev_end = tracks.get(key, float("-inf"))
            if ts < prev_end - 0.5:  # 0.5 us slack for rounding
                problems.append(
                    f"event {i} ({ev.get('name')}): overlaps previous slice "
                    f"on track {key} ({ts} < {prev_end})"
                )
            tracks[key] = max(prev_end, ts + dur)
    # One named track per replica: every (pid, tid) that carries slices on
    # an odd tid (the phases track) must have thread metadata.
    for (pid, tid) in tracks:
        base = (pid, tid if tid % 2 == 1 else tid - 1)
        if pid != 0 and base not in thread_names:
            problems.append(f"track {(pid, tid)} has slices but no thread_name")
    names = list(thread_names.values())
    if len(names) != len(set(names)):
        problems.append("replica track names are not unique")
    return problems


def synthetic_stream(
    n_replicas: int = 2, steps: int = 4, base_ts: float = 1_700_000_000.0
) -> List[dict]:
    """Deterministic multi-replica stream for --quick and tests: per step a
    quorum span, an allreduce_merge span, a commit and a step_summary per
    replica; replica 1 pays a heal on step 2; one kill fault and one drain
    instant ride along."""
    events: List[dict] = []
    step_s = 1.0
    for r in range(n_replicas):
        rid = f"{r}:{'abcdef'[r % 6]}{r}"
        skew = 0.002 * r  # small per-replica clock skew the aligner removes
        for step in range(1, steps + 1):
            end = base_ts + step * step_s + skew
            quorum_ms = 40.0 + 5 * r
            from torchft_tpu.obs.flight import mint_trace_id

            events.append(
                {
                    "ts": end - 0.5,
                    "replica_id": rid,
                    "event": "span",
                    "phase": "quorum",
                    "step": step,
                    "slice_gen": 0,
                    "duration_ms": quorum_ms,
                    "trace_id": mint_trace_id(0, rid, step),
                }
            )
            if r == 1 and step == 2:
                events.append(
                    {
                        "ts": end - 0.1,
                        "replica_id": rid,
                        "event": "span",
                        "phase": "heal",
                        "step": step,
                        "slice_gen": 0,
                        "duration_ms": 350.0,
                        "src_rank": 0,
                    }
                )
            events.append(
                {
                    "ts": end,
                    "replica_id": rid,
                    "event": "span",
                    "phase": "allreduce_merge",
                    "step": step,
                    "slice_gen": 0,
                    "duration_ms": 20.0,
                }
            )
            events.append(
                {
                    "ts": end,
                    "replica_id": rid,
                    "event": "commit",
                    "step": step,
                    "committed": True,
                }
            )
            events.append(
                {
                    "ts": end + 0.001,
                    "replica_id": rid,
                    "event": "step_summary",
                    "step": step,
                    "slice_gen": 0,
                    "committed": True,
                    "phases": {"quorum": quorum_ms, "allreduce_merge": 20.0},
                }
            )
    events.append(
        {
            "ts": base_ts + 2.4,
            "replica_id": "bench-driver",
            "event": "fault",
            "kind": "kill",
            "group": "1",
        }
    )
    events.append(
        {
            "ts": base_ts + 3.2,
            "replica_id": "0:a0",
            "event": "drain_notice",
            "source": "supervisor",
        }
    )
    events.sort(key=lambda ev: ev["ts"])
    return events


def synthetic_flight_stream(
    n_replicas: int = 2, steps: int = 4, base_ts: float = 1_700_000_000.0
) -> List[dict]:
    """Control-plane companion to :func:`synthetic_stream`: the lighthouse
    flight recorder's view of the same run — one server-side Quorum RPC
    span per (replica, step) whose trace id matches the worker stream's
    quorum span, periodic Heartbeat spans, and a ``quorum_formed``
    transition when the membership first assembles.  Used by
    ``tools/trace_export.py --quick`` and the tier-1 trace tests."""
    from torchft_tpu.obs.flight import mint_trace_id

    source = "lighthouse:29510"
    events: List[dict] = []
    members = [f"{r}:{'abcdef'[r % 6]}{r}" for r in range(n_replicas)]
    events.append(
        {
            "event": "cp_event",
            "source": source,
            "ts": base_ts + 0.95,
            "kind": "quorum_formed",
            "d_quorum_id": 1,
            "d_members": members,
            "d_joined": members,
            "d_left": [],
            "d_formation_ms": 42.0,
        }
    )
    for r, rid in enumerate(members):
        for step in range(1, steps + 1):
            end = base_ts + step * 1.0 + 0.002 * r - 0.46
            quorum_ms = 38.0 + 5 * r
            events.append(
                {
                    "event": "cp_rpc",
                    "source": source,
                    "ts": end,
                    "method": "Quorum",
                    "status": 0,
                    "peer": f"127.0.0.1:5{r}000",
                    "trace_id": mint_trace_id(0, rid, step),
                    "duration_ms": quorum_ms,
                }
            )
            events.append(
                {
                    "event": "cp_rpc",
                    "source": source,
                    "ts": end + 0.1,
                    "method": "Heartbeat",
                    "status": 0,
                    "peer": f"127.0.0.1:5{r}000",
                    "trace_id": mint_trace_id(0, rid, step),
                    "duration_ms": 0.05,
                }
            )
    events.sort(key=lambda ev: ev["ts"])
    return events


def load_hops_dump(path: str) -> dict:
    """Loads one ``hops_<replica>.json`` dump (Manager shutdown with
    ``TPUFT_HOP_DUMP_DIR`` set, or a bench's direct
    ``TCPCollective.hop_records()`` write).  Raises ValueError on a
    malformed document."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or not isinstance(doc.get("records"), list):
        raise ValueError(f"{path}: not a hop dump (missing records list)")
    return doc


def hops_to_stream(dump: dict) -> List[dict]:
    """Converts one hop dump into ``event: "hop"`` records for
    :func:`build_trace` — each carries the replica id plus the raw
    RingHopRecord fields (hop-start wall-clock ``ts``)."""
    rid = str(dump.get("replica_id", ""))
    out: List[dict] = []
    for rec in dump.get("records", []):
        if not isinstance(rec, dict) or "ts" not in rec:
            continue
        ev = dict(rec)
        ev["event"] = "hop"
        ev["replica_id"] = rid
        out.append(ev)
    return out


def synthetic_hop_stream(
    n_replicas: int = 2, steps: int = 4, base_ts: float = 1_700_000_000.0
) -> List[dict]:
    """Data-plane companion to :func:`synthetic_stream`: per (replica,
    step) a short burst of rs/ag hops on two lanes of the flat tier, in
    the window the worker stream's allreduce_merge span covers.  Used by
    ``tools/trace_export.py --quick`` and the tier-1 trace tests."""
    events: List[dict] = []
    for r in range(n_replicas):
        rid = f"{r}:{'abcdef'[r % 6]}{r}"
        for step in range(1, steps + 1):
            end = base_ts + step * 1.0 + 0.002 * r
            for lane in (0, 1):
                for h, sub in enumerate((1, 1, 2, 2)):  # rs, rs, ag, ag
                    events.append(
                        {
                            "event": "hop",
                            "replica_id": rid,
                            "ts": end - 0.4 + 0.08 * h + 0.01 * lane,
                            "tier": 0,
                            "lane": lane,
                            "tag": 65 * 8 * step + lane * 8 + sub,
                            "send_s": 0.004,
                            "recv_s": 0.05,
                            "comb_s": 0.002 if sub == 1 else 0.0,
                            "nbytes": 1 << 16,
                        }
                    )
    events.sort(key=lambda ev: ev["ts"])
    return events


def export(
    paths: Sequence[str],
    out_path: str,
    align: bool = True,
    stats: Optional[dict] = None,
    flight_paths: Sequence[str] = (),
    hops_paths: Sequence[str] = (),
) -> dict:
    """Reads JSONL streams (plus optional flight-recorder and hop-timeline
    dumps), builds the trace, writes ``out_path``.  Returns a summary dict
    (events, replicas, control-plane tracks, data-plane tracks,
    problems)."""
    from torchft_tpu.obs.report import read_events

    read_stats: dict = {}
    events = read_events(paths, stats=read_stats)
    flight_skipped: List[str] = []
    for fp in flight_paths:
        # A torn dump (server killed mid-write never happens — the dump is
        # atomic — but a foreign/corrupt file can be handed in) is skipped
        # and counted, like garbage JSONL lines.
        try:
            from torchft_tpu.obs.flight import flight_to_stream, load_flight_dump

            events.extend(flight_to_stream(load_flight_dump(fp)))
        except (OSError, ValueError):
            flight_skipped.append(fp)
    hops_skipped: List[str] = []
    for hp in hops_paths:
        try:
            events.extend(hops_to_stream(load_hops_dump(hp)))
        except (OSError, ValueError):
            hops_skipped.append(hp)
    events.sort(key=lambda ev: float(ev.get("ts", 0.0)))
    trace = build_trace(events, align=align)
    problems = validate_trace(trace)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(trace, f)
    replicas = trace.get("otherData", {}).get("replicas", {})
    control_plane = trace.get("otherData", {}).get("control_plane", {})
    dp_tracks = sum(
        1
        for ev in trace["traceEvents"]
        if ev.get("ph") == "M"
        and ev.get("name") == "thread_name"
        and " dp:" in str(ev.get("args", {}).get("name", ""))
    )
    summary = {
        "out": out_path,
        "input_events": len(events),
        "skipped_lines": read_stats.get("skipped_lines", 0),
        "trace_events": len(trace["traceEvents"]),
        "replicas": len(replicas),
        "control_plane_tracks": len(control_plane),
        "data_plane_tracks": dp_tracks,
        "unreadable_flight_dumps": flight_skipped,
        "unreadable_hop_dumps": hops_skipped,
        "problems": problems,
        "ok": not problems,
    }
    if stats is not None:
        stats.update(summary)
    return summary
