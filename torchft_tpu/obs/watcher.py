"""IncidentWatcher: the production-side incident loop.

The bench cells drive :mod:`torchft_tpu.obs.incident` by hand; nothing
watched the feed in a real run.  This daemon closes that gap: it polls a
lighthouse's ``GET /incident.json`` + ``GET /alerts.json`` (failing over
across an address list and following HA-standby redirects), auto-captures
an evidence bundle for every fresh trigger, computes the verdict, maps
the verdict kind to a *recommended* remediation policy through a
debounced flap guard, and appends every decision to a machine-readable
``watcher_journal.jsonl``.

The watcher RECOMMENDS, it does not remediate: dry-run is the default,
and ``--act`` gates the one action that already exists (the cooperative
drain) — the policy kinds it names (re-stripe / respawn / rebalance) are
reserved for the remediation PR (ROADMAP item 3).  The journal is the
contract either way: one line per decision, so a remediation loop (or an
operator) replays exactly what the watcher saw and when.

Journal record::

    {"ts": epoch_s, "incident_id": N, "reason": ..., "kind": ...,
     "target": "<group>", "policy": "drain", "acted": false,
     "bundle": "incident_<step>", "verdict": {...}}

Flap guard: one journal entry per (policy, target) pair per
``TPUFT_WATCHER_DEBOUNCE_S`` window (default 30 s) — a goodput_floor and
its slo_burn alert both naming the same victim within a window record
ONE recommendation, and a flapping sentinel cannot journal-spam.

Run standalone (``python -m torchft_tpu.obs.watcher --lighthouse ...``)
or let :mod:`torchft_tpu.launch` embed it (``--incident-watcher``).
"""

from __future__ import annotations

import argparse
import json
import os
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from torchft_tpu.obs.incident import (
    _http_base,
    capture_bundle,
    fetch_json,
    finalize_bundle,
)

__all__ = ["IncidentWatcher", "POLICY_BY_KIND", "main"]

# Verdict kind -> recommended remediation policy.  Only "drain" is
# actionable today (the cooperative-drain path exists end to end); the
# rest name the remediation the robustness PR will implement.
POLICY_BY_KIND: Dict[str, str] = {
    "kill": "respawn",        # supervisor restarts the dead group
    "region_loss": "rebalance",  # shift quorum floor / spares across regions
    "straggler": "drain",     # rotate the slow host out cooperatively
    "slow_link": "re-stripe", # move ring striping off the degraded edge
    "redundancy": "re-stripe",  # re-encode to restore shard coverage
    "goodput_dip": "drain",   # culprit-named dip: rotate the culprit out
    "slo_burn": "drain",      # sustained burn: rotate the culprit out
}


class IncidentWatcher:
    """Polls the incident feed, captures bundles, journals recommendations.

    Args:
        addresses: lighthouse HTTP addresses, tried in order (leader +
            standbys; standby GETs redirect to the leader, so any live
            address works — the list is for the address that is DOWN).
        workdir: bundle + journal directory.
        act: when True, a "drain" recommendation is executed (via
            ``drain_cb`` when given, else ``POST /replica/<group>/drain``
            against the serving lighthouse).  Everything else is always
            dry-run.
        metrics_paths: span JSONL streams to tail into each bundle.
        poll_interval_s / debounce_s: poll throttle and flap-guard window
            (defaults from TPUFT_WATCHER_POLL_S / TPUFT_WATCHER_DEBOUNCE_S).
        drain_cb: ``fn(group) -> None`` used for --act drains (the
            launcher wires its own ``Launcher.drain``).
        fetch / clock: injectables for unit tests — ``fetch(address,
            path)`` replaces the HTTP client, ``clock()`` replaces
            ``time.monotonic``.
    """

    def __init__(
        self,
        addresses: Sequence[str],
        workdir: str,
        *,
        act: bool = False,
        metrics_paths: Sequence[str] = (),
        poll_interval_s: Optional[float] = None,
        debounce_s: Optional[float] = None,
        drain_cb: Optional[Callable[[str], None]] = None,
        fetch: Optional[Callable[[str, str], Optional[dict]]] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.addresses = [a for a in addresses if a]
        if not self.addresses:
            raise ValueError("IncidentWatcher needs at least one address")
        self.workdir = workdir
        self.act = act
        self.metrics_paths = list(metrics_paths)
        self.poll_interval_s = (
            poll_interval_s
            if poll_interval_s is not None
            else _env_float("TPUFT_WATCHER_POLL_S", 2.0)
        )
        self.debounce_s = (
            debounce_s
            if debounce_s is not None
            else _env_float("TPUFT_WATCHER_DEBOUNCE_S", 30.0)
        )
        self._drain_cb = drain_cb
        self._fetch = fetch or fetch_json
        self._clock = clock
        self._seen: set = set()
        self._last_poll = float("-inf")
        self._last_action: Dict[Tuple[str, str], float] = {}
        self._good_addr = 0  # index of the last address that answered
        self.journal_path = os.path.join(workdir, "watcher_journal.jsonl")

    # -- feed access --------------------------------------------------------

    def _get(self, path: str) -> Optional[dict]:
        """Fetch with failover: start from the last good address, walk the
        list; remember whoever answers."""
        n = len(self.addresses)
        for off in range(n):
            i = (self._good_addr + off) % n
            doc = self._fetch(self.addresses[i], path)
            if doc is not None:
                self._good_addr = i
                return doc
        return None

    def serving_address(self) -> str:
        return self.addresses[self._good_addr]

    # -- the loop body ------------------------------------------------------

    def poll_once(self, force: bool = False) -> List[dict]:
        """One watcher iteration (internally throttled to
        ``poll_interval_s`` unless ``force``).  Returns the journal
        records appended this call."""
        now = self._clock()
        if not force and now - self._last_poll < self.poll_interval_s:
            return []
        self._last_poll = now
        feed = self._get("/incident.json")
        if not feed:
            return []
        appended: List[dict] = []
        for rec in feed.get("incidents", []):
            if not isinstance(rec, dict):
                continue
            rid = rec.get("id")
            if rid in self._seen:
                continue
            self._seen.add(rid)
            entry = self._handle_trigger(rec)
            if entry is not None:
                appended.append(entry)
        return appended

    def run(self, stop: Optional[Callable[[], bool]] = None) -> None:
        """Blocking loop for standalone use; ``stop()`` (when given) is
        checked each interval."""
        while not (stop and stop()):
            self.poll_once(force=True)
            time.sleep(self.poll_interval_s)

    # -- internals ----------------------------------------------------------

    def _handle_trigger(self, incident: dict) -> Optional[dict]:
        os.makedirs(self.workdir, exist_ok=True)
        bundle = capture_bundle(
            self.workdir,
            self.serving_address(),
            incident,
            metrics_paths=self.metrics_paths,
        )
        manifest = finalize_bundle(bundle, self.workdir)
        v = manifest.get("verdict") or {}
        kind = str(v.get("kind", "unknown"))
        policy = POLICY_BY_KIND.get(kind)
        if policy is None:
            return None  # unknown verdict: evidence captured, no recommendation
        target = str(v.get("replica") or incident.get("replica_id") or "cluster")
        # Flap guard: a (policy, target) pair recommends once per debounce
        # window — suppressed repeats journal NOTHING (the bundle already
        # recorded the repeat trigger in its manifest).
        now = self._clock()
        key = (policy, target)
        last = self._last_action.get(key)
        if last is not None and now - last < self.debounce_s:
            return None
        self._last_action[key] = now
        acted = False
        if self.act and policy == "drain" and target and target != "cluster":
            acted = self._do_drain(target)
        entry = {
            "ts": time.time(),
            "incident_id": incident.get("id"),
            "reason": incident.get("reason"),
            "kind": kind,
            "target": target,
            "policy": policy,
            "acted": acted,
            "bundle": os.path.basename(bundle),
            "verdict": v,
        }
        with open(self.journal_path, "a", encoding="utf-8") as f:
            f.write(json.dumps(entry) + "\n")
        return entry

    def _do_drain(self, group: str) -> bool:
        """Execute the one actionable policy.  Never raises — a failed
        drain is journaled as acted=false and the next confirming trigger
        (past the debounce) retries."""
        try:
            if self._drain_cb is not None:
                self._drain_cb(group)
                return True
            url = (
                _http_base(self.serving_address())
                + f"/replica/{group}:/drain?deadline_ms=30000"
            )
            req = urllib.request.Request(url, data=b"", method="POST")
            with urllib.request.urlopen(req, timeout=5) as resp:
                return 200 <= resp.status < 300
        except Exception:  # noqa: BLE001
            return False


def _env_float(name: str, default: float) -> float:
    try:
        v = float(os.environ.get(name, ""))
        return v if v > 0 else default
    except ValueError:
        return default


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="Watch a lighthouse's incident feed: capture bundles, "
        "journal flap-guarded remediation recommendations."
    )
    p.add_argument(
        "--lighthouse",
        required=True,
        help="comma-separated lighthouse HTTP addresses (leader first)",
    )
    p.add_argument("--workdir", default=".", help="bundle + journal directory")
    p.add_argument(
        "--metrics",
        default="",
        help="comma-separated span JSONL paths to tail into bundles",
    )
    p.add_argument(
        "--act",
        action="store_true",
        help="execute 'drain' recommendations (everything else stays dry-run)",
    )
    args = p.parse_args(argv)
    w = IncidentWatcher(
        [a.strip() for a in args.lighthouse.split(",") if a.strip()],
        args.workdir,
        act=args.act,
        metrics_paths=[m for m in args.metrics.split(",") if m],
    )
    w.run()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
