"""Unified worker-side Prometheus exposition: ONE ``/metrics`` per worker.

The lighthouse's native ``GET /metrics`` covers the control plane; this is
the worker's own endpoint, covering everything a single replica group can
report about itself: step pace, device<->host transfer totals, the ring
data plane's lane/hop counters (monotonic across reconfigures — sourced
from ``TCPCollective.lane_totals()``, which banks each generation's
counters at abort so scrapes never see a counter go backwards), and the
per-neighbor link-health estimates the slow-link sentinel scores.

Design: the endpoint holds no per-step state of its own — a ``provider``
callback (the Manager's ``_worker_metrics_snapshot``) is invoked at SCRAPE
time and returns the series list, so an unscraped endpoint costs the train
loop nothing.  Subsystems with their own exposition (the semisync plane's
``tpuft_semisync_*``) register a render callable via :meth:`add_section`
instead of opening a second port — the fold that retires the
semisync-only exporter.

Ports: ``TPUFT_WORKER_METRICS_PORT`` (0 = ephemeral).  The pre-unification
``TPUFT_SEMISYNC_METRICS_PORT`` is honored as a DEPRECATED alias (one
warning per process) so existing deployments keep scraping.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, List, Optional, Sequence, Tuple

__all__ = [
    "WorkerMetrics",
    "bucketize",
    "render_histogram",
    "render_histogram_counts",
    "HOP_LATENCY_BOUNDS",
    "HOP_BYTES_BOUNDS",
    "TPUFT_WORKER_METRICS_PORT_ENV",
    "TPUFT_WORKER_METRICS_BIND_ENV",
]

TPUFT_WORKER_METRICS_PORT_ENV = "TPUFT_WORKER_METRICS_PORT"
TPUFT_WORKER_METRICS_BIND_ENV = "TPUFT_WORKER_METRICS_BIND"
# Deprecated aliases (the semisync-only exporter this endpoint absorbed).
_LEGACY_PORT_ENV = "TPUFT_SEMISYNC_METRICS_PORT"
_LEGACY_BIND_ENV = "TPUFT_SEMISYNC_METRICS_BIND"

# One series: (name, kind, help, labels, value).  ``labels`` is a list of
# (key, value) pairs; the replica label is added by the renderer.
Series = Tuple[str, str, str, Sequence[Tuple[str, str]], float]

_alias_warned = False


def _prom_escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


# Shared bucket bounds for the worker-side hop histograms (docs/wire.md
# "Worker /metrics"): latency covers a loopback hop (~100 µs) to a
# shaped-WAN hop (~10 s); bytes cover a control frame to a whole-bucket
# stripe.  Built at SCRAPE time from the ring engines' retained hop
# timeline (TCPCollective.hop_records) — no new recording cost on the
# data path.
HOP_LATENCY_BOUNDS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)
HOP_BYTES_BOUNDS = (
    1024.0, 4096.0, 16384.0, 65536.0, 262144.0, 1048576.0, 4194304.0,
    16777216.0, 67108864.0, 268435456.0,
)


def bucketize(
    bounds: Sequence[float], values: Sequence[float],
    counts: Optional[List[int]] = None,
) -> Tuple[List[int], float]:
    """Folds raw observations into per-bucket (non-cumulative) counts over
    ``bounds`` (+Inf slot last); pass an existing ``counts`` list to
    ACCUMULATE — the monotonic-histogram building block.  Returns
    (counts, sum-of-values-added)."""
    if counts is None:
        counts = [0] * (len(bounds) + 1)
    total = 0.0
    for v in values:
        total += float(v)
        for i, b in enumerate(bounds):
            if v <= b:
                counts[i] += 1
                break
        else:
            counts[len(bounds)] += 1
    return counts, total


def render_histogram_counts(
    name: str,
    help_: str,
    bounds: Sequence[float],
    series: Sequence[Tuple[Sequence[Tuple[str, str]], Sequence[int], float]],
) -> str:
    """Prometheus text-format histogram family from per-bucket counts
    (``bucketize`` output): HELP/TYPE once, then cumulative
    ``_bucket{...,le="..."}`` / ``_sum`` / ``_count`` per (labels, counts,
    sum) triple.  The worker endpoint's counterpart of the native
    ``ExposeHistogram`` (flight.h).  Callers exposing these as TYPE
    histogram must feed MONOTONIC counts (accumulate across scrapes) —
    Prometheus reads any decrease as a counter reset."""
    def le_value(b: float) -> str:
        # The label must ROUND-TRIP to the exact bound bucketize compared
        # against: %g truncates to 6 significant digits, which renders
        # 1048576 as "1.04858e+06" — a boundary that does not exist, so
        # quantile interpolation and le-matching rules silently break.
        return str(int(b)) if float(b).is_integer() else repr(float(b))

    lines: List[str] = [f"# HELP {name} {help_}", f"# TYPE {name} histogram"]
    for labels, counts, total in series:
        pairs = [f'{k}="{_prom_escape(str(v))}"' for k, v in labels]
        prefix = ",".join(pairs)
        cum = 0
        for i, b in enumerate(bounds):
            cum += counts[i]
            le = f'le="{le_value(b)}"'
            label = "{" + (prefix + "," if prefix else "") + le + "}"
            lines.append(f"{name}_bucket{label} {cum}")
        cum += counts[len(bounds)]
        label = "{" + (prefix + "," if prefix else "") + 'le="+Inf"' + "}"
        lines.append(f"{name}_bucket{label} {cum}")
        suffix = "{" + prefix + "}" if prefix else ""
        lines.append(f"{name}_sum{suffix} {round(total, 6)}")
        lines.append(f"{name}_count{suffix} {cum}")
    return "\n".join(lines) + "\n"


def render_histogram(
    name: str,
    help_: str,
    bounds: Sequence[float],
    series: Sequence[Tuple[Sequence[Tuple[str, str]], Sequence[float]]],
) -> str:
    """One-shot convenience over :func:`bucketize` +
    :func:`render_histogram_counts` for raw observations.  Only suitable
    for single renders of a complete value set — repeated scrapes over a
    SLIDING window must accumulate via ``bucketize`` instead, or the
    exposed counters go backwards."""
    folded = []
    for labels, values in series:
        counts, total = bucketize(bounds, values)
        folded.append((labels, counts, total))
    return render_histogram_counts(name, help_, bounds, folded)


class WorkerMetrics:
    """Pull-based worker ``/metrics`` endpoint.

    ``provider`` is called per scrape and returns the series list;
    exceptions are swallowed (metrics must never fail training — same
    contract as the semisync exporter this replaces).
    """

    def __init__(
        self,
        replica_id: str = "",
        provider: Optional[Callable[[], List[Series]]] = None,
    ) -> None:
        self.replica_id = replica_id
        self._provider = provider
        self._lock = threading.Lock()
        self._sections: List[Callable[[], str]] = []
        self._server = None

    def add_section(self, render: Callable[[], str]) -> None:
        """Registers a subsystem's own text-format exposition (e.g. the
        semisync plane's ``tpuft_semisync_*``) to be appended per scrape."""
        with self._lock:
            self._sections.append(render)

    @property
    def serving(self) -> bool:
        return self._server is not None

    def render_prometheus(self) -> str:
        lines: List[str] = []
        series: List[Series] = []
        if self._provider is not None:
            try:
                series = list(self._provider())
            except Exception:  # noqa: BLE001 — metrics must not fail training
                series = []
        seen_help = set()
        for name, kind, help_, labels, value in series:
            if name not in seen_help:
                seen_help.add(name)
                lines.append(f"# HELP {name} {help_}")
                lines.append(f"# TYPE {name} {kind}")
            pairs = []
            if self.replica_id:
                pairs.append(f'replica="{_prom_escape(self.replica_id)}"')
            for k, v in labels:
                pairs.append(f'{k}="{_prom_escape(str(v))}"')
            label = "{" + ",".join(pairs) + "}" if pairs else ""
            lines.append(f"{name}{label} {value}")
        out = "\n".join(lines) + ("\n" if lines else "")
        with self._lock:
            sections = list(self._sections)
        for render in sections:
            try:
                out += render()
            except Exception:  # noqa: BLE001
                pass
        return out

    # -- HTTP exposition ----------------------------------------------------

    def serve(
        self, port: Optional[int] = None, bind: Optional[str] = None
    ) -> Optional[int]:
        """Starts the daemon ``GET /metrics`` server.  ``port=None`` reads
        ``TPUFT_WORKER_METRICS_PORT``, falling back to the deprecated
        ``TPUFT_SEMISYNC_METRICS_PORT`` alias (unset/empty = disabled,
        0 = ephemeral) — when the alias supplies the port, its companion
        ``TPUFT_SEMISYNC_METRICS_BIND`` supplies the bind too, so an
        existing non-loopback deployment keeps scraping.  ``bind``
        defaults to loopback (``::1``) — the endpoint is unauthenticated,
        so wider binds are an explicit operator choice.  Returns the
        bound port, or None when disabled.  Never raises."""
        global _alias_warned
        legacy = False
        if port is None:
            raw = os.environ.get(TPUFT_WORKER_METRICS_PORT_ENV, "")
            if not raw.strip():
                raw = os.environ.get(_LEGACY_PORT_ENV, "")
                if raw.strip():
                    legacy = True
                    if not _alias_warned:
                        _alias_warned = True
                        logging.getLogger("torchft_tpu.obs.prom").warning(
                            "%s is deprecated; the worker /metrics endpoint "
                            "is unified — set %s instead (serving the "
                            "unified exposition on the legacy port for now)",
                            _LEGACY_PORT_ENV,
                            TPUFT_WORKER_METRICS_PORT_ENV,
                        )
            if not raw.strip():
                return None
            try:
                port = int(raw)
            except ValueError:
                return None
        if bind is None:
            bind = os.environ.get(TPUFT_WORKER_METRICS_BIND_ENV, "").strip()
            if not bind and legacy:
                bind = os.environ.get(_LEGACY_BIND_ENV, "").strip()
            bind = bind or "::1"
        from torchft_tpu.http import serve_text_exposition

        server = serve_text_exposition(
            self.render_prometheus, port, bind,
            thread_name="tpuft_worker_metrics",
        )
        if server is None:
            return None
        self._server = server
        return server.server_address[1]

    def close(self) -> None:
        server, self._server = self._server, None
        if server is not None:
            try:
                server.shutdown()
                server.server_close()
            except Exception:  # noqa: BLE001
                pass
