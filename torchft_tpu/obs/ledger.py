"""Goodput ledger: cause-attributed accounting of every committed step.

tpu-ft's premise is per-step fault tolerance, so every second of lost wall
time has a *specific* cause — quorum wait, heal, wire stall, shaping,
drain.  Before this module those causes lived in four disconnected
artifacts (worker span JSONL, control-plane flight dumps, hop timelines,
lighthouse alerts) that only ``trace_export`` could join after the fact.
The ledger is the live join: each Manager classifies every committed
step's wall time into the pinned cause taxonomy below, rides the per-step
vector in ``step_summary`` records, and pushes cumulative per-cause
counters onto its lighthouse heartbeats (fields 14-16) so the cluster-wide
rollup (``GET /goodput.json``, ``tpuft_goodput_ratio``,
``tpuft_lost_seconds_total{cause=...}``) is always on and off the training
critical path — the Gemini-style accounting discipline (SOSP '23).

The taxonomy (:data:`CAUSES`) is a WIRE CONTRACT: the heartbeat's
``ledger_lost_seconds`` vector is ordered by :data:`LOST_CAUSES`, the
native lighthouse labels its counters from the same list
(``kLedgerCauses`` in native/src/lighthouse.cc), and docs/wire.md tables
it — tests/test_ledger.py greps all three against this module, the same
pinning discipline as ``metrics.EVENTS`` and ``FLIGHT_EVENTS``.

Classification rules (per committed step, wall = commit-to-commit
interval of this replica):

* ``quorum_server`` / ``quorum_transport`` — the ``quorum`` span, split by
  the server-side handling window when one is known (the PR 7 flight
  join: live, the Manager reads its own ManagerServer's flight ring for
  the round's server span; post-hoc, obs/report.py joins the lighthouse
  dump by trace id).  With no split available the whole wait is charged
  ``quorum_server`` — formation dominates in practice, and a lump charge
  beats a fabricated split.
* ``wire`` / ``stall`` / ``combine`` / ``shaping`` — the step's
  allreduce-blocking span time (``allreduce_merge`` + ``allreduce_d2h`` +
  ``allreduce_h2d``: the only parts of the data plane that block the
  train thread) distributed proportionally to this step's hop-stall
  deltas from the ring engines (PR 14's ``link_attribution`` classes:
  send-blocked net of shaping / recv-wait / decode+combine / pacer
  sleep).  The hop counters are CUMULATIVE per configure() and reset on
  every reconfiguration, so the delta window is epoch-banked exactly like
  obs/report.py's rollups (:func:`epoch_bank` is THE shared reset rule).
  A step with blocking time but no hop signal (non-ring collective,
  counters reset mid-window) charges it to ``other_ft``.
* ``heal`` — the ``heal`` + ``ec_reconstruct`` spans (reconstruction is
  healing by another path; same class so donor and donor-free clusters
  read comparably).
* ``drain`` — non-compute residual of a step run under a drain notice
  (the planned-departure cost visible from inside the step; the
  post-exit handoff gap is accounted cluster-side from stream coverage).
* ``other_ft`` — every remaining non-overlapped phase (commit vote,
  configure, ...).
* ``compute`` — wall minus everything above, floored at zero; when the
  charges exceed the wall (clock skew between span threads) they are
  scaled down proportionally, so the cause fractions always sum to ~1.0
  of the wall (pinned by tests/test_ledger.py).

Failed-commit steps are EXCLUDED from the ledger: their eventual commit
interval spans the retries, so the retried step's charges land in that
one committed interval (the same rule the straggler sentinel's step-time
telemetry uses).  Overlapped background phases (snapshot, ec_encode,
outer_sync) are tracked informationally (``overlap_s``) and never
charged — subtracting concurrent work from the wall would fabricate FT
cost the async pipeline specifically does not impose.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

from torchft_tpu.obs.spans import OVERLAPPED_PHASES

__all__ = [
    "CAUSES",
    "LOST_CAUSES",
    "epoch_bank",
    "StepLedger",
    "ledger_rollup",
    "crosscheck_goodput",
]

# The pinned cause taxonomy.  Order matters: LOST_CAUSES (everything but
# compute) is the wire order of the heartbeat's ledger_lost_seconds vector
# (proto field 16) and of the native lighthouse's kLedgerCauses label
# array — append-only; never reorder.
CAUSES = (
    "compute",
    "wire",
    "stall",
    "combine",
    "shaping",
    "quorum_server",
    "quorum_transport",
    "heal",
    "drain",
    "other_ft",
    "resize",
)
LOST_CAUSES = CAUSES[1:]

# Span phases that block the train thread on the allreduce data plane —
# the wall time the hop-stall deltas distribute over.
_AR_BLOCK_PHASES = ("allreduce_merge", "allreduce_d2h", "allreduce_h2d")
# Phases with their own cause class (everything else non-overlapped falls
# into other_ft / drain).  "configure" is the membership-transition
# reconfigure (lane rendezvous + engine rebuild) — the ``resize`` cause,
# so seconds lost to elastic membership churn are named, never smeared
# into other_ft.
_CLASSIFIED_PHASES = ("quorum", "heal", "ec_reconstruct", "configure") + _AR_BLOCK_PHASES


def epoch_bank(slot: List[float], value: float) -> None:
    """One observation of a CUMULATIVE-per-configure counter into a
    ``[closed-epoch sum, current-epoch high-water mark]`` slot: a snapshot
    below the previous one means the counter reset (a reconfigure), so the
    old epoch's high-water mark is banked and a new epoch opens.  THE
    reset-detection rule, shared by every rollup over lane/hop counters —
    the live ledger here and obs/report.py's ``data_plane`` /
    ``link_attribution`` post-hoc rollups — so they cannot diverge."""
    if value < slot[1]:  # counter reset: a reconfigure happened
        slot[0] += slot[1]
    slot[1] = value


_HOP_KEYS = ("send_block_s", "recv_wait_s", "combine_s", "shape_s")


def _hop_totals(lanes: Optional[dict]) -> Optional[Dict[str, float]]:
    """Sums the per-tier hop aggregates of one lane_stats snapshot into one
    cumulative {send_block_s, recv_wait_s, combine_s, shape_s} reading, or
    None when the snapshot carries no hop telemetry."""
    if not isinstance(lanes, dict):
        return None
    hops = lanes.get("hops")
    if not isinstance(hops, dict) or not hops:
        return None
    out = {k: 0.0 for k in _HOP_KEYS}
    for tier in hops.values():
        if not isinstance(tier, dict):
            continue
        for k in _HOP_KEYS:
            out[k] += float(tier.get(k, 0) or 0)
    return out


class StepLedger:
    """Per-replica live goodput ledger.

    One instance per Manager.  ``observe_step`` once per commit vote with
    the step's wall interval, the span-phase accumulation
    (``SpanTracker.phases_ms()``, read before ``step_summary`` flushes
    it), and the lane_stats snapshot; returns the step's cause vector (or
    None for failed commits) and folds it into the cumulative per-cause
    counters the heartbeat carries.  Thread-safe: observe runs on the
    train thread, snapshots may be read from a scrape thread.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._compute_s = 0.0
        self._lost_s: Dict[str, float] = {c: 0.0 for c in LOST_CAUSES}
        self._overlap_s = 0.0
        self._steps = 0
        self._steps_failed = 0
        # cause-source hop counters, epoch-banked across reconfigures:
        # key -> [closed-epoch sum, current-epoch high-water mark].
        self._hop_acc: Dict[str, List[float]] = {
            k: [0.0, 0.0] for k in _HOP_KEYS
        }
        # False until the first snapshot opened the delta window (the
        # deltas themselves come from _hop_acc's banked sums, not a
        # stored previous snapshot).
        self._hop_seeded = False

    # -- observation --------------------------------------------------------

    def _hop_delta(self, lanes: Optional[dict]) -> Optional[Dict[str, float]]:
        """This step's hop-stall deltas from the cumulative snapshot, via
        the shared epoch-banking rule; None when no snapshot or this is the
        first observation (no delta window yet)."""
        cur = _hop_totals(lanes)
        if cur is None:
            return None
        prev_banked = {
            k: self._hop_acc[k][0] + self._hop_acc[k][1] for k in _HOP_KEYS
        }
        for k in _HOP_KEYS:
            epoch_bank(self._hop_acc[k], cur[k])
        if not self._hop_seeded:
            self._hop_seeded = True
            return None
        now_banked = {
            k: self._hop_acc[k][0] + self._hop_acc[k][1] for k in _HOP_KEYS
        }
        return {k: max(0.0, now_banked[k] - prev_banked[k]) for k in _HOP_KEYS}

    def observe_step(
        self,
        step: int,
        wall_s: float,
        phases_ms: Dict[str, float],
        lanes: Optional[dict] = None,
        committed: bool = True,
        draining: bool = False,
        quorum_server_ms: Optional[float] = None,
    ) -> Optional[Dict[str, float]]:
        """Classifies one step's wall interval; returns the cause vector
        (seconds, keys = :data:`CAUSES`) for committed steps, None for
        failed commits (excluded — see module docstring)."""
        with self._lock:
            overlap = (
                sum(float(phases_ms.get(k, 0.0)) for k in OVERLAPPED_PHASES)
                / 1e3
            )
            self._overlap_s += overlap
            # The hop window must advance even on failed commits, or the
            # retried step's stalls would be charged twice into the
            # eventual committed interval's delta.
            hop_d = self._hop_delta(lanes)
            if not committed:
                self._steps_failed += 1
                return None
            wall = max(0.0, float(wall_s))

            q = float(phases_ms.get("quorum", 0.0)) / 1e3
            if quorum_server_ms is not None:
                q_server = min(q, max(0.0, float(quorum_server_ms)) / 1e3)
                q_transport = q - q_server
            else:
                q_server, q_transport = q, 0.0
            heal = (
                float(phases_ms.get("heal", 0.0))
                + float(phases_ms.get("ec_reconstruct", 0.0))
            ) / 1e3
            ar_block = (
                sum(float(phases_ms.get(k, 0.0)) for k in _AR_BLOCK_PHASES)
                / 1e3
            )
            other = (
                sum(
                    float(v)
                    for k, v in phases_ms.items()
                    if k not in _CLASSIFIED_PHASES and k not in OVERLAPPED_PHASES
                )
                / 1e3
            )

            causes = {c: 0.0 for c in CAUSES}
            causes["quorum_server"] = q_server
            causes["quorum_transport"] = q_transport
            causes["heal"] = heal
            causes["resize"] = float(phases_ms.get("configure", 0.0)) / 1e3
            # Distribute the train-thread's allreduce-blocking time over the
            # wire classes proportionally to this step's hop-stall deltas.
            hop_sum = sum(hop_d.values()) if hop_d else 0.0
            if ar_block > 0.0 and hop_sum > 0.0:
                shaping = hop_d["shape_s"]
                wire = max(0.0, hop_d["send_block_s"] - shaping)
                stall = hop_d["recv_wait_s"]
                combine = hop_d["combine_s"]
                denom = wire + stall + combine + shaping
                if denom > 0.0:
                    causes["wire"] = ar_block * wire / denom
                    causes["stall"] = ar_block * stall / denom
                    causes["combine"] = ar_block * combine / denom
                    causes["shaping"] = ar_block * shaping / denom
                else:
                    other += ar_block
            else:
                other += ar_block
            if draining:
                causes["drain"] = other
            else:
                causes["other_ft"] = other

            lost = sum(causes.values())
            if lost > wall > 0.0:
                # Span threads and the commit clock can disagree by clock
                # granularity; scale the charges so fractions sum to 1.0.
                scale = wall / lost
                for c in LOST_CAUSES:
                    causes[c] *= scale
                lost = wall
            causes["compute"] = max(0.0, wall - lost)

            self._compute_s += causes["compute"]
            for c in LOST_CAUSES:
                self._lost_s[c] += causes[c]
            self._steps += 1
            return causes

    # -- reads --------------------------------------------------------------

    def goodput_ratio(self) -> Optional[float]:
        """Cumulative productive fraction: compute over accounted wall;
        None before the first observation."""
        with self._lock:
            total = self._compute_s + sum(self._lost_s.values())
            if total <= 0.0:
                return None
            return self._compute_s / total

    def snapshot(self) -> dict:
        """Cumulative totals: {goodput_ratio, compute_s, lost_s{cause},
        overlap_s, steps, steps_failed}."""
        with self._lock:
            total = self._compute_s + sum(self._lost_s.values())
            return {
                "goodput_ratio": (
                    round(self._compute_s / total, 4) if total > 0 else None
                ),
                "compute_s": round(self._compute_s, 4),
                "lost_s": {c: round(v, 4) for c, v in self._lost_s.items()},
                "overlap_s": round(self._overlap_s, 4),
                "steps": self._steps,
                "steps_failed": self._steps_failed,
            }

    def heartbeat_vector(self) -> Tuple[float, float, List[float]]:
        """(goodput_ratio, compute_seconds, lost_seconds in LOST_CAUSES
        order) — exactly what ``ManagerServer.set_ledger`` pushes onto
        heartbeat fields 14-16.  Ratio is 0.0 before the first
        observation (proto3 zero = not reported)."""
        with self._lock:
            total = self._compute_s + sum(self._lost_s.values())
            ratio = self._compute_s / total if total > 0 else 0.0
            return (
                ratio,
                self._compute_s,
                [self._lost_s[c] for c in LOST_CAUSES],
            )


# ---------------------------------------------------------------------------
# Stream rollups (post-hoc, over the metrics JSONL)
# ---------------------------------------------------------------------------


def ledger_rollup(events: Sequence[dict]) -> dict:
    """Sums the per-step ``ledger`` cause vectors riding in committed
    ``step_summary`` records: per-replica and cluster totals plus the
    cluster productive fraction over ACCOUNTED step time.  This is the
    stream-side mirror of the lighthouse's live rollup — the bench's
    goodput cross-check reads it, and an incident verdict charges lost
    seconds from it."""
    per_replica: Dict[str, Dict[str, float]] = {}
    n_steps = 0
    for ev in events:
        if ev.get("event") != "step_summary" or not ev.get("committed"):
            continue
        led = ev.get("ledger")
        if not isinstance(led, dict):
            continue
        causes = led.get("causes")
        if not isinstance(causes, dict):
            continue
        rid = str(ev.get("replica_id", ""))
        acc = per_replica.setdefault(rid, {c: 0.0 for c in CAUSES})
        for c in CAUSES:
            acc[c] += float(causes.get(c, 0.0) or 0.0)
        n_steps += 1
    totals = {c: 0.0 for c in CAUSES}
    for acc in per_replica.values():
        for c in CAUSES:
            totals[c] += acc[c]
    accounted = sum(totals.values())
    return {
        "per_replica": {
            rid: {c: round(v, 4) for c, v in acc.items()}
            for rid, acc in sorted(per_replica.items())
        },
        "totals": {c: round(v, 4) for c, v in totals.items()},
        "productive_fraction": (
            round(totals["compute"] / accounted, 4) if accounted > 0 else None
        ),
        "steps": n_steps,
    }


def crosscheck_goodput(events: Sequence[dict]) -> dict:
    """Cross-checks the commit-count dead-window headline against the
    ledger stream's own accounting of the same run.

    Two independent implementations over one JSONL stream must agree:
    the bench headline (``obs.report.deadwindow`` — commit timelines
    alone) and the ledger/report classification (stream-coverage gaps +
    heal credit + drain).  Both are expressed as lost seconds over the
    dead-window span; ``disagreement`` is the absolute difference of the
    two goodput fractions and the bench fails a trial above 0.05 — a
    larger gap means one of the accountings is lying about where the wall
    time went.  The per-step FT causes (``ledger`` rollup) are reported
    alongside as additive detail: the dead-window headline deliberately
    ignores steady-state FT overhead, so they are NOT in the
    disagreement.

    Returns {deadwindow_fraction, ledger_fraction, disagreement, ok,
    ledger} — fractions None (ok=True) when the run has no fault-charged
    headline to check."""
    from torchft_tpu.obs import report

    commits = report.commit_timelines(events)
    faults = report.fault_times(events)
    dw = report.deadwindow(commits, faults)
    out = {
        "deadwindow_fraction": dw["fraction"],
        "ledger_fraction": None,
        "disagreement": None,
        "ok": True,
        "ledger": ledger_rollup(events),
    }
    if dw["fraction"] is None or not dw["span_s"]:
        return out
    attr = report.attribute(events)
    t = attr["totals"]
    gap_lost = t["idle_s"] + t["drain_s"] + t["heal_s"]
    lf = max(0.0, 1.0 - gap_lost / dw["span_s"])
    out["ledger_fraction"] = round(lf, 4)
    out["disagreement"] = round(abs(lf - dw["fraction"]), 4)
    out["ok"] = out["disagreement"] <= 0.05
    return out
