"""Observability: step-scoped tracing, goodput attribution, trace export.

Three halves plus the live exposition:

- :mod:`torchft_tpu.obs.spans` — the *producer* side.  ``SpanTracker``
  wraps each Manager step phase (quorum, configure, heal, allreduce-merge,
  commit vote) in begin/end spans keyed by ``(slice_gen, step,
  replica_id)`` with monotonic-clock durations, emitted through
  :class:`~torchft_tpu.metrics.MetricsLogger` as versioned ``span``
  records, plus one ``step_summary`` record per step carrying the full
  phase breakdown.  ``StepTimeStats`` keeps the rolling per-step busy-time
  EWMA + p50/p99 the Manager pushes onto heartbeats for the lighthouse's
  straggler sentinel.

- :mod:`torchft_tpu.obs.report` — the *consumer* side.  Merges every
  replica's JSONL stream into a per-step cluster timeline, classifies wall
  time into productive / quorum-wait / heal / drain / idle, names the
  critical-path phase per step, and computes the dead-window goodput
  fraction.  ``bench.py`` calls the same functions, so the benchmark
  headline and the report tool cannot drift apart.  CLI::

      python -m torchft_tpu.obs.report metrics.jsonl [...]

- :mod:`torchft_tpu.obs.trace` — the *timeline* side.  Merges the same
  streams into one Chrome/Perfetto ``trace.json`` (one track per replica
  incarnation, phase slices, fault/drain/alert instants, commit-barrier
  clock alignment).  CLI::

      python tools/trace_export.py metrics.jsonl [...]

- :mod:`torchft_tpu.obs.flight` — the *control-plane* side.  Registry and
  consumers for the native servers' flight recorders (bounded RPC-span +
  state-transition rings, ``GET /debug/flight.json``, ``TPUFT_FLIGHT_DIR``
  shutdown dumps): causal trace ids, quorum-transition reconstruction,
  and conversion into the Perfetto control-plane track.

- :mod:`torchft_tpu.obs.ledger` — the *accounting* side.  Every committed
  step's wall classified into the pinned cause taxonomy (``CAUSES``),
  per-step vectors in ``step_summary.ledger``, cumulative counters on
  heartbeat fields 14-16, cluster rollup on the lighthouse's
  ``GET /goodput.json`` — plus the stream rollup and the bench's
  headline-vs-ledger cross-check.

- :mod:`torchft_tpu.obs.incident` — the *capture* side.  Polls the
  lighthouse's incident-trigger feed (``GET /incident.json``) and bundles
  flight rings + alerts + ledger + span tails + dumps into
  ``incident_<step>/`` with a machine-readable verdict.  CLI::

      python tools/incident.py capture <workdir> --lighthouse http://...

The live leg — cluster metrics, latency histograms, the sentinels, the
goodput ledger and the incident feed — is served by the native lighthouse
(``GET /metrics``, ``GET /alerts.json``, ``GET /goodput.json``,
``GET /incident.json``, ``GET /debug/flight.json``; the cross-plane map
and knob index live in docs/observability.md).
"""

from torchft_tpu.obs.flight import FLIGHT_EVENTS, mint_trace_id
from torchft_tpu.obs.ledger import CAUSES, LOST_CAUSES, StepLedger
from torchft_tpu.obs.spans import SpanTracker, StepTimeStats

__all__ = [
    "CAUSES",
    "FLIGHT_EVENTS",
    "LOST_CAUSES",
    "SpanTracker",
    "StepLedger",
    "StepTimeStats",
    "mint_trace_id",
]
