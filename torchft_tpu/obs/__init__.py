"""Observability: step-scoped tracing and goodput attribution.

Two halves:

- :mod:`torchft_tpu.obs.spans` — the *producer* side.  ``SpanTracker``
  wraps each Manager step phase (quorum, configure, heal, allreduce-merge,
  commit vote) in begin/end spans keyed by ``(slice_gen, step,
  replica_id)`` with monotonic-clock durations, emitted through
  :class:`~torchft_tpu.metrics.MetricsLogger` as versioned ``span``
  records, plus one ``step_summary`` record per step carrying the full
  phase breakdown.

- :mod:`torchft_tpu.obs.report` — the *consumer* side.  Merges every
  replica's JSONL stream into a per-step cluster timeline, classifies wall
  time into productive / quorum-wait / heal / drain / idle, names the
  critical-path phase per step, and computes the dead-window goodput
  fraction.  ``bench.py`` calls the same functions, so the benchmark
  headline and the report tool cannot drift apart.  CLI::

      python -m torchft_tpu.obs.report metrics.jsonl [...]

The third leg — live cluster metrics — is served by the native lighthouse
(``GET /metrics``, Prometheus text exposition; see docs/wire.md).
"""

from torchft_tpu.obs.spans import SpanTracker

__all__ = ["SpanTracker"]
