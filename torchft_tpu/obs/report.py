"""Goodput attribution: merge per-replica JSONL streams into a per-step
cluster timeline and say where the wall-clock went.

CLI::

    python -m torchft_tpu.obs.report metrics.jsonl [more.jsonl ...] [--json]

Input is the event stream documented in torchft_tpu/metrics.py (all
replicas may share one file — O_APPEND keeps lines atomic — or each may
have its own).  Output:

- a per-step phase attribution table: for every committed step, the
  slowest replica's wall time split into productive compute vs the FT
  phases (quorum wait, configure, heal, allreduce d2h, allreduce merge,
  commit vote) and the critical-path phase — the bucket that dominated the
  slowest replica;
- cluster totals: wall time classified productive / quorum-wait / heal /
  drain / idle per group and summed;
- the dead-window goodput fraction, computed by :func:`deadwindow` — the
  SAME function ``bench.py`` calls for its headline, so the benchmark
  number and this report cannot drift apart (pinned by
  tests/test_bench_contract.py).

Timing discipline: durations inside one replica's stream use ``t_mono``
(NTP-step-immune); cross-replica alignment (t0, spans, gaps between
incarnations — which never share a monotonic origin) uses ``ts``.

Faults are part of the stream: bench.py writes a ``fault`` record (kind
kill|drain, group=victim) at injection time, so this tool charges the same
fault timeline the benchmark charged.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from torchft_tpu.obs.spans import OVERLAPPED_PHASES

__all__ = [
    "read_events",
    "commit_timelines",
    "fault_times",
    "election_windows",
    "deadwindow",
    "attribute",
    "render",
]


def read_events(
    paths: Sequence[str], stats: Optional[dict] = None
) -> List[dict]:
    """Reads + merges JSONL streams, sorted by wall-clock ``ts``.

    Garbage lines never raise: a writer killed mid-record leaves a
    truncated trailing line, a torn multi-process write can interleave two
    records, and stray text parses to a non-dict JSON value — all are
    skipped and COUNTED, with one warning per file, so a kill-run stream is
    always readable and the caller can see how much was lost.  Pass
    ``stats`` (a dict, filled in place) to receive ``skipped_lines``,
    ``skipped_by_file`` and ``unreadable_files`` — the last lists files
    that could not be opened OR failed mid-read (flaky storage); a
    partially read file keeps its already-parsed events and its skipped
    count.  The CLI surfaces these in its ``--json`` output.
    """
    events: List[dict] = []
    skipped_by_file: Dict[str, int] = {}
    unreadable: List[str] = []
    for path in paths:
        skipped = 0
        try:
            f = open(path, "rb")
        except OSError:
            unreadable.append(path)
            continue
        with f:
            try:
                for line in f:
                    if not line.strip():
                        continue
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        skipped += 1
                        continue
                    if not isinstance(ev, dict):
                        # json.loads accepts bare scalars; a corrupted line
                        # that happens to parse must not crash consumers
                        # doing ev.get(...).
                        skipped += 1
                        continue
                    events.append(ev)
            except OSError:
                # Mid-file I/O failure: keep what parsed, keep the skip
                # count, and flag the file so the caller knows the stream
                # is incomplete.
                unreadable.append(path)
        if skipped:
            skipped_by_file[path] = skipped
            print(
                f"warning: {path}: skipped {skipped} unparseable line(s) "
                "(truncated or torn writes)",
                file=sys.stderr,
            )
    if stats is not None:
        stats["skipped_lines"] = sum(skipped_by_file.values())
        stats["skipped_by_file"] = skipped_by_file
        stats["unreadable_files"] = unreadable
    events.sort(key=lambda ev: float(ev.get("ts", 0.0)))
    return events


def _group(replica_id: str) -> str:
    """Replica ids are "<group>:<uuid>" with a fresh uuid per incarnation;
    the group prefix is the stable identity."""
    return str(replica_id).split(":", 1)[0]


def commit_timelines(events: Sequence[dict]) -> Dict[str, List[float]]:
    """{group: sorted committed-commit ts list} across all incarnations."""
    commits: Dict[str, List[float]] = {}
    for ev in events:
        if ev.get("event") == "commit" and ev.get("committed"):
            commits.setdefault(_group(ev.get("replica_id", "")), []).append(
                float(ev["ts"])
            )
    for ts_list in commits.values():
        ts_list.sort()
    return commits


def fault_times(events: Sequence[dict]) -> List[Tuple[float, str]]:
    """[(ts, victim group)] from ``fault`` records (written by bench.py).

    ``straggler`` faults are excluded: an injected slowdown is not a death
    — the victim keeps committing (slowly), so charging its commit gap as
    a dead window would fabricate downtime.  The straggler scenario's own
    accounting (detection latency, post-injection rate) lives in bench.py.

    ``lighthouse`` faults are excluded too: a lighthouse kill is a CONTROL
    PLANE fault, not a worker death — no replica group's commit timeline
    belongs to it (charging it here would mark the trial unrecovered
    against a group that never existed).  Leader-election dead time is
    instead charged like quorum wait via :func:`election_windows`.
    """
    return [
        (float(ev["ts"]), str(ev.get("group", "")))
        for ev in events
        if ev.get("event") == "fault"
        and str(ev.get("kind")) not in ("straggler", "lighthouse")
    ]


# THE reset-detection rule for cumulative-per-configure counters, shared
# with the live goodput ledger (torchft_tpu/obs/ledger.py) so the post-hoc
# rollups here (data_plane, link_attribution) and the ledger's per-step
# hop deltas cannot diverge on what a reconfigure looks like.
from torchft_tpu.obs.ledger import epoch_bank as _epoch_bank
from torchft_tpu.obs.ledger import ledger_rollup as _ledger_rollup


def data_plane(events: Sequence[dict]) -> dict:
    """Cross-topology data-plane rollup from step_summary records.

    ``allreduce_payload_bytes`` sums the per-step payload accounting, which
    the Manager computes through the collective's ``wire_nbytes`` probe —
    the single telemetry source, so a flat-ring run and a ring2d run of the
    same workload read comparable totals (and the derived
    ``tpuft_allreduce_gb_per_s`` gauge stays comparable too).
    ``tier_wire_bytes`` attributes actual wire traffic per ring tier
    ("flat" = the flat ring's next-direction lanes; "row"/"col" = the 2D
    topology's nested tiers) from the lane_stats snapshot each step_summary
    embeds.  Those counters are CUMULATIVE per configure() — they RESET on
    every quorum reconfiguration — so the rollup accumulates per
    (replica, tier) epochs: a snapshot that drops below the previous one
    closes the old epoch (its high-water mark is banked) and opens a new
    one; the total is banked epochs plus the live epoch's high-water mark.
    A plain per-replica max would silently drop all traffic that predates
    a reconfiguration — precisely the fault runs this report analyzes."""
    payload: Dict[str, int] = {}
    # rid -> tier -> [closed-epoch sum, current-epoch high-water mark]
    tier_acc: Dict[str, Dict[str, List[int]]] = {}
    topologies: set = set()
    for ev in events:
        if ev.get("event") != "step_summary":
            continue
        rid = str(ev.get("replica_id", ""))
        nbytes = ev.get("allreduce_bytes")
        if nbytes:
            payload[rid] = payload.get(rid, 0) + int(nbytes)
        lanes = ev.get("allreduce_lanes")
        if isinstance(lanes, dict):
            topologies.add(str(lanes.get("topology", "ring")))
            tiers = {"flat": sum(lanes.get("sent") or [])}
            for name, tier in (lanes.get("tiers") or {}).items():
                tiers[name] = sum(tier.get("sent") or [])
            acc = tier_acc.setdefault(rid, {})
            for name, v in tiers.items():
                _epoch_bank(acc.setdefault(name, [0, 0]), int(v))
    tier_totals: Dict[str, int] = {}
    for tiers in tier_acc.values():
        for name, (closed, cur) in tiers.items():
            tier_totals[name] = tier_totals.get(name, 0) + closed + cur
    return {
        "allreduce_payload_bytes": sum(payload.values()),
        "per_replica_payload_bytes": dict(sorted(payload.items())),
        "tier_wire_bytes": dict(sorted(tier_totals.items())),
        "topologies": sorted(topologies),
    }


def link_attribution(events: Sequence[dict]) -> dict:
    """Data-plane wall attribution from the hop telemetry each
    step_summary's ``allreduce_lanes["hops"]`` snapshot embeds: splits the
    allreduce wall per replica into four classes —

    * ``wire_s``   — send-blocked time net of modeled shaping (real
      serialization/backpressure on the OUTBOUND edge: the localizing
      signal when a link degrades),
    * ``stall_s``  — recv-wait (blocked on the inbound edge: upstream
      serialization + propagation + peer pace, the equalized symptom),
    * ``combine_s`` — decode + elementwise combine (host CPU),
    * ``shaping_s`` — time slept in the LinkShaper's virtual-time pacer
      (bench-only modeled serialization; 0 on unshaped links).

    The hop counters are CUMULATIVE per configure() and reset on every
    quorum reconfiguration, so accumulation is epoch-banked exactly like
    :func:`data_plane` (a snapshot below its predecessor closes the old
    epoch).  ``fractions`` normalizes over the four classes' sum — the
    bench's degraded cell pins that the added wall of a shaped edge lands
    in wire+shaping/stall, not combine."""
    keys = ("send_block_s", "recv_wait_s", "combine_s", "shape_s", "hops")
    # rid -> key -> [closed-epoch sum, current-epoch high-water mark]
    acc: Dict[str, Dict[str, List[float]]] = {}
    for ev in events:
        if ev.get("event") != "step_summary":
            continue
        lanes = ev.get("allreduce_lanes")
        if not isinstance(lanes, dict):
            continue
        hops = lanes.get("hops")
        if not isinstance(hops, dict):
            continue
        rid = str(ev.get("replica_id", ""))
        cur = {k: 0.0 for k in keys}
        for tier in hops.values():
            for k in keys:
                cur[k] += float(tier.get(k, 0) or 0)
        slots = acc.setdefault(rid, {})
        for k, v in cur.items():
            _epoch_bank(slots.setdefault(k, [0.0, 0.0]), v)
    per_replica: Dict[str, dict] = {}
    totals = {"wire_s": 0.0, "stall_s": 0.0, "combine_s": 0.0, "shaping_s": 0.0}
    for rid, slots in acc.items():
        tot = {k: slots.get(k, [0.0, 0.0]) for k in keys}
        v = {k: tot[k][0] + tot[k][1] for k in keys}
        shaping = v["shape_s"]
        wire = max(0.0, v["send_block_s"] - shaping)
        row = {
            "wire_s": round(wire, 4),
            "stall_s": round(v["recv_wait_s"], 4),
            "combine_s": round(v["combine_s"], 4),
            "shaping_s": round(shaping, 4),
            "hops": int(v["hops"]),
        }
        denom = wire + v["recv_wait_s"] + v["combine_s"] + shaping
        row["fractions"] = {
            k: (round(row[k] / denom, 4) if denom > 0 else None)
            for k in ("wire_s", "stall_s", "combine_s", "shaping_s")
        }
        per_replica[rid] = row
        for k in totals:
            totals[k] += row[k]
    denom = sum(totals.values())
    return {
        "per_replica": dict(sorted(per_replica.items())),
        "totals": {k: round(v, 4) for k, v in totals.items()},
        "fractions": {
            k: (round(v / denom, 4) if denom > 0 else None)
            for k, v in totals.items()
        },
    }


def election_windows(events: Sequence[dict]) -> List[Tuple[float, float]]:
    """[(start_ts, end_ts)] of lighthouse leader elections in the stream:
    from a scripted lighthouse fault (``fault`` kind="lighthouse") to the
    next standby takeover (``lighthouse_failover``, emitted by
    torchft_tpu/ha/replica.py with the new leader epoch).  A fault with no
    subsequent takeover yields no window (the election never resolved —
    nothing to bound)."""
    starts = sorted(
        float(ev["ts"])
        for ev in events
        if ev.get("event") == "fault" and str(ev.get("kind")) == "lighthouse"
    )
    takeovers = sorted(
        float(ev["ts"]) for ev in events if ev.get("event") == "lighthouse_failover"
    )
    windows: List[Tuple[float, float]] = []
    for s in starts:
        ends = [t for t in takeovers if t >= s]
        if ends:
            windows.append((s, ends[0]))
    return windows


def _fault_records(events: Sequence[dict]) -> List[dict]:
    return [ev for ev in events if ev.get("event") == "fault"]


def deadwindow(
    commits: Dict[str, List[float]], kills: Sequence[Tuple[float, str]]
) -> dict:
    """Dead-window goodput accounting (the benchmark headline).

    Over the window [t0, t_end] — t0 = the first moment EVERY group has
    committed (startup JIT excluded), t_end = the last commit — each
    killed group's commit gaps that contain >= 1 kill are charged as dead
    time, minus one median step interval (the step it would have taken
    anyway), and goodput = 1 - dead/span.  Insensitive to host-load rate
    drift, handles single/double/during-heal kills identically
    (overlapping kills land in one longer gap).

    Returns dead_time_s/fraction None (victims_recovered False) when a
    killed group never commits after its last kill — that trial measured
    an unrecovered victim, not goodput.
    """
    if not commits:
        return {
            "t0": None, "t_end": None, "span_s": None, "dead_time_s": None,
            "fraction": None, "victims_recovered": False,
        }
    t0 = max(min(ts_list) for ts_list in commits.values())
    t_end = max(max(ts_list) for ts_list in commits.values())
    span = t_end - t0
    dead_total = 0.0
    victims_recovered = True
    for g in {grp for _, grp in kills}:
        g_kills = sorted(ts for ts, grp in kills if grp == g)
        cs = sorted(commits.get(g, []))
        if not cs or max(cs) < max(g_kills):
            victims_recovered = False  # never committed after its kill
            continue
        steps_iv = [b - a for a, b in zip(cs, cs[1:])]
        med = sorted(steps_iv)[len(steps_iv) // 2] if steps_iv else 0.0
        for a, b in zip(cs, cs[1:]):
            if any(a <= k < b for k in g_kills):
                dead_total += max(0.0, (b - a) - med)
    fraction = None
    if kills and span > 0 and victims_recovered:
        fraction = max(0.0, 1.0 - dead_total / span)
    return {
        "t0": t0,
        "t_end": t_end,
        "span_s": span,
        "dead_time_s": dead_total if kills else None,
        "fraction": fraction,
        "victims_recovered": victims_recovered if kills else True,
    }


# ---------------------------------------------------------------------------
# Per-step attribution
# ---------------------------------------------------------------------------

# Phases that run on background threads CONCURRENT with the train step
# (torchft_tpu/obs/spans.py OVERLAPPED_PHASES): the donor-side async
# snapshot flatten and the semisync engine's background fragment rounds
# (outer_sync).  They are reported (snapshot_overlap_s sums all of them)
# but never charged against productive wall time — subtracting an
# overlapped span from the step interval would fabricate FT cost that the
# async pipeline specifically does not impose.
#
# NOT in this tuple: ``allreduce_d2h`` / ``allreduce_h2d``, the
# GradientAverager's per-bucket device->host fetch and the result
# scatter-back.  Both block the train thread (the pipeline overlaps
# bucket k's WIRE time with bucket k+1's copy, but the copy wait itself is
# serial with compute), so they fall through the generic branch below into
# ``other_ft`` — FT overhead, never productive.  Moving either here would
# inflate productive time by exactly the transfer stall and break the
# dead-window math bench.py reproduces from these streams.
# Aliased from the one registry (obs/spans.py), not duplicated: a phase
# added to OVERLAPPED_PHASES but missed here would be charged against
# productive wall time — fabricated FT cost.
_OVERLAPPED = OVERLAPPED_PHASES

# Phase ms a legacy (pre-span) stream carries on its lifecycle events,
# mapped onto span phase names so old recordings still attribute.
_LEGACY_MS = {
    "quorum": ("quorum", "quorum_ms"),
    "reconfigure": ("configure", "configure_ms"),
    "heal_fetched": ("heal", "heal_ms"),
    "commit": ("commit_vote", "vote_ms"),
}


def _phase_ms(events: Sequence[dict]) -> Dict[Tuple[str, int], Dict[str, float]]:
    """{(replica_id, step): {phase: ms}} from span records, falling back to
    the legacy *_ms fields when a stream predates spans.  step_summary is
    authoritative when present (it is the flushed accumulation)."""
    spans: Dict[Tuple[str, int], Dict[str, float]] = {}
    summarized: set = set()
    for ev in events:
        rid = str(ev.get("replica_id", ""))
        kind = ev.get("event")
        if kind == "step_summary" and isinstance(ev.get("phases"), dict):
            key = (rid, int(ev.get("step", -1)))
            if key in summarized:
                # A failed-then-retried commit vote summarizes the same step
                # twice; the committed interval's wall spans both attempts,
                # so their phases ADD (replacing would misattribute the
                # first attempt's waits as productive time).
                d = spans.setdefault(key, {})
                for k, v in ev["phases"].items():
                    d[k] = d.get(k, 0.0) + float(v)
            else:
                # First summary supersedes the raw spans already
                # accumulated for this key — they are the same
                # measurements, flushed.
                spans[key] = {k: float(v) for k, v in ev["phases"].items()}
                summarized.add(key)
        elif kind == "span":
            key = (rid, int(ev.get("step", -1)))
            if key in summarized:
                continue
            d = spans.setdefault(key, {})
            phase = str(ev.get("phase", "?"))
            d[phase] = d.get(phase, 0.0) + float(ev.get("duration_ms", 0.0))
        elif kind in _LEGACY_MS:
            phase, field = _LEGACY_MS[kind]
            if ev.get(field) is None:
                continue
            key = (rid, int(ev.get("step", ev.get("max_step", -1))))
            if key in summarized:
                continue
            d = spans.setdefault(key, {})
            # Spans supersede the legacy duplicates of the same phase: the
            # Manager emits both (span record + legacy event) from ONE
            # measurement, so take max instead of summing.
            d[phase] = max(d.get(phase, 0.0), float(ev[field]))
    return spans


def quorum_server_ms(
    events: Sequence[dict], flight_events: Sequence[dict]
) -> Dict[Tuple[str, int], float]:
    """``{(replica_id, step): server-side quorum ms}`` joining the worker
    span stream against a lighthouse flight recorder by causal trace id.

    The worker's ``quorum`` span measures the CLIENT-observed wait (RPC
    transport, failover retries, the blocked server handler).  The flight
    recorder's ``rpc`` span for the same trace id measures the SERVER-side
    handling window (which contains the formation wait).  Their difference
    is client transport/retry cost — the split :func:`attribute` reports.
    Server spans for one trace id are summed across records (an HA
    failover records a rejection span on the old leader and the real span
    on the new one; both are real server-side time the client paid)."""
    server_ms: Dict[str, float] = {}
    for ev in flight_events:
        if ev.get("kind") != "rpc" or ev.get("method") != "Quorum":
            continue
        tid = str(ev.get("trace_id", ""))
        if not tid:
            continue
        server_ms[tid] = server_ms.get(tid, 0.0) + max(
            0.0, float(ev.get("dur_us", 0)) / 1e3
        )
    # Each (replica, step) sums its DISTINCT trace ids' server totals, not
    # one total per worker span: a retried commit re-runs the quorum with
    # the SAME step-keyed trace id and emits a second worker span — adding
    # server_ms per span would double the server share and zero out the
    # transport split on exactly the retried steps.
    tids_by_key: Dict[Tuple[str, int], set] = {}
    for ev in events:
        if ev.get("event") != "span" or ev.get("phase") != "quorum":
            continue
        tid = str(ev.get("trace_id", ""))
        if tid in server_ms:
            key = (str(ev.get("replica_id", "")), int(ev.get("step", -1)))
            tids_by_key.setdefault(key, set()).add(tid)
    return {
        key: sum(server_ms[tid] for tid in tids)
        for key, tids in tids_by_key.items()
    }


def attribute(
    events: Sequence[dict], flight_events: Optional[Sequence[dict]] = None
) -> dict:
    """Builds the per-step cluster attribution.

    Returns ``{"steps": [row...], "totals": {...}, "goodput": {...}}``.
    Each row: ``step``, ``replicas`` (committing that step), ``wall_s``
    (slowest replica's commit-to-commit interval), per-phase seconds of
    that slowest replica, ``productive_s`` (wall minus FT phases) and
    ``critical`` — the dominating bucket.

    Totals classify every group's [t0, t_end] wall time into productive /
    quorum_wait / heal / drain / idle: step intervals split by their phase
    breakdown; gaps between incarnations (or commit gaps containing a
    fault) are idle, or drain when a drain fault falls inside.

    With ``flight_events`` (a lighthouse flight-recorder dump's events,
    see obs/flight.py), quorum_wait_s is additionally split into
    ``quorum_server_s`` (the lighthouse's own formation/handling window,
    matched by causal trace id) and ``quorum_transport_s`` (client
    transport + failover retries) — informational sub-buckets, not new
    accounting classes.
    """
    commits = commit_timelines(events)
    faults = fault_times(events)
    dw = deadwindow(commits, faults)
    phase_ms = _phase_ms(events)
    elections = election_windows(events)
    server_q_ms = (
        quorum_server_ms(events, flight_events) if flight_events else {}
    )

    # Per-incarnation commit sequences: (rid, [(ts, t_mono, step)...]).
    per_inc: Dict[str, List[Tuple[float, float, int]]] = {}
    for ev in events:
        if ev.get("event") == "commit" and ev.get("committed"):
            per_inc.setdefault(str(ev.get("replica_id", "")), []).append(
                (
                    float(ev["ts"]),
                    float(ev.get("t_mono", ev["ts"])),
                    int(ev.get("step", -1)),
                )
            )

    steps: Dict[int, List[dict]] = {}
    totals = {
        "productive_s": 0.0,
        "quorum_wait_s": 0.0,
        "heal_s": 0.0,
        "other_ft_s": 0.0,
        "drain_s": 0.0,
        "idle_s": 0.0,
        # Informational: background snapshot time OVERLAPPED with the steps
        # above — deliberately outside the accounted classification.
        "snapshot_overlap_s": 0.0,
        # Informational: leader-election time inside step intervals.  Its
        # charge flows through quorum_wait_s (an election stalls exactly
        # the quorum path, so it is classified as quorum wait, NOT as a
        # worker fault's idle time) — this total just makes the election
        # cost visible on its own line.
        "election_s": 0.0,
        # Informational split of quorum_wait_s when a flight recorder was
        # provided: server-side formation/handling vs client transport and
        # retries.  Zero (not the split) without flight data.
        "quorum_server_s": 0.0,
        "quorum_transport_s": 0.0,
    }
    t0 = dw["t0"]
    for rid, seq in per_inc.items():
        seq.sort()
        for (ts_a, mono_a, _), (ts_b, mono_b, step) in zip(seq, seq[1:]):
            if t0 is not None and ts_b < t0:
                continue  # startup, outside the measured window
            # Same process: monotonic delta is the trustworthy duration.
            wall = max(0.0, mono_b - mono_a)
            phases = phase_ms.get((rid, step), {})
            q = phases.get("quorum", 0.0) / 1e3
            # Leader-election overlap with this interval is charged like
            # quorum wait: the quorum span usually measures the stall
            # already (the blocked quorum RPC IS the election wait), so the
            # election window acts as a FLOOR on q rather than adding to
            # it — never double-charged, never read as productive time.
            election = sum(
                max(0.0, min(ts_b, e) - max(ts_a, s)) for s, e in elections
            )
            election = min(election, wall)
            if election > q:
                q = election
            # ec_reconstruct is healing by another path (the donor-free
            # shard fallback) — same class, so a cluster that heals via
            # reconstruction reads comparably to one that heals via donors.
            heal = (
                phases.get("heal", 0.0) + phases.get("ec_reconstruct", 0.0)
            ) / 1e3
            skip = ("quorum", "heal", "ec_reconstruct") + _OVERLAPPED
            other_ft = (
                sum(v for k, v in phases.items() if k not in skip) / 1e3
            )
            snapshot_overlap = (
                sum(phases.get(k, 0.0) for k in _OVERLAPPED) / 1e3
            )
            # Flight-recorder split of the quorum wait: the server-side
            # window (clamped to q — clock granularity can make the server
            # span read microseconds past the client wait) vs the client's
            # transport/retry remainder.  Only meaningful when the span's
            # trace id matched a recorded server span.
            q_server = min(q, server_q_ms.get((rid, step), 0.0) / 1e3)
            q_transport = q - q_server if (rid, step) in server_q_ms else 0.0
            productive = max(0.0, wall - q - heal - other_ft)
            buckets = {
                "productive": productive,
                "quorum_wait": q,
                "heal": heal,
                **{k: v / 1e3 for k, v in phases.items() if k not in skip},
            }
            critical = max(buckets, key=lambda k: buckets[k]) if wall > 0 else "-"
            steps.setdefault(step, []).append(
                {
                    "replica_id": rid,
                    "wall_s": wall,
                    "quorum_wait_s": q,
                    "quorum_server_s": q_server,
                    "quorum_transport_s": q_transport,
                    "heal_s": heal,
                    "other_ft_s": other_ft,
                    "snapshot_overlap_s": snapshot_overlap,
                    "productive_s": productive,
                    "critical": critical,
                }
            )
            totals["productive_s"] += productive
            totals["quorum_wait_s"] += q
            totals["quorum_server_s"] += q_server
            totals["quorum_transport_s"] += q_transport
            totals["heal_s"] += heal
            totals["other_ft_s"] += other_ft
            totals["snapshot_overlap_s"] += snapshot_overlap
            totals["election_s"] += election

    # A restarted incarnation's heal span lies BEFORE its first commit, so
    # no commit interval covers it; credit it to the heal class (carved
    # out of that group's gap below) instead of leaving it in idle.
    first_commit_heal: Dict[str, float] = {}
    for rid, seq in per_inc.items():
        if not seq:
            continue
        ts_first, _, step_first = seq[0]
        if t0 is not None and ts_first >= t0:
            first_phases = phase_ms.get((rid, step_first), {})
            h = (
                first_phases.get("heal", 0.0)
                + first_phases.get("ec_reconstruct", 0.0)
            ) / 1e3
            if h:
                g = _group(rid)
                first_commit_heal[g] = first_commit_heal.get(g, 0.0) + h

    # Idle / drain: per group, wall time in [t0, t_end] not covered by
    # intra-incarnation step intervals — restart windows and fault gaps.
    # A gap belonging to a group whose only faults were drains is planned
    # departure cost ("drain"); everything else is dead time ("idle").
    if t0 is not None:
        drain_groups = {
            str(ev.get("group", ""))
            for ev in _fault_records(events)
            if str(ev.get("kind")) == "drain"
        }
        kill_groups = {
            str(ev.get("group", ""))
            for ev in _fault_records(events)
            if str(ev.get("kind")) not in ("drain", "straggler")
        }
        for g, ts_list in commits.items():
            covered = 0.0
            for rid, seq in per_inc.items():
                if _group(rid) != g:
                    continue
                for (ts_a, _, _), (ts_b, _, _) in zip(seq, seq[1:]):
                    a = max(ts_a, t0)
                    if ts_b > a:
                        covered += ts_b - a
            group_span = max(0.0, dw["t_end"] - max(t0, min(ts_list)))
            gap = max(0.0, group_span - covered)
            heal_in_gap = min(gap, first_commit_heal.get(g, 0.0))
            totals["heal_s"] += heal_in_gap
            gap -= heal_in_gap
            if g in drain_groups and g not in kill_groups:
                totals["drain_s"] += gap
            else:
                totals["idle_s"] += gap

    rows = []
    for step in sorted(steps):
        reps = steps[step]
        slowest = max(reps, key=lambda r: r["wall_s"])
        rows.append(
            {
                "step": step,
                "replicas": len(reps),
                "wall_s": round(slowest["wall_s"], 4),
                "productive_s": round(slowest["productive_s"], 4),
                "quorum_wait_s": round(slowest["quorum_wait_s"], 4),
                "quorum_server_s": round(slowest["quorum_server_s"], 4),
                "quorum_transport_s": round(slowest["quorum_transport_s"], 4),
                "heal_s": round(slowest["heal_s"], 4),
                "other_ft_s": round(slowest["other_ft_s"], 4),
                "snapshot_overlap_s": round(slowest["snapshot_overlap_s"], 4),
                "critical": slowest["critical"],
            }
        )

    accounted = sum(
        totals[k] for k in
        ("productive_s", "quorum_wait_s", "heal_s", "drain_s", "idle_s",
         "other_ft_s")
    )
    fractions = {
        k.replace("_s", "_fraction"): (round(v / accounted, 4) if accounted else None)
        for k, v in totals.items()
    }
    return {
        "steps": rows,
        "totals": {k: round(v, 3) for k, v in totals.items()},
        "fractions": fractions,
        # Byte-level rollup (payload + per-tier wire), comparable across
        # ring/ring2d topologies — not a time-accounting class.
        "data_plane": data_plane(events),
        # Hop-level wall attribution of the allreduce path (wire / stall /
        # combine / shaping) from the ring engines' hop telemetry.
        "link_attribution": link_attribution(events),
        # Per-step goodput-ledger rollup (obs/ledger.py): the cause
        # vectors each committed step_summary carries, summed per replica
        # and cluster-wide — the stream-side mirror of the lighthouse's
        # live /goodput.json.
        "ledger": _ledger_rollup(events),
        "goodput": {
            "deadwindow_fraction": (
                round(dw["fraction"], 4) if dw["fraction"] is not None else None
            ),
            "dead_time_s": (
                round(dw["dead_time_s"], 3) if dw["dead_time_s"] is not None else None
            ),
            "span_s": round(dw["span_s"], 3) if dw["span_s"] is not None else None,
            "victims_recovered": dw["victims_recovered"],
            "faults": len(faults),
            # Control-plane fault visibility: resolved leader elections in
            # the stream (their time is in totals.election_s, charged as
            # quorum wait — never as a worker dead window).
            "lighthouse_elections": len(elections),
        },
    }


def render(result: dict, out=sys.stdout) -> None:
    """Human-readable attribution table + goodput summary."""
    w = out.write
    w(
        f"{'step':>6} {'reps':>4} {'wall_s':>8} {'product':>8} "
        f"{'quorum':>8} {'heal':>8} {'other_ft':>8}  critical\n"
    )
    for r in result["steps"]:
        w(
            f"{r['step']:>6} {r['replicas']:>4} {r['wall_s']:>8.3f} "
            f"{r['productive_s']:>8.3f} {r['quorum_wait_s']:>8.3f} "
            f"{r['heal_s']:>8.3f} {r['other_ft_s']:>8.3f}  {r['critical']}\n"
        )
    t = result["totals"]
    w("\ntotals (s): " + "  ".join(f"{k}={v}" for k, v in t.items()) + "\n")
    f = result["fractions"]
    w("fractions:  " + "  ".join(f"{k}={v}" for k, v in f.items()) + "\n")
    g = result["goodput"]
    w(
        f"\ngoodput (dead-window): fraction={g['deadwindow_fraction']} "
        f"dead_time_s={g['dead_time_s']} span_s={g['span_s']} "
        f"faults={g['faults']} victims_recovered={g['victims_recovered']}\n"
    )


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m torchft_tpu.obs.report",
        description="Per-step goodput attribution from tpu-ft metrics JSONL",
    )
    ap.add_argument("paths", nargs="+", help="metrics.jsonl file(s)")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument(
        "--flight",
        action="append",
        default=[],
        metavar="FLIGHT_JSON",
        help="flight-recorder dump(s) (flight_lighthouse_*.json) — splits "
        "quorum_wait into server-formation vs client-transport by trace id",
    )
    args = ap.parse_args(argv)
    stats: dict = {}
    events = read_events(args.paths, stats=stats)
    if not events:
        print("no events parsed", file=sys.stderr)
        return 1
    flight: list = []
    unreadable_flight: list = []
    for fp in args.flight:
        try:
            from torchft_tpu.obs.flight import flight_events as _fes
            from torchft_tpu.obs.flight import load_flight_dump

            flight.extend(_fes(load_flight_dump(fp)))
        except (OSError, ValueError):
            unreadable_flight.append(fp)
            print(f"warning: {fp}: unreadable flight dump", file=sys.stderr)
    result = attribute(events, flight_events=flight or None)
    result["input"] = {
        "events": len(events),
        "skipped_lines": stats.get("skipped_lines", 0),
        "unreadable_files": stats.get("unreadable_files", []),
        "flight_events": len(flight),
        "unreadable_flight_dumps": unreadable_flight,
    }
    if args.json:
        json.dump(result, sys.stdout)
        print()
    else:
        render(result)
        if stats.get("skipped_lines"):
            sys.stdout.write(
                f"\n({stats['skipped_lines']} unparseable line(s) skipped)\n"
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
