"""Step-scoped tracing: begin/end spans for Manager step phases.

Turns the flat metrics event stream into a distributed trace without a
tracing dependency: each phase of a step runs inside ``SpanTracker.span``,
which measures a monotonic-clock duration and emits one ``span`` record
keyed by ``(slice_gen, step, replica_id)`` — ``replica_id`` comes from the
underlying :class:`~torchft_tpu.metrics.MetricsLogger`, ``slice_gen`` from
``TPUFT_SLICE_GEN`` (the scheduler's restart counter, see spec.py), so
records from every incarnation of every replica across restarts merge into
one unambiguous timeline.  ``obs/report.py`` is the matching consumer.

The known phase names are fixed in :data:`PHASES`; a span may use any name
(the record is self-describing) but report.py's attribution buckets are
built from these.

One tracker per Manager.  Phases of the same step may run on different
threads (the quorum thread vs the train loop), so the per-step breakdown
is lock-guarded; ``step_summary(step, committed=...)`` flushes the
accumulated phases as one record after the commit vote.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, Optional

from torchft_tpu.metrics import MetricsLogger

__all__ = [
    "PHASES",
    "OVERLAPPED_PHASES",
    "Span",
    "SpanTracker",
    "StepTimeStats",
]

# The Manager step phases report.py attributes (docs/architecture.md
# "Observability").  quorum = blocking wait on the lighthouse round;
# configure = collective rebuild on quorum change; heal = peer weight
# fetch; allreduce_d2h = the GradientAverager's per-bucket device->host
# fetch into the persistent flat buffers (blocks the train thread, so it
# is FT-overhead time, NOT productive compute — report.py charges it to
# the other-FT bucket and the straggler sentinel subtracts it from busy
# time); allreduce_h2d = the matching result scatter-back (device_put of
# reduced buckets onto the leaves' devices/shardings — with device wire
# prep it moves wire-dtype bytes; charged exactly like allreduce_d2h so
# the FULL round-trip cost is attributed, not just the fetch);
# allreduce_merge = drain of pending allreduce futures at commit
# time; commit_vote = the two-phase commit barrier RPC; snapshot = the
# donor-side device->host flatten on the HTTP transport's background
# snapshotter — an OVERLAPPED phase (it runs concurrently with the train
# step, so report.py shows it but does not charge it against productive
# time; a snapshot span on the critical path is exactly the regression the
# async pipeline exists to prevent); outer_sync = one fragment's
# background pseudogradient round on the semisync engine's worker
# (torchft_tpu/semisync) — OVERLAPPED for the same reason: it runs
# concurrent with inner steps, and only the round-end drain (charged as
# allreduce_merge) ever blocks the train thread; ec_encode = the k+m
# Reed-Solomon shard encode on the same background snapshotter
# (torchft_tpu/ec) — OVERLAPPED like snapshot, and the bench's
# donor-side-overhead cell exists to keep it that way; ec_reconstruct =
# the donor-free heal fallback assembling max-step state from surviving
# shard holders — blocks the healing group's quorum thread exactly like
# heal, and report.py folds it into the heal class.
PHASES = (
    "quorum",
    "configure",
    "heal",
    "ec_reconstruct",
    "allreduce_d2h",
    "allreduce_h2d",
    "allreduce_merge",
    "commit_vote",
    "snapshot",
    "ec_encode",
    "outer_sync",
)

# Phases that run on background threads concurrent with compute: report.py
# excludes these from per-step critical-path attribution.
OVERLAPPED_PHASES = ("snapshot", "ec_encode", "outer_sync")


class Span:
    """One in-flight phase measurement; ``duration_ms`` is valid after the
    ``with`` block exits (monotonic clock, NTP-immune)."""

    def __init__(self, tracker: "SpanTracker", phase: str, step: int, fields: dict):
        self._tracker = tracker
        self.phase = phase
        self.step = step
        self.fields = fields
        self.t_start = 0.0
        self.duration_ms: float = 0.0

    def __enter__(self) -> "Span":
        self.t_start = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.duration_ms = round((time.monotonic() - self.t_start) * 1e3, 3)
        self._tracker._finish(self, ok=exc_type is None)


class SpanTracker:
    """Emits ``span`` / ``step_summary`` records through a MetricsLogger.

    Spans are emitted even for phases that raise (with ``ok: false``) so a
    hung-then-failed quorum still shows up in the trace with its real
    duration.
    """

    def __init__(
        self, metrics: MetricsLogger, slice_gen: Optional[int] = None
    ) -> None:
        self._metrics = metrics
        if slice_gen is None:
            try:
                slice_gen = int(os.environ.get("TPUFT_SLICE_GEN", "0"))
            except ValueError:
                slice_gen = 0
        self.slice_gen = slice_gen
        self._lock = threading.Lock()
        # phase -> accumulated ms since the last step_summary.  Keyed by
        # phase, NOT by step: a heal fast-forwards the step number mid-step
        # (quorum ran at the old step, heal at max_step, the vote at
        # max_step), yet all of it is one train-loop iteration — the
        # summary flushes everything since the previous vote.  Individual
        # span records still carry their own step.
        self._acc: Dict[str, float] = {}

    @property
    def enabled(self) -> bool:
        return self._metrics.enabled

    def span(self, phase: str, step: int, **fields) -> Span:
        """Context manager measuring one phase of one step."""
        return Span(self, phase, step, fields)

    def phases_ms(self) -> Dict[str, float]:
        """Copy of the per-phase accumulation since the last
        ``step_summary`` flush — the goodput ledger reads this at commit
        time (BEFORE the flush) to classify the step's wall interval into
        its cause taxonomy (torchft_tpu/obs/ledger.py)."""
        with self._lock:
            return dict(self._acc)

    def ft_accounted_ms(self) -> float:
        """Milliseconds accumulated in NON-overlapped phases since the last
        ``step_summary`` flush — the FT wait time of the step in flight.
        The Manager subtracts this from the commit-to-commit wall interval
        to get the step's BUSY time for the straggler sentinel: in lockstep
        training the raw commit interval equalizes across the quorum (the
        slow host delays everyone), so only wall-minus-waits distinguishes
        the replica that actually computed the whole time."""
        with self._lock:
            return sum(
                v for k, v in self._acc.items() if k not in OVERLAPPED_PHASES
            )

    def _finish(self, span: Span, ok: bool) -> None:
        with self._lock:
            self._acc[span.phase] = self._acc.get(span.phase, 0.0) + span.duration_ms
        rec = {
            "phase": span.phase,
            "step": span.step,
            "slice_gen": self.slice_gen,
            "duration_ms": span.duration_ms,
        }
        if not ok:
            rec["ok"] = False
        rec.update(span.fields)
        self._metrics.emit("span", **rec)

    def step_summary(self, step: int, committed: bool, **fields) -> None:
        """Emits the per-step phase breakdown and resets the accumulator.
        Call once per step, after the commit vote."""
        with self._lock:
            rec = {
                "step": step,
                "slice_gen": self.slice_gen,
                "committed": committed,
                "phases": {k: round(v, 3) for k, v in self._acc.items()},
                "accounted_ms": round(sum(self._acc.values()), 3),
            }
            self._acc = {}
        rec.update(fields)
        self._metrics.emit("step_summary", **rec)


class StepTimeStats:
    """Rolling per-step wall-time statistics for the straggler sentinel.

    ``observe(ms)`` once per committed step with the step's BUSY
    milliseconds (commit-to-commit wall minus the FT wait phases; see
    ``SpanTracker.ft_accounted_ms``).  Maintains an EWMA — the smoothed
    pace the Manager pushes onto its lighthouse heartbeats — plus a sliding
    window for p50/p99, which ride in the ``step_summary`` record and
    bench.py's step-time distributions.

    Knobs: ``TPUFT_STEP_TIME_ALPHA`` (EWMA weight of the newest step,
    default 0.5 — heavy enough that a host going 2x slow crosses a 1.5x
    alert threshold on its first slow step, so detection latency is the
    sentinel's grace count, not the smoothing) and
    ``TPUFT_STEP_TIME_WINDOW`` (percentile window, default 64 steps).
    Thread-safe: observe runs on the train thread, snapshots may be read
    from anywhere.
    """

    def __init__(
        self, alpha: Optional[float] = None, window: Optional[int] = None
    ) -> None:
        if alpha is None:
            try:
                alpha = float(os.environ.get("TPUFT_STEP_TIME_ALPHA", "0.5"))
            except ValueError:
                alpha = 0.5
        if not (0.0 < alpha <= 1.0):
            alpha = 0.5
        if window is None:
            try:
                window = int(os.environ.get("TPUFT_STEP_TIME_WINDOW", "64"))
            except ValueError:
                window = 64
        self.alpha = alpha
        self._lock = threading.Lock()
        self._window: deque = deque(maxlen=max(2, window))
        self._ewma: Optional[float] = None
        self._last: float = 0.0
        self._n = 0

    def observe(self, ms: float) -> None:
        if ms < 0.0:
            return
        with self._lock:
            self._last = ms
            self._ewma = (
                ms
                if self._ewma is None
                else self.alpha * ms + (1.0 - self.alpha) * self._ewma
            )
            self._window.append(ms)
            self._n += 1

    @property
    def ewma_ms(self) -> float:
        with self._lock:
            return self._ewma or 0.0

    @property
    def last_ms(self) -> float:
        with self._lock:
            return self._last

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile over the sliding window (0 when empty)."""
        with self._lock:
            if not self._window:
                return 0.0
            ordered = sorted(self._window)
            idx = min(len(ordered) - 1, int(p / 100.0 * len(ordered)))
            return ordered[idx]

    def snapshot(self) -> Dict[str, float]:
        """{ewma, last, p50, p99, max, n} in ms — the step_summary payload."""
        with self._lock:
            ordered = sorted(self._window)
            n = len(ordered)

            def pct(p: float) -> float:
                return ordered[min(n - 1, int(p / 100.0 * n))] if n else 0.0

            return {
                "ewma": round(self._ewma or 0.0, 3),
                "last": round(self._last, 3),
                "p50": round(pct(50.0), 3),
                "p99": round(pct(99.0), 3),
                "max": round(ordered[-1], 3) if n else 0.0,
                "n": self._n,
            }
