"""Step-scoped tracing: begin/end spans for Manager step phases.

Turns the flat metrics event stream into a distributed trace without a
tracing dependency: each phase of a step runs inside ``SpanTracker.span``,
which measures a monotonic-clock duration and emits one ``span`` record
keyed by ``(slice_gen, step, replica_id)`` — ``replica_id`` comes from the
underlying :class:`~torchft_tpu.metrics.MetricsLogger`, ``slice_gen`` from
``TPUFT_SLICE_GEN`` (the scheduler's restart counter, see spec.py), so
records from every incarnation of every replica across restarts merge into
one unambiguous timeline.  ``obs/report.py`` is the matching consumer.

The known phase names are fixed in :data:`PHASES`; a span may use any name
(the record is self-describing) but report.py's attribution buckets are
built from these.

One tracker per Manager.  Phases of the same step may run on different
threads (the quorum thread vs the train loop), so the per-step breakdown
is lock-guarded; ``step_summary(step, committed=...)`` flushes the
accumulated phases as one record after the commit vote.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

from torchft_tpu.metrics import MetricsLogger

__all__ = ["PHASES", "OVERLAPPED_PHASES", "Span", "SpanTracker"]

# The Manager step phases report.py attributes (docs/architecture.md
# "Observability").  quorum = blocking wait on the lighthouse round;
# configure = collective rebuild on quorum change; heal = peer weight
# fetch; allreduce_merge = drain of pending allreduce futures at commit
# time; commit_vote = the two-phase commit barrier RPC; snapshot = the
# donor-side device->host flatten on the HTTP transport's background
# snapshotter — an OVERLAPPED phase (it runs concurrently with the train
# step, so report.py shows it but does not charge it against productive
# time; a snapshot span on the critical path is exactly the regression the
# async pipeline exists to prevent).
PHASES = ("quorum", "configure", "heal", "allreduce_merge", "commit_vote", "snapshot")

# Phases that run on background threads concurrent with compute: report.py
# excludes these from per-step critical-path attribution.
OVERLAPPED_PHASES = ("snapshot",)


class Span:
    """One in-flight phase measurement; ``duration_ms`` is valid after the
    ``with`` block exits (monotonic clock, NTP-immune)."""

    def __init__(self, tracker: "SpanTracker", phase: str, step: int, fields: dict):
        self._tracker = tracker
        self.phase = phase
        self.step = step
        self.fields = fields
        self.t_start = 0.0
        self.duration_ms: float = 0.0

    def __enter__(self) -> "Span":
        self.t_start = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.duration_ms = round((time.monotonic() - self.t_start) * 1e3, 3)
        self._tracker._finish(self, ok=exc_type is None)


class SpanTracker:
    """Emits ``span`` / ``step_summary`` records through a MetricsLogger.

    Spans are emitted even for phases that raise (with ``ok: false``) so a
    hung-then-failed quorum still shows up in the trace with its real
    duration.
    """

    def __init__(
        self, metrics: MetricsLogger, slice_gen: Optional[int] = None
    ) -> None:
        self._metrics = metrics
        if slice_gen is None:
            try:
                slice_gen = int(os.environ.get("TPUFT_SLICE_GEN", "0"))
            except ValueError:
                slice_gen = 0
        self.slice_gen = slice_gen
        self._lock = threading.Lock()
        # phase -> accumulated ms since the last step_summary.  Keyed by
        # phase, NOT by step: a heal fast-forwards the step number mid-step
        # (quorum ran at the old step, heal at max_step, the vote at
        # max_step), yet all of it is one train-loop iteration — the
        # summary flushes everything since the previous vote.  Individual
        # span records still carry their own step.
        self._acc: Dict[str, float] = {}

    @property
    def enabled(self) -> bool:
        return self._metrics.enabled

    def span(self, phase: str, step: int, **fields) -> Span:
        """Context manager measuring one phase of one step."""
        return Span(self, phase, step, fields)

    def _finish(self, span: Span, ok: bool) -> None:
        with self._lock:
            self._acc[span.phase] = self._acc.get(span.phase, 0.0) + span.duration_ms
        rec = {
            "phase": span.phase,
            "step": span.step,
            "slice_gen": self.slice_gen,
            "duration_ms": span.duration_ms,
        }
        if not ok:
            rec["ok"] = False
        rec.update(span.fields)
        self._metrics.emit("span", **rec)

    def step_summary(self, step: int, committed: bool, **fields) -> None:
        """Emits the per-step phase breakdown and resets the accumulator.
        Call once per step, after the commit vote."""
        with self._lock:
            rec = {
                "step": step,
                "slice_gen": self.slice_gen,
                "committed": committed,
                "phases": {k: round(v, 3) for k, v in self._acc.items()},
                "accounted_ms": round(sum(self._acc.values()), 3),
            }
            self._acc = {}
        rec.update(fields)
        self._metrics.emit("step_summary", **rec)
