"""Fault-tolerant optimizer wrapper for optax.

Reference parity: torchft/optim.py (OptimizerWrapper, torchft/optim.py:24-63).
The reference wraps a torch optimizer so that ``zero_grad()`` starts the
step's quorum and ``step()`` only applies when the commit vote passes.  In
JAX the optimizer is a pure ``optax.GradientTransformation`` over pytrees, so
the wrapper holds ``(params, opt_state)`` explicitly and the commit gate
decides whether the freshly computed pytrees replace the held state or are
dropped on the floor (the TPU analogue of skipping ``optim.step()``).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from torchft_tpu.manager import Manager


class Optimizer:
    """Commit-gated optax optimizer.

    Usage::

        opt = Optimizer(manager, optax.adamw(3e-4), params)
        for batch in data:
            opt.step_begin()                  # starts quorum (zero_grad analogue)
            grads = grad_fn(opt.params, batch)
            grads = synchronizer.allreduce(grads)  # manager.allreduce per bucket
            opt.step(grads)                   # applies only if should_commit()

    ``params``/``opt_state`` always hold the last *committed* values.
    """

    def __init__(self, manager: Manager, tx: Any, params: Any, opt_state: Any = None) -> None:
        self._manager = manager
        self._tx = tx
        self.params = params
        self.opt_state = opt_state if opt_state is not None else tx.init(params)

    @property
    def manager(self) -> Manager:
        return self._manager

    def step_begin(self) -> None:
        """Starts the quorum for this step (reference: zero_grad →
        manager.start_quorum, torchft/optim.py:44-49)."""
        self._manager.start_quorum()

    # Alias matching the reference's API shape.
    zero_grad = step_begin

    def step(self, grads: Any) -> bool:
        """Applies ``grads`` iff the commit vote passes (reference:
        torchft/optim.py:51-55).  Returns True when the update landed."""
        import optax

        if not self._manager.should_commit():
            return False
        updates, self.opt_state = self._tx.update(grads, self.opt_state, self.params)
        self.params = optax.apply_updates(self.params, updates)
        return True

    def state_dict(self) -> Tuple[Any, Any]:
        return (self.params, self.opt_state)

    def load_state_dict(self, state: Tuple[Any, Any]) -> None:
        self.params, self.opt_state = state
