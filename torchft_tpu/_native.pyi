# Typed public surface of the ctypes bindings over the native C++ core,
# so the runtime-loaded classes type-check for callers — the analogue of
# the reference's PyO3 stub (torchft/_torchft.pyi).
from typing import Any, Dict, List, Optional

LIGHTHOUSE_QUORUM: int
LIGHTHOUSE_HEARTBEAT: int
LIGHTHOUSE_STATUS: int
LIGHTHOUSE_EVICT: int
LIGHTHOUSE_DRAIN: int
LIGHTHOUSE_REPLICATE: int
LIGHTHOUSE_LEADER_INFO: int
LIGHTHOUSE_REGION_DIGEST: int
LIGHTHOUSE_REGIONS: int
NOT_LEADER_PREFIX: str
MANAGER_QUORUM: int
MANAGER_CHECKPOINT_METADATA: int
MANAGER_SHOULD_COMMIT: int
MANAGER_KILL: int
STORE_SET: int
STORE_GET: int
STORE_ADD: int
STORE_DELETE: int

class QuorumResult:
    quorum_id: int
    replica_rank: int
    replica_world_size: int
    recover_src_manager_address: str
    recover_src_replica_rank: Optional[int]
    recover_dst_replica_ranks: List[int]
    recover_dst_replica_ranks_all: List[int]
    recover_src_replica_ranks: List[int]
    recover_src_manager_addresses: List[str]
    participant_replica_ranks: List[int]
    participant_manager_addresses: List[str]
    store_address: str
    max_step: int
    max_replica_rank: Optional[int]
    max_world_size: int
    heal: bool
    def __init__(
        self,
        quorum_id: int = ...,
        replica_rank: int = ...,
        replica_world_size: int = ...,
        recover_src_manager_address: str = ...,
        recover_src_replica_rank: Optional[int] = ...,
        recover_dst_replica_ranks: List[int] = ...,
        recover_dst_replica_ranks_all: List[int] = ...,
        recover_src_replica_ranks: List[int] = ...,
        recover_src_manager_addresses: List[str] = ...,
        store_address: str = ...,
        max_step: int = ...,
        max_replica_rank: Optional[int] = ...,
        max_world_size: int = ...,
        heal: bool = ...,
    ) -> None: ...

class LighthouseServer:
    def __init__(
        self,
        bind: str = ...,
        min_replicas: int = ...,
        join_timeout_ms: int = ...,
        quorum_tick_ms: int = ...,
        heartbeat_timeout_ms: int = ...,
        http_bind: str = ...,
    ) -> None: ...
    def address(self) -> str: ...
    def http_address(self) -> str: ...
    def evict(self, replica_prefix: str) -> int: ...
    def drain(self, replica_prefix: str, deadline_ms: int = ...) -> int: ...
    def set_role(
        self,
        leader: bool,
        leader_address: str = ...,
        leader_http_address: str = ...,
        epoch: int = ...,
        lease_expires_ms: int = ...,
    ) -> None: ...
    def role(self) -> int: ...
    def leader_epoch(self) -> int: ...
    def set_federation(
        self, region: str, root_addrs: str, push_interval_ms: int = ...
    ) -> None: ...
    def regions_json(self) -> str: ...
    def regions(self) -> Dict[str, Any]: ...
    def flight_json(self, limit: int = ...) -> str: ...
    def flight(self, limit: int = ...) -> Dict[str, Any]: ...
    def link_state(self, replica_id: str) -> int: ...
    def snapshot(self) -> bytes: ...
    def shutdown(self) -> None: ...

def parse_not_leader(msg: str) -> Optional[str]: ...

class LighthouseClient:
    def __init__(self, addr: str, connect_timeout_ms: int = ...) -> None: ...
    def quorum(
        self,
        replica_id: str,
        timeout_ms: int = ...,
        address: str = ...,
        store_address: str = ...,
        step: int = ...,
        world_size: int = ...,
        shrink_only: bool = ...,
        data: Optional[Dict[str, Any]] = ...,
        trace_id: str = ...,
    ) -> Any: ...  # pb.Quorum
    def heartbeat(
        self,
        replica_id: str,
        timeout_ms: int = ...,
        step: int = ...,
        state: str = ...,
        step_time_ms_ewma: float = ...,
        step_time_ms_last: float = ...,
        trace_id: str = ...,
        link_recv_gbps: float = ...,
        link_send_gbps: float = ...,
        link_hop_rtt_ms: float = ...,
    ) -> None: ...
    def evict(self, replica_prefix: str, timeout_ms: int = ...) -> int: ...
    def drain(
        self,
        replica_prefix: str,
        deadline_ms: int = ...,
        timeout_ms: int = ...,
        trace_id: str = ...,
    ) -> int: ...
    def status(self, timeout_ms: int = ...) -> Any: ...  # pb.LighthouseStatusResponse
    def leader(self, timeout_ms: int = ...) -> Any: ...  # pb.LighthouseLeaderInfoResponse
    def replicate(self, snapshot: bytes, timeout_ms: int = ...) -> Any: ...
    def close(self) -> None: ...

class ManagerServer:
    def __init__(
        self,
        replica_id: str,
        lighthouse_addr: str,
        bind: str = ...,
        store_addr: str = ...,
        world_size: int = ...,
        heartbeat_interval_ms: int = ...,
        connect_timeout_ms: int = ...,
    ) -> None: ...
    def address(self) -> str: ...
    def set_status(
        self,
        step: int,
        state: str,
        step_time_ms_ewma: float = ...,
        step_time_ms_last: float = ...,
        allreduce_gb_per_s: float = ...,
        ec_shards_held: int = ...,
        ec_shard_step: int = ...,
        ec_k: int = ...,
        link_recv_gbps: float = ...,
        link_send_gbps: float = ...,
        link_hop_rtt_ms: float = ...,
    ) -> None: ...
    def set_ledger(
        self,
        goodput_ratio: float,
        compute_seconds: float,
        lost_seconds: List[float],
    ) -> None: ...
    def flight_json(self, limit: int = ...) -> str: ...
    def flight(self, limit: int = ...) -> Dict[str, Any]: ...
    def shutdown(self) -> None: ...

class ManagerClient:
    def __init__(self, addr: str, connect_timeout_ms: int = ...) -> None: ...
    def _quorum(
        self,
        group_rank: int,
        step: int,
        checkpoint_metadata: str,
        shrink_only: bool,
        timeout_ms: int,
        init_sync: bool = ...,
        commit_failures: int = ...,
        trace_id: str = ...,
    ) -> QuorumResult: ...
    def _checkpoint_metadata(
        self, rank: int, timeout_ms: int, trace_id: str = ...
    ) -> str: ...
    def should_commit(
        self,
        group_rank: int,
        step: int,
        should_commit: bool,
        timeout_ms: int,
        trace_id: str = ...,
    ) -> bool: ...
    def close(self) -> None: ...

class StoreServer:
    def __init__(self, bind: str = ...) -> None: ...
    def address(self) -> str: ...
    def shutdown(self) -> None: ...

class StoreClient:
    def __init__(
        self, addr: str, prefix: str = ..., connect_timeout_ms: int = ...
    ) -> None: ...
    def set(self, key: str, value: bytes, timeout_ms: int = ...) -> None: ...
    def get(
        self, key: str, wait: bool = ..., timeout_ms: int = ...
    ) -> Optional[bytes]: ...
    def add(self, key: str, delta: int, timeout_ms: int = ...) -> int: ...
    def delete(self, key: str, timeout_ms: int = ...) -> None: ...
    def close(self) -> None: ...

def ring_engine_available() -> bool: ...
def ring_engine_unavailable_reason() -> str: ...

class RingEngine:
    TIER_FLAT: int
    TIER_ROW: int
    TIER_COL: int
    PASS_FULL: int
    PASS_RS: int
    PASS_AG: int
    OP_SUM: int
    OP_MAX: int
    OP_MIN: int
    WIRE_RAW: int
    WIRE_BF16: int
    WIRE_INT8: int
    WIRE_INT4: int
    pass_calls: int
    def __init__(
        self, lanes: int, shaper_mbps: float = ..., shaper_rtt_ms: float = ...
    ) -> None: ...
    def set_tier(
        self, tier: int, next_fds: List[int], prev_fds: List[int]
    ) -> None: ...
    def exchange(
        self, tier: int, lane: int, tag: int, payload: bytes, timeout_s: float
    ) -> bytes: ...
    def ring_pass(
        self,
        tier: int,
        lane: int,
        n: int,
        rank: int,
        tag_base: int,
        rs_sub: int,
        ag_sub: int,
        mode: int,
        op: int,
        wire: int,
        chunk_ptrs: List[int],
        chunk_elems: List[int],
        timeout_s: float,
    ) -> None: ...
    def ring_pass_multi(
        self,
        tier: int,
        nstripes: int,
        n: int,
        rank: int,
        lanes: List[int],
        tag_bases: List[int],
        rs_sub: int,
        ag_sub: int,
        mode: int,
        op: int,
        wire: int,
        chunk_ptrs: List[int],
        chunk_elems: List[int],
        timeout_s: float,
    ) -> None: ...
    def set_shm(
        self, tier: int, direction: int, lane: int, path: str, token: int
    ) -> None: ...
    def counters(self, tier: int) -> tuple[List[int], List[int]]: ...
    def shaper_counters(self, tier: int, direction: int) -> tuple[int, int]: ...
    def link_bytes(self, tier: int, direction: int, lane: int) -> int: ...
    def set_hop(self, sample: int, cap: int = ...) -> None: ...
    def hop_stats(self, tier: int) -> Dict[str, Any]: ...
    def hop_records(self, cap: int = ...) -> List[Dict[str, Any]]: ...
    def shaper_wait_s(self, tier: int, direction: int) -> float: ...
    def set_shaper(
        self, tier: int, direction: int, mbps: float, rtt_ms: float
    ) -> None: ...
    def open_fd_count(self) -> int: ...
    def close(self) -> None: ...
