"""Erasure-coded peer state: donor-free healing (docs/architecture.md
"Donor-free healing").

After each committed step the checkpoint snapshotter's background thread
additionally encodes the canonical serialized state stream into ``k + m``
systematic Reed-Solomon shards over GF(256) and spreads them across the
replica groups (deterministic placement rotated per step, parity pushed
over integrity-checked HTTP).  A recovering group whose assigned donors
are unreachable — or whose donor fetch fails mid-stream — reconstructs the
max-step state from ANY ``k`` surviving shard holders instead: no donor on
the recovery critical path, no serving window, no rotation, tolerant of
``m`` simultaneous group losses (Gemini SOSP '23, ECRM HPCA '21; see
PAPERS.md).

Modules:
  - :mod:`~torchft_tpu.ec.gf` — vectorized GF(256) arithmetic (log/exp +
    full multiplication tables) and Gauss-Jordan inversion;
  - :mod:`~torchft_tpu.ec.encoder` — systematic Cauchy-matrix Reed-Solomon
    encode/decode over byte streams, bitwise-exact;
  - :mod:`~torchft_tpu.ec.placement` — deterministic shard -> peer-group
    placement, rotated per step;
  - :mod:`~torchft_tpu.ec.store` — in-memory bounded shard store, the
    integrity-checked HTTP push/fetch client, the any-k reconstruction
    client, and :class:`~torchft_tpu.ec.store.ECPlane` (the Manager-facing
    coordinator).
"""

from torchft_tpu.ec.encoder import Shard, decode_stream, encode_stream
from torchft_tpu.ec.placement import shard_holder, shards_for_holder
from torchft_tpu.ec.store import ECConfig, ECPlane, ShardStore, reconstruct

__all__ = [
    "ECConfig",
    "ECPlane",
    "Shard",
    "ShardStore",
    "decode_stream",
    "encode_stream",
    "reconstruct",
    "shard_holder",
    "shards_for_holder",
]
