"""Systematic Reed-Solomon shard codec over serialized state streams.

The unit of encoding is the CANONICAL serialized checkpoint stream — the
exact ``write_state_dict`` frame the HTTP ``/full`` endpoint serves
(length-prefixed pickled StateDictMeta + the raw flat bucket buffers).
Encoding that stream rather than individual tensors buys the bitwise
contract for free: a decode reproduces the identical frame bytes, so
``read_state_dict`` + ``unflatten_state_dict`` on the reconstruction path
yields a state dict bitwise-equal to a direct donor fetch — the property
the recovery planner's fallback (and its pinning test) relies on.

Layout: the stream is padded to ``k * L`` bytes (``L = ceil(total / k)``)
and split into ``k`` data shards; ``m`` parity shards are the Cauchy-matrix
rows of :func:`~torchft_tpu.ec.gf.cauchy_matrix` applied over the data
shards.  The code is MDS: ANY ``k`` of the ``k + m`` shards reconstruct the
stream.  When all ``k`` data shards survive, decode is a pure concatenation
(no field math at all — the common case when fewer than ``m + 1`` holders
died).

Every shard carries its own header (step, index, geometry, CRC32C) so a
shard fetched over HTTP is self-verifying; a corrupt shard is detected and
EXCLUDED, and the decoder simply draws on another holder.
"""

from __future__ import annotations

import io
import pickle
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from torchft_tpu.checkpointing.integrity import CRC_ALGO, checksum, verify
from torchft_tpu.checkpointing.serialization import (
    StateDictMeta,
    as_u8,
    state_dict_frames,
)
from torchft_tpu.ec import gf

__all__ = [
    "Shard",
    "decode_shards",
    "decode_stream",
    "encode_buffers",
    "encode_shards",
    "encode_stream",
    "read_shard",
    "write_shard",
    "write_shard_part",
]


@dataclass
class Shard:
    """One erasure shard plus the self-describing header that travels with
    it on the wire (``/ec/shard/<step>/<idx>``)."""

    step: int
    idx: int
    k: int
    m: int
    total_len: int  # unpadded canonical stream length
    crc: int
    algo: str
    payload: np.ndarray  # uint8, length ceil(total_len / k)
    # Generation fingerprint: checksum of the canonical stream's header
    # prefix.  Shards are only combinable when they came from the SAME
    # stream; every group's committed-step state is bitwise identical (the
    # commit protocol's invariant), so a digest mismatch at one (step, idx)
    # marks a divergent encoder — the reconstruction client groups holders
    # by digest and only decodes within the majority generation.  The
    # prefix embeds the per-buffer CRCs (meta.crcs), which is what makes
    # this 4-byte field content-binding, not just structural.
    digest: int = 0

    def header(self) -> dict:
        return {
            "step": self.step,
            "idx": self.idx,
            "k": self.k,
            "m": self.m,
            "total_len": self.total_len,
            "crc": self.crc,
            "algo": self.algo,
            "digest": self.digest,
        }

    @property
    def nbytes(self) -> int:
        return int(self.payload.nbytes)


def _gather_stream(prefix: bytes, buffers: Sequence[np.ndarray], k: int) -> Tuple[List[np.ndarray], int]:
    """Splits the virtual concatenation ``prefix + buffers`` into ``k``
    equal uint8 slices (last zero-padded) without materializing the whole
    multi-GB stream: each slice is filled segment-by-segment from the
    source buffers (one copy total — the shards themselves)."""
    total = len(prefix) + sum(int(b.nbytes) for b in buffers)
    L = max(2, -(-total // k))  # ceil
    L += L & 1  # even length: the GF pair-table gather walks uint16 views
    slices = [np.zeros(L, dtype=np.uint8) for _ in range(k)]
    pos = 0

    def emit(src: memoryview) -> None:
        nonlocal pos
        off = 0
        n = len(src)
        while off < n:
            s, r = divmod(pos, L)
            take = min(n - off, L - r)
            slices[s][r : r + take] = np.frombuffer(src[off : off + take], dtype=np.uint8)
            pos += take
            off += take

    emit(memoryview(prefix))
    for b in buffers:
        emit(memoryview(as_u8(b)))
    return slices, total


def encode_buffers(
    data: Sequence[np.ndarray],
    k: int,
    m: int,
    step: int,
    total_len: int,
    want: Optional[Sequence[int]] = None,
    digest: int = 0,
) -> Dict[int, Shard]:
    """k data slices -> the requested self-verifying shards (systematic:
    shards 0..k-1 ARE the data slices; k..k+m-1 the Cauchy parity rows).

    ``want`` limits which shards are materialized: data shards are free
    slices, but EVERY parity shard costs a full GF pass over the stream —
    so the write side (ECPlane) asks only for its placement assignment
    (plus all parity when it is the step's designated pusher) instead of
    paying m full passes on every group every step.  None = all k + m.
    """
    want_set = set(range(k + m)) if want is None else {int(i) for i in want}
    parity_rows = sorted(i - k for i in want_set if i >= k)
    parity: Dict[int, np.ndarray] = {}
    if parity_rows:
        mat = gf.cauchy_matrix(m, k)[parity_rows]
        for row, payload in zip(parity_rows, gf.gf_matmul(mat, data)):
            parity[k + row] = payload
    shards: Dict[int, Shard] = {}
    for idx in sorted(want_set):
        payload = data[idx] if idx < k else parity[idx]
        shards[idx] = Shard(
            step=step,
            idx=idx,
            k=k,
            m=m,
            total_len=total_len,
            crc=checksum(memoryview(payload)),
            algo=CRC_ALGO,
            payload=payload,
            digest=digest,
        )
    return shards


def encode_stream(
    meta: StateDictMeta,
    buffers: Sequence[np.ndarray],
    k: int,
    m: int,
    step: int,
) -> List[Shard]:
    """Encodes one flattened state dict into ALL its k + m shards."""
    prefix, _ = state_dict_frames(meta, list(buffers))
    data, total = _gather_stream(prefix, buffers, k)
    shards = encode_buffers(
        data, k, m, step, total, digest=_stream_digest(meta, buffers, prefix)
    )
    return [shards[i] for i in range(k + m)]


def _stream_digest(meta: StateDictMeta, buffers: Sequence[np.ndarray], prefix: bytes) -> int:
    """Content fingerprint of the canonical stream.  When the header
    already embeds per-buffer CRCs (the transport's default), hashing the
    prefix alone is content-binding; with TPUFT_HTTP_CRC=0 the prefix is
    only structural, so the buffers are checksummed here — otherwise two
    divergent same-shape encoders would collide and reconstruction could
    silently combine their shards into garbage."""
    if getattr(meta, "crcs", None) is not None:
        return checksum(prefix)
    chain = bytearray(checksum(prefix).to_bytes(4, "little"))
    for b in buffers:
        chain += checksum(b).to_bytes(4, "little")
    return checksum(bytes(chain))


def encode_shards(
    meta: StateDictMeta,
    buffers: Sequence[np.ndarray],
    k: int,
    m: int,
    step: int,
    want: Sequence[int],
) -> Dict[int, Shard]:
    """Encodes only the requested shard indices (the ECPlane write path)."""
    prefix, _ = state_dict_frames(meta, list(buffers))
    data, total = _gather_stream(prefix, buffers, k)
    return encode_buffers(
        data, k, m, step, total, want=want,
        digest=_stream_digest(meta, buffers, prefix),
    )


def decode_data_slices(
    shards: Dict[int, np.ndarray], k: int, m: int
) -> List[np.ndarray]:
    """ANY ``k`` entries of ``{shard_idx: payload}`` -> the k data slices.
    Raises ValueError when fewer than k distinct shards are given.  When
    all k data shards survive this is free (the systematic fast path);
    missing data rows are solved via the inverted generator submatrix."""
    if len(shards) < k:
        raise ValueError(f"need {k} shards to decode, have {len(shards)}")
    have = sorted(shards)[: k]
    L = len(shards[have[0]])
    for i in have:
        if len(shards[i]) != L:
            raise ValueError(f"shard {i} length {len(shards[i])} != {L}")
    data: List[Optional[np.ndarray]] = [None] * k
    missing = [j for j in range(k) if j not in shards]
    for j in range(k):
        if j in shards:
            data[j] = np.asarray(shards[j], dtype=np.uint8)
    if missing:
        # Solve for the missing data rows: rows of the generator matrix for
        # the k shards we ARE using, inverted over GF(256).
        gen = np.vstack([np.eye(k, dtype=np.uint8), gf.cauchy_matrix(m, k)])
        sub = gen[have]  # k x k, invertible by the MDS property
        inv = gf.gf_mat_inv(sub)
        used = [np.asarray(shards[i], dtype=np.uint8) for i in have]
        for j in missing:
            acc = np.zeros(L, dtype=np.uint8)
            for c, s in zip(inv[j], used):
                gf.addmul_into(acc, int(c), s)
            data[j] = acc
    return [d for d in data]  # type: ignore[misc]


def decode_shards(shards: Dict[int, np.ndarray], k: int, m: int, total_len: int) -> bytes:
    """ANY ``k`` entries of ``{shard_idx: payload}`` -> the original stream
    bytes (trimmed to ``total_len``)."""
    out = np.concatenate(decode_data_slices(shards, k, m))
    return out.tobytes()[:total_len]


class _SliceStream(io.RawIOBase):
    """Read-only stream over the virtual concatenation of the data slices,
    trimmed to the unpadded stream length — lets ``read_state_dict``
    deserialize a decoded checkpoint WITHOUT materializing a multi-GB
    contiguous copy first (two full copies saved on the systematic fast
    path, which matters on the heal critical path)."""

    def __init__(self, slices: Sequence[np.ndarray], total_len: int) -> None:
        self._views = [memoryview(s).cast("B") for s in slices]
        self._total = total_len
        self._pos = 0

    def readable(self) -> bool:  # pragma: no cover - io protocol
        return True

    def readinto(self, b) -> int:
        out = memoryview(b).cast("B")
        n = min(len(out), self._total - self._pos)
        if n <= 0:
            return 0
        L = len(self._views[0])
        done = 0
        while done < n:
            s, r = divmod(self._pos, L)
            take = min(n - done, L - r)
            out[done : done + take] = self._views[s][r : r + take]
            done += take
            self._pos += take
        return n

    def read(self, size: int = -1) -> bytes:
        if size is None or size < 0:
            size = self._total - self._pos
        buf = bytearray(min(size, self._total - self._pos))
        self.readinto(memoryview(buf))
        return bytes(buf)


def decode_stream(shards: Sequence[Shard]) -> Tuple[StateDictMeta, List[np.ndarray]]:
    """Verified shards -> (StateDictMeta, raw host buffers), bitwise-equal
    to what ``read_state_dict`` returns on a direct donor fetch.  Geometry
    must agree across the shards (one encode generation)."""
    from torchft_tpu.checkpointing.serialization import read_state_dict

    if not shards:
        raise ValueError("no shards")
    k, m, total = shards[0].k, shards[0].m, shards[0].total_len
    digest = shards[0].digest
    payloads: Dict[int, np.ndarray] = {}
    for s in shards:
        if (s.k, s.m, s.total_len) != (k, m, total):
            raise ValueError(
                f"shard {s.idx} geometry ({s.k},{s.m},{s.total_len}) != ({k},{m},{total})"
            )
        if s.digest != digest:
            # Shards from divergent encode generations (e.g. pre-init-sync
            # states) would decode to garbage that still parses nowhere —
            # refuse the combination outright.
            raise ValueError(
                f"shard {s.idx} digest {s.digest:#x} != {digest:#x}: "
                "mixed encode generations"
            )
        payloads[s.idx] = s.payload
    data = decode_data_slices(payloads, k, m)
    return read_state_dict(_SliceStream(data, total))


# -- wire framing ------------------------------------------------------------


def write_shard(shard: Shard) -> bytes:
    """8-byte LE header length + pickled header + raw payload — the body of
    one ``/ec/shard/<step>/<idx>`` transfer (both directions)."""
    header = pickle.dumps(shard.header())
    return b"".join(
        [len(header).to_bytes(8, "little"), header, shard.payload.tobytes()]
    )


def write_shard_part(shard: Shard, part: int, n: int) -> bytes:
    """Header + one payload byte range — the ``?part=<i>&n=<N>`` response
    body of ``/ec/shard/<step>/<idx>``.  Boundaries are ``i * L // N`` over
    the PAYLOAD (header lengths vary with pickled int widths, so frame
    offsets would not align across shard indices — payload offsets do,
    which is what lets the subset-rotation fetch decode each range with a
    different k-subset of shards).  Every part carries the full
    self-describing header (tiny next to the payload) so any part alone
    identifies generation and geometry; there is no per-part CRC —
    reassemblies verify the whole-payload CRC (single-shard range fetch)
    or the decoded stream's per-buffer CRCs (subset-rotation fetch)."""
    header = pickle.dumps(shard.header())
    pl = as_u8(shard.payload)
    lo, hi = part * len(pl) // n, (part + 1) * len(pl) // n
    return b"".join(
        [len(header).to_bytes(8, "little"), header, pl[lo:hi].tobytes()]
    )


def read_shard(raw: bytes, verify_crc: bool = True) -> Shard:
    """Parses (and by default CRC-verifies) one shard frame.  A mismatch
    raises IOError — the caller excludes the shard and draws on another
    holder, which is the 'corrupt shard detected and excluded' contract."""
    stream = io.BytesIO(raw)
    hlen = int.from_bytes(stream.read(8), "little")
    header = pickle.loads(stream.read(hlen))
    payload = np.frombuffer(stream.read(), dtype=np.uint8)
    shard = Shard(payload=payload, **header)
    if verify_crc:
        verify(
            memoryview(payload),
            shard.crc,
            shard.algo,
            f"ec shard {shard.idx} (step {shard.step})",
        )
    return shard
