"""Shard store, integrity-checked shard transport, and the EC coordinator.

Three layers:

  - :class:`ShardStore` — the bounded in-memory shard inventory one group
    keeps for its peers, served by the checkpoint HTTP server at
    ``GET /ec/shard/<step>/<idx>`` and filled both locally (the group's own
    placement-assigned shards, materialized from its own snapshot) and
    remotely (``POST /ec/shard/<step>/<idx>`` parity pushes);
  - module functions — the HTTP client side: push, inventory probe, fetch
    (CRC-verified on receipt), and :func:`reconstruct`, which assembles the
    max-step state from ANY ``k`` reachable shard holders;
  - :class:`ECPlane` — the Manager-facing coordinator: hooks the checkpoint
    transport's background snapshotter (encode OFF the train loop's
    critical path, in the overlapped ``ec_encode`` span), tracks the quorum
    peer set, and exposes the reconstruction entry the recovery planner's
    donor-free fallback calls.

Trust model: shard payloads are CRC-checked end to end (computed at encode
time, carried in the shard header, verified on every receive — push AND
fetch), so a torn push or a bit-flipped fetch is excluded, never decoded.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from torchft_tpu.ec.encoder import (
    Shard,
    decode_stream,
    encode_shards,
    read_shard,
    write_shard,
)
from torchft_tpu.ec.placement import shard_holder, shards_for_holder

logger = logging.getLogger("torchft_tpu.ec")

__all__ = [
    "ECConfig",
    "ECPlane",
    "ShardStore",
    "fetch_inventory",
    "fetch_shard",
    "push_shard",
    "reconstruct",
]

# Environment knobs (docs/api.md "Erasure-coded peer state").
TPUFT_EC_K_ENV = "TPUFT_EC_K"
TPUFT_EC_M_ENV = "TPUFT_EC_M"
TPUFT_EC_RETAIN_ENV = "TPUFT_EC_RETAIN"
TPUFT_EC_MODE_ENV = "TPUFT_EC_MODE"
TPUFT_EC_INTERVAL_ENV = "TPUFT_EC_INTERVAL"

_MODES = ("fallback", "prefer")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        logger.warning("ignoring malformed %s", name)
        return default


@dataclass
class ECConfig:
    """Erasure-coding geometry + policy.

    Args:
        k: data shard count (0 disables the EC plane entirely).
        m: parity shard count — the number of simultaneous group losses a
            step's shard generation survives.
        retain: encode generations kept per store (newest-step wins).
        mode: ``"fallback"`` reconstructs only when the donor fetch fails
            or no donor is reachable; ``"prefer"`` heals via reconstruction
            FIRST (the fully donor-free mode — survivors never open a
            serving window) and falls back to the donor fetch.
        interval: encode every Nth committed step (1 = every step).
    """

    k: int = 0
    m: int = 2
    retain: int = 2
    mode: str = "fallback"
    interval: int = 1

    def __post_init__(self) -> None:
        if self.k < 0 or self.m < 0 or self.k + self.m > 256:
            raise ValueError(f"bad EC geometry k={self.k} m={self.m}")
        if self.mode not in _MODES:
            # A typo'd mode silently running the lossy default would be a
            # policy surprise; construction is the place to fail loudly.
            raise ValueError(f"TPUFT_EC_MODE must be one of {_MODES}, got {self.mode!r}")
        self.retain = max(1, self.retain)
        self.interval = max(1, self.interval)

    @property
    def enabled(self) -> bool:
        return self.k > 0

    @property
    def n_shards(self) -> int:
        return self.k + self.m

    @classmethod
    def from_env(cls) -> "ECConfig":
        return cls(
            k=_env_int(TPUFT_EC_K_ENV, 0),
            m=_env_int(TPUFT_EC_M_ENV, 2),
            retain=_env_int(TPUFT_EC_RETAIN_ENV, 2),
            mode=os.environ.get(TPUFT_EC_MODE_ENV, "fallback") or "fallback",
            interval=_env_int(TPUFT_EC_INTERVAL_ENV, 1),
        )


class ShardStore:
    """Thread-safe bounded shard inventory: {step: {idx: Shard}}.

    Retention keeps the newest ``retain`` steps — a recovering peer always
    asks for the quorum's max step, and one generation of slack covers the
    holder whose own commit (and encode) landed a beat later.
    """

    def __init__(self, retain: int = 2) -> None:
        self._retain = max(1, retain)
        self._lock = threading.Lock()
        self._by_step: Dict[int, Dict[int, Shard]] = {}

    def put(self, shard: Shard) -> None:
        with self._lock:
            self._by_step.setdefault(shard.step, {})[shard.idx] = shard
            while len(self._by_step) > self._retain:
                del self._by_step[min(self._by_step)]

    def get(self, step: int, idx: int) -> Optional[Shard]:
        with self._lock:
            return self._by_step.get(step, {}).get(idx)

    def have(self, step: int) -> List[int]:
        with self._lock:
            return sorted(self._by_step.get(step, {}))

    def inventory(self, step: int) -> dict:
        """The ``GET /ec/have/<step>`` body: held indices + geometry +
        per-index generation digests (the reconstruction client only
        combines shards of one digest — see encoder.Shard.digest)."""
        with self._lock:
            shards = self._by_step.get(step, {})
            geo = next(iter(shards.values()), None)
            return {
                "step": step,
                "shards": sorted(shards),
                "k": geo.k if geo else 0,
                "m": geo.m if geo else 0,
                "total_len": geo.total_len if geo else 0,
                "digests": {str(i): s.digest for i, s in shards.items()},
            }

    def latest_step(self) -> int:
        with self._lock:
            return max(self._by_step) if self._by_step else -1

    def coverage(self) -> Tuple[int, int]:
        """(latest step held, shard count at that step) — the pair the
        Manager pushes onto heartbeats for the lighthouse's per-step
        shard-coverage gauges; (-1, 0) while empty."""
        with self._lock:
            if not self._by_step:
                return -1, 0
            step = max(self._by_step)
            return step, len(self._by_step[step])

    def nbytes(self) -> int:
        with self._lock:
            return sum(
                s.nbytes for shards in self._by_step.values() for s in shards.values()
            )


# -- HTTP client side --------------------------------------------------------


def _urlopen(url: str, timeout: float, data: Optional[bytes] = None):
    req = urllib.request.Request(url, data=data, method="POST" if data is not None else "GET")
    return urllib.request.urlopen(req, timeout=timeout)


def push_shard(base_url: str, shard: Shard, timeout: float) -> None:
    """POSTs one shard frame to a holder's store (server re-verifies the
    CRC before storing)."""
    with _urlopen(
        f"{base_url}/ec/shard/{shard.step}/{shard.idx}", timeout, data=write_shard(shard)
    ) as resp:
        resp.read()


def fetch_shard(base_url: str, step: int, idx: int, timeout: float) -> Shard:
    """Fetches + CRC-verifies one shard (IOError on corruption)."""
    with _urlopen(f"{base_url}/ec/shard/{step}/{idx}", timeout) as resp:
        return read_shard(resp.read())


# Range-striped shard fetch parallelism: parts per shard (0 = auto-size at
# ~one part per MB of shard frame, capped).  The same receiver-chooses
# contract as the checkpoint path's chunk striping.
TPUFT_EC_FETCH_PARTS_ENV = "TPUFT_EC_FETCH_PARTS"
_MAX_FETCH_PARTS = 8

# Subset-rotation striping (decode each payload range from its own
# k-subset so every reachable holder's link serves, parity included).
# Opt-in: the (k+m)/k fan-out wins only when holder LINKS bind; on a
# CPU-bound host the per-range GF math for parity rows costs more than the
# idle links were worth (measured ~25% slower on the 1-core bench host),
# so operators enable it where reconstruction is genuinely link-bound.
TPUFT_EC_SUBSET_STRIPE_ENV = "TPUFT_EC_SUBSET_STRIPE"


def _subset_stripe_enabled() -> bool:
    return os.environ.get(TPUFT_EC_SUBSET_STRIPE_ENV, "0") in ("1", "true", "on")


def _fetch_parts_for(est_bytes: int) -> int:
    raw = os.environ.get(TPUFT_EC_FETCH_PARTS_ENV, "0")
    try:
        parts = int(raw)
    except ValueError:
        parts = 0
    if parts > 0:
        return min(parts, _MAX_FETCH_PARTS)
    return max(1, min(_MAX_FETCH_PARTS, est_bytes // (1 << 20)))


def fetch_shard_part(
    base_url: str, step: int, idx: int, part: int, n: int, timeout: float
) -> Shard:
    """Fetches header + payload range ``part`` of ``n`` (NOT CRC-verified:
    the payload is a fragment; assemblies verify — see write_shard_part)."""
    with _urlopen(
        f"{base_url}/ec/shard/{step}/{idx}?part={part}&n={n}", timeout
    ) as resp:
        return read_shard(resp.read(), verify_crc=False)


def fetch_shard_striped(
    urls: Sequence[str],
    step: int,
    idx: int,
    timeout: float,
    est_bytes: int = 0,
) -> Shard:
    """Fetches ONE shard as disjoint payload byte ranges pulled in
    parallel — ``?part=<i>&n=<N>`` splits round-robin across every holder
    advertising this (idx, digest), or as N parallel connections to a
    single holder (the regime where the striped donor fetch already
    measured its win on this class of host).  Reassembly is in-order
    payload concatenation; the whole-payload CRC then verifies the
    assembly, so a holder serving divergent or misaligned bytes fails the
    fetch exactly like a torn stream (IOError)."""
    if not urls:
        raise IOError(f"ec shard {idx} (step {step}): no holders to fetch from")
    n = _fetch_parts_for(est_bytes)
    if n <= 1:
        return fetch_shard(urls[0], step, idx, timeout)

    def pull_part(p: int) -> Shard:
        return fetch_shard_part(urls[p % len(urls)], step, idx, p, n, timeout)

    with ThreadPoolExecutor(max_workers=n) as pool:
        parts = list(pool.map(pull_part, range(n)))
    first = parts[0]
    for p in parts[1:]:
        if (p.digest, p.k, p.m, p.total_len) != (
            first.digest, first.k, first.m, first.total_len,
        ):
            raise IOError(
                f"ec shard {idx} (step {step}): holders disagree on "
                "generation/geometry across range parts"
            )
    whole = Shard(
        payload=np.concatenate([np.asarray(p.payload, dtype=np.uint8) for p in parts]),
        **first.header(),
    )
    from torchft_tpu.checkpointing.integrity import verify

    verify(
        memoryview(whole.payload), whole.crc, whole.algo,
        f"ec shard {idx} (step {step}, striped reassembly)",
    )
    return whole


def _reconstruct_subset_striped(
    usable: Dict[int, List[str]],
    k: int,
    m: int,
    step: int,
    deadline: float,
    stats: dict,
):
    """Subset-rotation striped reconstruction: with ``h > k`` distinct
    reachable shard indices, the payload splits into ``h`` byte ranges and
    each range decodes from its OWN k-subset (Reed-Solomon is positionwise,
    so per-range decodes concatenate into the whole-stream decode).  The
    rotation excludes each index from exactly ``h - k`` ranges, so every
    holder link serves ``k/h`` of a shard instead of one idle-parity setup
    serving nothing — in the link-bound regime that is the (k+m)/k fan-out
    the striped donor fetch gets from extra donors, applied to the shard
    plane.  Integrity: no whole-shard CRC can apply to ranges; the decoded
    stream's per-buffer CRCs (read_state_dict) verify end to end instead.
    Raises on any failure — the caller falls back to whole-shard pulls."""
    from torchft_tpu.checkpointing.serialization import read_state_dict
    from torchft_tpu.ec.encoder import _SliceStream, decode_data_slices

    idxs = sorted(usable)
    h = len(idxs)
    grid = [
        (r, idx)
        for r in range(h)
        for j, idx in enumerate(idxs)
        # Range r excludes the h - k indices rotating from position r.
        if not any((r + t) % h == j for t in range(h - k))
    ]

    def pull_part(job):
        r, idx = job
        url = usable[idx][r % len(usable[idx])]
        return fetch_shard_part(
            url, step, idx, r, h, max(1.0, deadline - time.monotonic())
        )

    with ThreadPoolExecutor(max_workers=min(16, len(grid))) as pool:
        parts = list(pool.map(pull_part, grid))
    total_len = parts[0].total_len
    digest = parts[0].digest
    by_range: Dict[int, Dict[int, np.ndarray]] = {}
    for (r, idx), p in zip(grid, parts):
        if (p.digest, p.k, p.m, p.total_len) != (digest, k, m, total_len):
            raise IOError(
                f"ec shard {idx} range {r}: generation/geometry mismatch"
            )
        by_range.setdefault(r, {})[idx] = np.asarray(p.payload, dtype=np.uint8)
    per_range = [decode_data_slices(by_range[r], k, m) for r in range(h)]
    slices = [
        np.concatenate([per_range[r][j] for r in range(h)]) for j in range(k)
    ]
    stats["subset_striped"] = {"ranges": h, "indices": idxs[: h]}
    return read_state_dict(_SliceStream(slices, total_len))


def fetch_inventory(base_url: str, step: int, timeout: float) -> dict:
    with _urlopen(f"{base_url}/ec/have/{step}", timeout) as resp:
        return json.loads(resp.read().decode())


def reconstruct(
    holders: Sequence[str],
    step: int,
    timeout: float,
    poll_s: float = 0.3,
) -> Tuple[object, List[np.ndarray], dict]:
    """Assembles the step-``step`` state from any ``k`` shard holders.

    Probes every holder's inventory (in parallel), fetches ``k`` distinct
    shards (data shards preferred — the systematic fast path decodes by
    concatenation), retries corrupt/failed shards against alternate holders
    and alternate indices, and polls until the deadline while coverage is
    still short (a holder's encode for this step may land a moment after
    its commit).  Returns ``(meta, buffers, stats)``; raises RuntimeError
    when k distinct shards never became reachable.
    """
    if not holders:
        raise RuntimeError("ec reconstruct: no shard holders")
    deadline = time.monotonic() + timeout
    stats: dict = {"holders": len(holders), "probes": 0, "corrupt": 0, "fetch_errors": 0}
    last_err: Optional[Exception] = None
    bad: set = set()  # (idx, url) pairs that failed
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise RuntimeError(
                f"ec reconstruct for step {step} timed out: "
                f"{stats['probes']} probes over {len(holders)} holders, "
                f"last error: {last_err}"
            )
        # Inventory sweep: which holder has which shard indices, grouped
        # by generation digest — only shards of ONE generation combine.
        by_digest: Dict[int, Dict[int, List[str]]] = {}
        geo: Optional[Tuple[int, int, int]] = None
        per_probe = max(1.0, min(5.0, remaining))

        def probe(url: str):
            try:
                return url, fetch_inventory(url, step, per_probe)
            except Exception as e:  # noqa: BLE001 — a dead holder is data
                return url, e

        with ThreadPoolExecutor(max_workers=min(16, len(holders))) as pool:
            outcomes = list(pool.map(probe, holders))
        stats["probes"] += 1
        for url, inv in outcomes:
            if isinstance(inv, Exception):
                last_err = inv
                continue
            if not inv.get("shards"):
                continue
            if inv.get("k"):
                geo = (inv["k"], inv["m"], inv["total_len"])
            digests = inv.get("digests") or {}
            for idx in inv["shards"]:
                d = int(digests.get(str(idx), 0))
                by_digest.setdefault(d, {}).setdefault(idx, []).append(url)
        k = geo[0] if geo else 0
        # The widest-coverage generation wins; committed-step state is
        # bitwise identical across groups, so multiple digests mean a
        # divergent encoder (or pre-sync step-0 state) to be excluded.
        by_idx: Dict[int, List[str]] = {}
        if by_digest:
            by_idx = max(by_digest.values(), key=len)
            if len(by_digest) > 1:
                stats["digest_groups"] = len(by_digest)
        usable = {
            idx: [u for u in urls if (idx, u) not in bad]
            for idx, urls in by_idx.items()
        }
        usable = {idx: urls for idx, urls in usable.items() if urls}
        if geo and len(usable) >= k:
            # Shard frame size estimate for the range-striping auto-sizer:
            # total_len / k data bytes plus a small header.
            est_shard_bytes = geo[2] // max(1, k)
            # Subset-rotation striping (opt-in, link-bound deployments):
            # more reachable indices than k means idle holder links under
            # whole-shard pulls; per-range k-subset decode spreads the SAME
            # k shards' worth of bytes over all of them.  Any failure falls
            # back to the whole-shard path below.
            if (
                len(usable) > k
                and _subset_stripe_enabled()
                and _fetch_parts_for(est_shard_bytes) > 1
            ):
                try:
                    meta, buffers = _reconstruct_subset_striped(
                        usable, k, geo[1], step, deadline, stats
                    )
                    idxs = sorted(usable)
                    stats["shards_used"] = idxs
                    stats["parity_used"] = sum(1 for i in idxs if i >= k)
                    return meta, buffers, stats
                except Exception as e:  # noqa: BLE001 — degrade, don't fail
                    stats["fetch_errors"] += 1
                    stats.pop("subset_striped", None)
                    last_err = e

            chosen = sorted(usable)[:k]  # lowest-first: data shards decode by concat

            def pull(idx: int):
                errs: List[Exception] = []
                # Range-striped first (disjoint byte ranges in parallel,
                # round-robin over every same-digest holder of this idx —
                # the striped-donor fetch's parallelism applied to the
                # shard plane).  Any failure — including a pre-range
                # holder serving full frames for part requests, which the
                # reassembly CRC catches — falls back to the whole-shard
                # per-holder loop below.
                if _fetch_parts_for(est_shard_bytes) > 1:
                    try:
                        got = fetch_shard_striped(
                            usable[idx], step, idx,
                            max(1.0, deadline - time.monotonic()),
                            est_bytes=est_shard_bytes,
                        )
                        stats["striped_fetches"] = stats.get("striped_fetches", 0) + 1
                        return got
                    except Exception as e:  # noqa: BLE001 — degrade, don't fail
                        stats["fetch_errors"] += 1
                        errs.append(e)
                for url in usable[idx]:
                    try:
                        return fetch_shard(url, step, idx, max(1.0, deadline - time.monotonic()))
                    except IOError as e:
                        stats["corrupt"] += 1
                        bad.add((idx, url))
                        errs.append(e)
                    except Exception as e:  # noqa: BLE001 — holder died mid-fetch
                        stats["fetch_errors"] += 1
                        bad.add((idx, url))
                        errs.append(e)
                return errs[-1] if errs else RuntimeError(f"no holder for shard {idx}")

            with ThreadPoolExecutor(max_workers=min(16, k)) as pool:
                pulls = list(pool.map(pull, chosen))
            got = [p for p in pulls if isinstance(p, Shard)]
            if len(got) == k:
                meta, buffers = decode_stream(got)
                stats["shards_used"] = [s.idx for s in got]
                stats["parity_used"] = sum(1 for s in got if s.idx >= k)
                return meta, buffers, stats
            last_err = next(p for p in pulls if not isinstance(p, Shard))
            # Loop: the bad-set now excludes the failures; alternate indices
            # or holders may still cover k.
            continue
        time.sleep(min(poll_s, max(0.0, deadline - time.monotonic())))


# -- Manager-facing coordinator ----------------------------------------------


class ECPlane:
    """Per-group EC coordinator (one per Manager, rank 0 of the group).

    Write side: :meth:`on_snapshot` runs on the checkpoint transport's
    background snapshotter after every flatten — it encodes the canonical
    stream into ``k + m`` shards inside the overlapped ``ec_encode`` span,
    stores this group's placement-assigned shards locally, and (as the
    step's rotated designated pusher) pushes parity shards to the peers
    that own them, so holders whose own pipeline is behind still hold
    their parity.  Data shards are never pushed: every group materializes
    its own assignment from its own (replicated) state — zero wire cost.

    Read side: :meth:`reconstruct_state` is the recovery planner's
    donor-free fallback — probe the peer set's shard inventories, fetch any
    ``k``, decode, hand back (meta, buffers) bitwise-equal to a donor
    fetch.

    The replicated-state assumption: cross-group replica state is
    IDENTICAL at a committed step (the torchft DDP/HSDP model) — that is
    what lets every group encode the same canonical stream independently.
    """

    def __init__(
        self,
        config: ECConfig,
        store: Optional[ShardStore] = None,
        spans=None,
        metrics=None,
        resolve_peer: Optional[Callable[[str], str]] = None,
        push_timeout: float = 30.0,
    ) -> None:
        self.config = config
        self.store = store if store is not None else ShardStore(retain=config.retain)
        self._spans = spans
        self._metrics = metrics
        # manager address -> shard-endpoint base URL (the peer's checkpoint
        # transport metadata); resolution dials the peer's manager, so the
        # result is cached per address.
        self._resolve_peer = resolve_peer
        self._peer_http: Dict[str, str] = {}
        self._push_timeout = push_timeout
        self._lock = threading.Lock()
        self._peer_ranks: List[int] = []
        self._peer_addrs: Dict[int, str] = {}
        self._self_rank: Optional[int] = None
        self._last_encoded_step = -1

    # -- membership ---------------------------------------------------------

    def set_peers(
        self, ranks: Sequence[int], addrs: Sequence[str], self_rank: Optional[int]
    ) -> None:
        """Updates the placement membership from the latest quorum's
        participant list (sorted replica ranks + manager addresses)."""
        with self._lock:
            self._peer_ranks = sorted(ranks)
            self._peer_addrs = dict(zip(ranks, addrs))
            self._self_rank = self_rank

    def _membership(self):
        with self._lock:
            return list(self._peer_ranks), dict(self._peer_addrs), self._self_rank

    def wants_snapshot(self, step: int) -> bool:
        """Whether enqueueing a snapshot for ``step`` would lead to an
        encode — the Manager asks BEFORE enqueueing, because the flatten +
        CRC pass the snapshotter pays happens regardless of whether
        :meth:`on_snapshot` then encodes; skipping the enqueue when the
        interval/membership/step gates would drop it anyway saves a full
        state-sized host copy per gated step."""
        ranks, _, self_rank = self._membership()
        if not (
            self.config.enabled
            and self_rank is not None
            and len(ranks) >= 2
            and step > 0
            and step > self._last_encoded_step
            and step % self.config.interval == 0
        ):
            return False
        # Placement gate: with more groups than shards, the rotation gives
        # this group zero assignments on some steps; unless it is also the
        # step's designated parity pusher, on_snapshot would encode nothing
        # — so don't pay the flatten for it.
        return bool(
            shards_for_holder(step, self_rank, ranks, self.config.n_shards)
            or ranks[step % len(ranks)] == self_rank
        )

    def _http_base(self, addr: str) -> Optional[str]:
        if self._resolve_peer is None:
            return addr  # tests/benches hand shard URLs directly
        base = self._peer_http.get(addr)
        if base is None:
            try:
                base = self._resolve_peer(addr)
            except Exception as e:  # noqa: BLE001 — a dead peer resolves later
                logger.debug("ec peer %s unresolvable: %s", addr, e)
                return None
            self._peer_http[addr] = base
        return base

    # -- write side (snapshotter thread) ------------------------------------

    def on_snapshot(self, step: int, meta, buffers) -> None:
        """Encode + place one committed step's shard generation.  Runs on
        the background snapshotter — never on the train loop — and must
        never raise (a failed encode degrades to donor-path-only healing
        for this step)."""
        cfg = self.config
        ranks, addrs, self_rank = self._membership()
        if not cfg.enabled or self_rank is None or len(ranks) < 2:
            return
        if step <= 0:
            # Pre-init-sync states legitimately DIVERGE across groups
            # (different random init until participant 0's weights land);
            # encoding them would spread mixed-generation shards that can
            # never combine.  Step 0 heals stay on the donor path.
            return
        if step <= self._last_encoded_step or step % cfg.interval != 0:
            return
        try:
            # Materialize ONLY what this group needs: its placement-assigned
            # shards (data assignments are free slices) plus — when it is
            # the step's designated pusher — every parity shard.  Each
            # parity shard costs a full GF pass over the stream, so the
            # fleet-wide encode cost per step is ~(m/n + m) passes total,
            # not n*m.
            own = shards_for_holder(step, self_rank, ranks, cfg.n_shards)
            is_pusher = ranks[step % len(ranks)] == self_rank
            want = set(own)
            if is_pusher:
                want |= set(range(cfg.k, cfg.n_shards))
            if not want:
                self._last_encoded_step = step
                return
            if self._spans is not None:
                with self._spans.span("ec_encode", step=step) as sp:
                    shards = encode_shards(meta, buffers, cfg.k, cfg.m, step, want)
                encode_ms = sp.duration_ms
            else:
                t0 = time.monotonic()
                shards = encode_shards(meta, buffers, cfg.k, cfg.m, step, want)
                encode_ms = (time.monotonic() - t0) * 1e3
            self._last_encoded_step = step
            for idx in own:
                self.store.put(shards[idx])
            pushed, push_errors, push_bytes = self._push_parity(
                step, shards, ranks, addrs, self_rank, is_pusher
            )
            if self._metrics is not None:
                any_shard = next(iter(shards.values()))
                self._metrics.emit(
                    "ec_push",
                    step=step,
                    k=cfg.k,
                    m=cfg.m,
                    encode_ms=round(encode_ms, 3),
                    shard_bytes=any_shard.nbytes,
                    held=len(self.store.have(step)),
                    pushed=pushed,
                    push_errors=push_errors,
                    push_bytes=push_bytes,
                )
        except Exception as e:  # noqa: BLE001 — encode must not kill the snapshotter
            logger.exception("ec encode for step %s failed: %s", step, e)

    def _push_parity(self, step, shards, ranks, addrs, self_rank, is_pusher):
        """The step's designated pusher sends each parity shard to its
        assigned holder.  Rotating the pusher (not broadcasting from every
        group) keeps wire cost at one copy of the parity per step for the
        whole cluster; receivers verify the CRC and store idempotently."""
        cfg = self.config
        pushed = errors = nbytes = 0
        if not is_pusher:
            return pushed, errors, nbytes
        for idx in range(cfg.k, cfg.n_shards):
            holder = shard_holder(step, idx, ranks)
            if holder == self_rank:
                continue
            base = self._http_base(addrs.get(holder, ""))
            if not base:
                errors += 1
                continue
            try:
                push_shard(base, shards[idx], self._push_timeout)
                pushed += 1
                nbytes += shards[idx].nbytes
            except Exception as e:  # noqa: BLE001 — push is best-effort
                errors += 1
                # Drop the cached URL: a respawned peer keeps its manager
                # address but gets a fresh checkpoint-HTTP port, and a
                # cache that never invalidates would silently bleed
                # redundancy on every following step.
                self._peer_http.pop(addrs.get(holder, ""), None)
                logger.warning(
                    "ec parity push shard %d step %d to rank %s failed: %s",
                    idx, step, holder, e,
                )
        return pushed, errors, nbytes

    # -- read side (recovery planner) ----------------------------------------

    def holder_urls(self) -> List[str]:
        """Shard-endpoint base URLs of every resolvable peer (self's own
        store is reachable through its local transport too, but a fresh
        incarnation's store is empty — peers are the interesting set)."""
        ranks, addrs, self_rank = self._membership()
        urls: List[str] = []
        for rank in ranks:
            if rank == self_rank:
                continue
            base = self._http_base(addrs.get(rank, ""))
            if base:
                urls.append(base)
        return urls

    def reconstruct_state(self, step: int, timeout: float):
        """(meta, buffers, stats) for ``step`` from any ``k`` holders."""
        try:
            return reconstruct(self.holder_urls(), step, timeout)
        except Exception:
            # A failed reconstruction may mean stale cached peer URLs
            # (respawned peers on fresh ports); the next attempt should
            # re-resolve everything rather than retry dead endpoints.
            self._peer_http.clear()
            raise

    def coverage(self) -> Tuple[int, int]:
        return self.store.coverage()

    def reshard(self) -> int:
        """Proactive re-placement after a membership change: re-derives the
        newest held generation's placement under the NEW peer set and
        pushes every held shard whose new holder is a peer.  Called by the
        Manager on the quorum thread right after a participant-set change
        (set_peers has already installed the new membership), so coverage
        is restored BEFORE the next fault instead of waiting for the next
        encode interval — the window the ``tpuft_ec_shard_coverage``
        lighthouse alert fires on.  Keeps the local copies (extra
        redundancy is free; retention evicts them); best-effort like every
        push path — returns the number of shards actually pushed."""
        cfg = self.config
        ranks, addrs, self_rank = self._membership()
        if not cfg.enabled or self_rank is None or len(ranks) < 2:
            return 0
        step = self.store.latest_step()
        if step < 0:
            return 0
        pushed = errors = nbytes = 0
        for idx in self.store.have(step):
            holder = shard_holder(step, idx, ranks)
            if holder == self_rank:
                continue
            shard = self.store.get(step, idx)
            if shard is None:
                continue  # evicted between have() and get()
            base = self._http_base(addrs.get(holder, ""))
            if not base:
                errors += 1
                continue
            try:
                push_shard(base, shard, self._push_timeout)
                pushed += 1
                nbytes += shard.nbytes
            except Exception as e:  # noqa: BLE001 — reshard is best-effort
                errors += 1
                self._peer_http.pop(addrs.get(holder, ""), None)
                logger.warning(
                    "ec reshard shard %d step %d to rank %s failed: %s",
                    idx, step, holder, e,
                )
        if self._metrics is not None and (pushed or errors):
            self._metrics.emit(
                "ec_push",
                step=step,
                k=cfg.k,
                m=cfg.m,
                reshard=True,
                held=len(self.store.have(step)),
                pushed=pushed,
                push_errors=errors,
                push_bytes=nbytes,
            )
        return pushed
