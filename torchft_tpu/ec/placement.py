"""Deterministic shard -> peer-group placement, rotated per step.

Every group derives the SAME placement from the same inputs — the sorted
participant rank list of the quorum and the step being encoded — so the
write side (which shards do I materialize into my own store?) and the read
side (which holder should have shard i?) agree without any coordination
RPC.  The per-step rotation spreads both the storage and the
reconstruction read load across the fleet instead of pinning shard 0's
bytes to the same group forever.

Placement is an OPTIMIZATION hint on the read side: the reconstruction
client probes holders' ``/ec/have/<step>`` inventories anyway, so a stale
membership view degrades to an extra probe, never to a wrong decode.
"""

from __future__ import annotations

from typing import List, Sequence, TypeVar

H = TypeVar("H")

__all__ = ["shard_holder", "shards_for_holder"]


def shard_holder(step: int, idx: int, holders: Sequence[H]) -> H:
    """The holder assigned shard ``idx`` of the step-``step`` generation.
    ``holders`` must be the same sorted sequence on every group (the
    quorum's participant ranks)."""
    if not holders:
        raise ValueError("no holders")
    return holders[(idx + step) % len(holders)]


def shards_for_holder(
    step: int, holder: H, holders: Sequence[H], n_shards: int
) -> List[int]:
    """All shard indices assigned to ``holder`` this step (the write-side
    view: which shards a group materializes into its own store)."""
    return [
        idx
        for idx in range(n_shards)
        if shard_holder(step, idx, holders) == holder
    ]
