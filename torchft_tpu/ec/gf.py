"""Vectorized GF(256) arithmetic for the Reed-Solomon shard codec.

The field is GF(2^8) with the primitive polynomial x^8 + x^4 + x^3 + x^2 + 1
(0x11D, the classic Reed-Solomon field; generator 2).  Two table families:

  - ``_EXP``/``_LOG``: scalar multiply/divide/invert via logarithms (the
    textbook construction, used for matrix algebra on tiny k x k systems);
  - ``_MUL``: the full 256 x 256 product table (64 KB), so multiplying a
    CONSTANT into a multi-hundred-MB shard is one ``np.take`` per shard —
    numpy fancy-indexing runs at memory bandwidth, which is what makes the
    encode affordable inside the overlapped snapshot window.

Addition in GF(2^8) is XOR, so accumulation across data shards is
``np.bitwise_xor`` — also a bandwidth-bound numpy primitive.

Everything here is pure numpy; no device, no dependency beyond the stdlib.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

__all__ = [
    "addmul_into",
    "cauchy_matrix",
    "gf_inv",
    "gf_matmul",
    "gf_mat_inv",
    "gf_mul",
    "mul_const",
]

_POLY = 0x11D


def _build_tables():
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= _POLY
    exp[255:510] = exp[0:255]  # wraparound so log[a] + log[b] never reduces
    # Full product table: MUL[a, b] = a * b in GF(256).
    a = np.arange(256, dtype=np.int32)
    la = log[a][:, None]  # (256, 1)
    lb = log[a][None, :]  # (1, 256)
    mul = exp[la + lb].astype(np.uint8)
    mul[0, :] = 0
    mul[:, 0] = 0
    return exp, log, mul


_EXP, _LOG, _MUL = _build_tables()


def gf_mul(a: int, b: int) -> int:
    """Scalar product in GF(256)."""
    return int(_MUL[a & 0xFF, b & 0xFF])


def gf_inv(a: int) -> int:
    """Multiplicative inverse (raises on 0, which has none)."""
    if a == 0:
        raise ZeroDivisionError("0 has no inverse in GF(256)")
    return int(_EXP[255 - _LOG[a]])


# Per-constant uint16 PAIR tables, built lazily and cached: T16[c][hi<<8|lo]
# = (c*hi)<<8 | (c*lo).  Gathering through a uint16 view halves the element
# count fancy indexing walks — measured ~2x over the byte table on this
# class of host — at 64 KB per constant (only the handful of Cauchy/inverse
# coefficients a deployment actually uses get built).
_PAIR_TABLES: dict = {}


def _pair_table(c: int) -> np.ndarray:
    t = _PAIR_TABLES.get(c)
    if t is None:
        row = _MUL[c].astype(np.uint16)
        t = (row[:, None] << 8 | row[None, :]).ravel()
        _PAIR_TABLES[c] = t
    return t


def _mul_gather(c: int, vec: np.ndarray) -> np.ndarray:
    """``c * vec`` for c >= 2 via the fastest available gather."""
    if vec.nbytes % 2 == 0:
        return _pair_table(c)[vec.view(np.uint16)].view(np.uint8)
    return _MUL[c][vec]


def mul_const(c: int, vec: np.ndarray) -> np.ndarray:
    """``c * vec`` elementwise over a uint8 array (one table gather)."""
    if c == 0:
        return np.zeros_like(vec)
    if c == 1:
        return vec.copy()
    return _mul_gather(c, vec)


def addmul_into(acc: np.ndarray, c: int, vec: np.ndarray) -> None:
    """``acc ^= c * vec`` in place — the encode/decode inner loop."""
    if c == 0:
        return
    if c == 1:
        np.bitwise_xor(acc, vec, out=acc)
        return
    np.bitwise_xor(acc, _mul_gather(c, vec), out=acc)


def gf_matmul(mat: np.ndarray, shards: Sequence[np.ndarray]) -> List[np.ndarray]:
    """Rows of ``mat`` (r x k, uint8) applied to ``k`` equal-length uint8
    shards: ``out[i] = XOR_j mat[i, j] * shards[j]``."""
    r, k = mat.shape
    assert k == len(shards), f"matrix is {r}x{k} but {len(shards)} shards given"
    out: List[np.ndarray] = []
    for i in range(r):
        acc = np.zeros_like(shards[0])
        for j in range(k):
            addmul_into(acc, int(mat[i, j]), shards[j])
        out.append(acc)
    return out


def gf_mat_inv(mat: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inverse of a k x k uint8 matrix over GF(256).

    Raises ValueError on a singular matrix — with the Cauchy construction
    below that never happens for a legal shard subset, so a singularity here
    means corrupted shard indices, and decode must fail loudly."""
    k = mat.shape[0]
    assert mat.shape == (k, k)
    a = mat.astype(np.uint8).copy()
    inv = np.eye(k, dtype=np.uint8)
    for col in range(k):
        pivot = -1
        for row in range(col, k):
            if a[row, col] != 0:
                pivot = row
                break
        if pivot < 0:
            raise ValueError("singular matrix over GF(256)")
        if pivot != col:
            a[[col, pivot]] = a[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        pinv = gf_inv(int(a[col, col]))
        a[col] = _MUL[pinv].take(a[col])
        inv[col] = _MUL[pinv].take(inv[col])
        for row in range(k):
            if row == col or a[row, col] == 0:
                continue
            c = int(a[row, col])
            a[row] ^= _MUL[c].take(a[col])
            inv[row] ^= _MUL[c].take(inv[col])
    return inv


def cauchy_matrix(m: int, k: int) -> np.ndarray:
    """The m x k Cauchy matrix P[i, j] = 1 / (x_i + y_j) with x_i = k + i,
    y_j = j.  The systematic generator [I_k ; P] built from it is MDS: every
    k x k submatrix of the stacked matrix is invertible, so ANY k of the
    k + m shards reconstruct the data (the property the every-k-subset
    decode test pins).  Requires k + m <= 256."""
    if k + m > 256:
        raise ValueError(f"k + m = {k + m} exceeds the GF(256) field size")
    out = np.zeros((m, k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            out[i, j] = gf_inv((k + i) ^ j)
    return out
