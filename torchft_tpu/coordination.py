"""Low-level coordination API re-exports.

Reference parity: torchft/coordination.py:17-33 — the public surface of the
native bindings for users who want to build custom fault-tolerance logic on
the raw quorum/heartbeat primitives.
"""

from torchft_tpu._native import (
    LighthouseClient,
    LighthouseServer,
    ManagerClient,
    ManagerServer,
    QuorumResult,
    StoreClient,
    StoreServer,
)
from torchft_tpu.proto import tpuft_pb2 as proto

Quorum = proto.Quorum
QuorumMember = proto.QuorumMember

__all__ = [
    "LighthouseClient",
    "LighthouseServer",
    "ManagerClient",
    "ManagerServer",
    "Quorum",
    "QuorumMember",
    "QuorumResult",
    "StoreClient",
    "StoreServer",
]
