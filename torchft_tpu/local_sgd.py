"""Communication-efficient replica synchronization: LocalSGD and DiLoCo.

Reference parity: torchft/local_sgd.py (LocalSGD torchft/local_sgd.py:41-167,
DiLoCo torchft/local_sgd.py:170-320).  Both run many inner optimizer steps
locally and synchronize across replica groups only every ``sync_every``
steps, with commit gating so a failed sync never corrupts the model.

JAX adaptation: instead of hooking a torch optimizer and mutating
``param.data`` in place, these classes own a reference to the training
state through ``get_params``/``set_params`` callables (pytrees are
immutable), and ``step()`` is called explicitly after each inner update.
DiLoCo's device backup of the last-synced params is a host (numpy) pytree —
the analogue of the reference's pinned-CPU backup tensors
(torchft/local_sgd.py:205-222).

Note on the pseudogradient sign: the DiLoCo paper (arXiv:2311.08105) defines
the outer gradient as ``backup - local`` so that an SGD *descent* step moves
the global params toward the averaged local progress; the reference computes
``local - backup`` (torchft/local_sgd.py:290) and relies on the outer
optimizer's configuration to compensate.  We implement the paper sign.
"""

from __future__ import annotations

from types import TracebackType
from typing import Any, Callable, List, Optional, Type

import numpy as np

from torchft_tpu.manager import Manager

__all__ = ["LocalSGD", "DiLoCo"]


def _tree_to_host(tree: Any) -> Any:
    import jax

    return jax.tree.map(np.asarray, tree)


class LocalSGD:
    """Averages raw model weights across replica groups every ``sync_every``
    inner steps (reference: torchft/local_sgd.py:41-167).

    Usage::

        with LocalSGD(manager, get_params, set_params, sync_every=100) as lsgd:
            for batch in data:
                params = inner_update(params, batch)   # plain local optax step
                lsgd.step()                            # counts + maybe syncs
    """

    def __init__(
        self,
        manager: Manager,
        get_params: Callable[[], Any],
        set_params: Callable[[Any], None],
        sync_every: int,
    ) -> None:
        assert sync_every >= 1, "sync_every must be >= 1"
        self._manager = manager
        self._get_params = get_params
        self._set_params = set_params
        self._sync_every = sync_every
        self._local_step = 0

    def __enter__(self) -> "LocalSGD":
        return self

    def __exit__(
        self,
        exc_type: Optional[Type[BaseException]],
        exc_value: Optional[BaseException],
        traceback: Optional[TracebackType],
    ) -> bool:
        return False

    def step(self) -> None:
        """Call after each inner optimizer step (the reference's registered
        post-step hook, torchft/local_sgd.py:95-104)."""
        self._local_step += 1
        if self._local_step >= self._sync_every:
            self.sync()

    def sync(self) -> None:
        """Quorum + weight averaging + commit-gated copy-back
        (reference: torchft/local_sgd.py:106-135)."""
        self._manager.start_quorum()
        averaged = self._average(self._get_params())
        if self._manager.should_commit():
            self._set_params(averaged)
        self._local_step = 0

    def _average(self, params: Any) -> Any:
        from torchft_tpu.ddp import PerLeafGradientAverager

        # PARAMETERS, not gradients: opt out of lossy wire encodings —
        # bf16-per-hop rounding of the weights themselves would accumulate
        # across syncs (gradient noise does not excuse it here).
        return PerLeafGradientAverager(self._manager).allreduce(
            params, allow_wire_compression=False
        )


class DiLoCo:
    """Inner/outer optimizer synchronization (reference:
    torchft/local_sgd.py:170-320; DiLoCo, arXiv:2311.08105).

    Keeps a host backup of the last globally-committed params.  Every
    ``sync_every`` inner steps: compute pseudogradients ``backup - local``,
    allreduce-average them across groups, restore the backup params, and only
    if the commit vote passes apply the outer optimizer (typically SGD with
    Nesterov momentum) to the backup using the averaged pseudogradient.

    Requires synchronous quorum (``use_async_quorum=False``) exactly like the
    reference (torchft/local_sgd.py:188-192): a healing group must have the
    committed weights *before* computing its pseudogradient.
    """

    def __init__(
        self,
        manager: Manager,
        get_params: Callable[[], Any],
        set_params: Callable[[Any], None],
        outer_tx: Any,
        sync_every: int,
    ) -> None:
        if manager._use_async_quorum:
            raise ValueError(
                "DiLoCo requires synchronous quorum: construct the Manager "
                "with use_async_quorum=False"
            )
        assert sync_every >= 1, "sync_every must be >= 1"
        self._manager = manager
        self._get_params = get_params
        self._set_params = set_params
        self._outer_tx = outer_tx
        self._sync_every = sync_every
        self._local_step = 0

        # Host backup of the last-synced params (torchft/local_sgd.py:205-222).
        self._backup = _tree_to_host(get_params())
        self._outer_state = outer_tx.init(self._backup)

        # The outer-loop state must travel with the model when a restarted
        # group heals from a peer: a fresh-init backup would make the next
        # sync compute pseudogradients against the wrong base and silently
        # diverge (the reference's DiLoCo recovery test checkpoints
        # original_parameters + outer optimizer state for exactly this,
        # torchft/local_sgd_integ_test.py:124-158).
        manager.register_state_dict_fn(
            "diloco", self._load_outer_state, self._save_outer_state
        )

    def __enter__(self) -> "DiLoCo":
        return self

    def __exit__(
        self,
        exc_type: Optional[Type[BaseException]],
        exc_value: Optional[BaseException],
        traceback: Optional[TracebackType],
    ) -> bool:
        return False

    @property
    def backup_params(self) -> Any:
        return self._backup

    @backup_params.setter
    def backup_params(self, value: Any) -> None:
        self._backup = _tree_to_host(value)

    def _save_outer_state(self) -> Any:
        return {
            "backup": self._backup,
            "outer_state": _tree_to_host(self._outer_state),
        }

    def _load_outer_state(self, state: Any) -> None:
        self.backup_params = state["backup"]
        self._outer_state = state["outer_state"]

    def step(self) -> None:
        self._local_step += 1
        if self._local_step >= self._sync_every:
            self.sync()

    def sync(self) -> None:
        """Pseudogradient sync (reference: torchft/local_sgd.py:277-303)."""
        self._manager.start_quorum()
        self._perform_sync()
        self._local_step = 0

    def _perform_sync(self) -> None:
        import jax
        import optax

        from torchft_tpu.ddp import PerLeafGradientAverager

        local = _tree_to_host(self._get_params())
        pseudograds = jax.tree.map(lambda b, l: b - l, self._backup, local)

        # Average pseudogradients across participating groups.
        averaged = PerLeafGradientAverager(self._manager).allreduce(pseudograds)

        if self._manager.should_commit():
            updates, self._outer_state = self._outer_tx.update(
                averaged, self._outer_state, self._backup
            )
            self._backup = optax.apply_updates(self._backup, updates)
        # Commit or not, the live params are reset to the (possibly updated)
        # last-committed weights (torchft/local_sgd.py:294-301).
        self._set_params(self._backup)
