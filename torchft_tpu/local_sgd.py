"""Communication-efficient replica synchronization: LocalSGD and DiLoCo.

Reference parity: torchft/local_sgd.py (LocalSGD torchft/local_sgd.py:41-167,
DiLoCo torchft/local_sgd.py:170-320).  Both run many inner optimizer steps
locally and synchronize across replica groups only every ``sync_every``
steps, with commit gating so a failed sync never corrupts the model.

As of the streaming semi-sync subsystem (torchft_tpu/semisync), ``DiLoCo``
here is a THIN WRAPPER: the old constructor, ``step()``/``sync()`` cadence,
``backup_params`` accessor, and the ``"diloco"`` state-dict channel are
preserved, but the data plane underneath is
:class:`torchft_tpu.semisync.StreamingDiLoCo` in blocking mode
(``stream=False``, ``codec="auto"``) — fragment-bucketed pseudogradient
rounds through the striped ring instead of the per-leaf host allreduce the
port started with.  New code that wants background fragment streaming and
the int8+EF wire should use ``StreamingDiLoCo`` directly.

JAX adaptation: instead of hooking a torch optimizer and mutating
``param.data`` in place, these classes own a reference to the training
state through ``get_params``/``set_params`` callables (pytrees are
immutable), and ``step()`` is called explicitly after each inner update.

Note on the pseudogradient sign: the DiLoCo paper (arXiv:2311.08105) defines
the outer gradient as ``backup - local`` so that an SGD *descent* step moves
the global params toward the averaged local progress; the reference computes
``local - backup`` (torchft/local_sgd.py:290) and relies on the outer
optimizer's configuration to compensate.  We implement the paper sign.
"""

from __future__ import annotations

from types import TracebackType
from typing import Any, Callable, Optional, Type

import numpy as np

from torchft_tpu.manager import Manager

__all__ = ["LocalSGD", "DiLoCo"]

# jax module cache: _tree_to_host runs on the sync path every round, and
# the old per-call ``import jax`` paid an import-machinery lookup per sync
# (plus one per leaf via np.asarray on trees that were ALREADY host
# numpy).  Cached module + an isinstance skip make the host conversion
# free for host trees.
_jax_mod = None


def _tree_to_host(tree: Any) -> Any:
    global _jax_mod
    if _jax_mod is None:
        import jax

        _jax_mod = jax
    return _jax_mod.tree.map(
        lambda x: x if isinstance(x, np.ndarray) else np.asarray(x), tree
    )


class LocalSGD:
    """Averages raw model weights across replica groups every ``sync_every``
    inner steps (reference: torchft/local_sgd.py:41-167).

    Usage::

        with LocalSGD(manager, get_params, set_params, sync_every=100) as lsgd:
            for batch in data:
                params = inner_update(params, batch)   # plain local optax step
                lsgd.step()                            # counts + maybe syncs
    """

    def __init__(
        self,
        manager: Manager,
        get_params: Callable[[], Any],
        set_params: Callable[[Any], None],
        sync_every: int,
    ) -> None:
        assert sync_every >= 1, "sync_every must be >= 1"
        self._manager = manager
        self._get_params = get_params
        self._set_params = set_params
        self._sync_every = sync_every
        self._local_step = 0
        # Hoisted out of the sync hot path: the old code constructed a
        # fresh averager (and re-imported its module) inside every sync.
        from torchft_tpu.ddp import PerLeafGradientAverager

        self._averager = PerLeafGradientAverager(manager)

    def __enter__(self) -> "LocalSGD":
        return self

    def __exit__(
        self,
        exc_type: Optional[Type[BaseException]],
        exc_value: Optional[BaseException],
        traceback: Optional[TracebackType],
    ) -> bool:
        return False

    def step(self) -> None:
        """Call after each inner optimizer step (the reference's registered
        post-step hook, torchft/local_sgd.py:95-104)."""
        self._local_step += 1
        if self._local_step >= self._sync_every:
            self.sync()

    def sync(self) -> None:
        """Quorum + weight averaging + commit-gated copy-back
        (reference: torchft/local_sgd.py:106-135).

        Errors UP TO the commit vote LATCH on the manager and the step
        counter resets in a ``finally``: a sync that dies mid-quorum on one
        group must not leave that group's ``_local_step`` desynced from its
        peers — all groups re-enter the next round on the same cadence, and
        the latched error fails this round's commit instead of crashing the
        loop (a rank that failed before voting still votes False, so
        sibling local ranks never burn the full barrier timeout).  The
        post-vote copy-back is OUTSIDE the latch: once peers were told we
        committed, a failed ``set_params`` must crash (and heal back to the
        committed weights), never be swallowed into silent divergence."""
        from torchft_tpu.manager import ExceededMaxRetriesError

        averaged = None
        committed = False
        voted = False
        try:
            self._manager.start_quorum()
            averaged = self._average(self._get_params())
            voted = True
            committed = bool(self._manager.should_commit())
        except ExceededMaxRetriesError:
            # The give-up contract must still propagate: a loop configured
            # with max_retries relies on this exception to terminate.
            raise
        except Exception as e:  # noqa: BLE001 — latch, never desync cadence
            try:
                self._manager.report_error(e)
            except Exception:  # noqa: BLE001 — mocked managers
                pass
            if not voted:
                # Sibling local ranks are already in the two-phase commit
                # barrier; vote (False, via the latched error) instead of
                # leaving them to time out round after round.
                try:
                    self._manager.should_commit()
                except Exception:  # noqa: BLE001 — vote itself failing
                    pass
        finally:
            self._local_step = 0
        if committed and averaged is not None:
            self._set_params(averaged)

    def _average(self, params: Any) -> Any:
        # PARAMETERS, not gradients: opt out of lossy wire encodings —
        # bf16-per-hop rounding of the weights themselves would accumulate
        # across syncs (gradient noise does not excuse it here), and the
        # int8+EF codec is gradient-only by the same argument.
        return self._averager.allreduce(params, allow_wire_compression=False)


class DiLoCo:
    """Inner/outer optimizer synchronization (reference:
    torchft/local_sgd.py:170-320; DiLoCo, arXiv:2311.08105).

    Thin wrapper over :class:`torchft_tpu.semisync.StreamingDiLoCo` in
    BLOCKING mode: the legacy call shape — quorum + pseudogradient
    allreduce + commit-gated outer step, all inside ``sync()`` — with the
    fragment-bucketed data plane underneath.  Keeps a host backup of the
    last globally-committed params; every ``sync_every`` inner steps:
    compute pseudogradients ``backup - local``, allreduce-average them
    across groups, and only if the commit vote passes apply the outer
    optimizer (typically SGD with Nesterov momentum) to the backup.

    Requires synchronous quorum (``use_async_quorum=False``) exactly like
    the reference (torchft/local_sgd.py:188-192): a healing group must have
    the committed weights *before* computing its pseudogradient.
    """

    def __init__(
        self,
        manager: Manager,
        get_params: Callable[[], Any],
        set_params: Callable[[Any], None],
        outer_tx: Any,
        sync_every: int,
    ) -> None:
        from torchft_tpu.semisync import StreamingDiLoCo

        # codec="auto" preserves the port's wire behavior (the collective's
        # own policy: bf16 only on bandwidth-bound links); stream=False
        # preserves the blocking sync-at-the-boundary cadence and the
        # quorum/vote call pattern the wrapper tests pin; outer_scope=
        # "tree" preserves the single whole-tree outer optimizer state —
        # its exact semantics for cross-leaf-coupled transforms
        # (global-norm clipping) AND its state-dict format (old durable
        # checkpoints keep loading).
        self._impl = StreamingDiLoCo(
            manager,
            get_params,
            set_params,
            outer_tx,
            sync_every,
            codec="auto",
            stream=False,
            outer_scope="tree",
        )

    def __enter__(self) -> "DiLoCo":
        return self

    def __exit__(
        self,
        exc_type: Optional[Type[BaseException]],
        exc_value: Optional[BaseException],
        traceback: Optional[TracebackType],
    ) -> bool:
        return self._impl.__exit__(exc_type, exc_value, traceback)

    @property
    def backup_params(self) -> Any:
        return self._impl.backup_params

    @backup_params.setter
    def backup_params(self, value: Any) -> None:
        self._impl.backup_params = value

    def step(self) -> None:
        self._impl.step()

    def sync(self) -> None:
        """Pseudogradient sync (reference: torchft/local_sgd.py:277-303);
        latches errors and resets the inner-step counter in a ``finally``
        (see StreamingDiLoCo.sync)."""
        self._impl.sync()
