"""Drain-notice sources, multiplexed into one event.

A planned departure is announced through one of three channels, each with a
different shape; :class:`DrainWatcher` normalizes them into a single
:class:`DrainNotice` and invokes one callback exactly once:

  - **SIGTERM** — what Kubernetes (and most orchestrators) send at the
    start of a termination grace period.  The handler chains to any
    previously installed one.
  - **GCE metadata server** — a poller over the instance metadata
    ``preempted`` and ``maintenance-event`` endpoints (the 30 s
    spot/preemptible notice and host-maintenance announcements).  Off by
    default; enabled by ``TPUFT_GCE_DRAIN_POLL=1`` or a
    ``TPUFT_GCE_METADATA_URL`` override (which tests point at a local
    stub server).
  - **Explicit trigger** — a JSON notice file (``TPUFT_DRAIN_DIR`` +
    ``drain_<group>.json``, written atomically by the launcher's
    ``drain()`` or by an operator from the CLI), or a programmatic
    :meth:`DrainWatcher.trigger` call.

The watcher never raises into the train loop and is safe to start in any
process (signal installation silently degrades off the main thread).
"""

from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

logger = logging.getLogger(__name__)

__all__ = [
    "DRAIN_DIR_ENV",
    "DRAIN_GRACE_ENV",
    "GCE_METADATA_URL_ENV",
    "GCE_POLL_ENV",
    "DrainNotice",
    "DrainWatcher",
]

# Directory the supervisor and the CLI write per-group notice files into
# (file name: drain_<REPLICA_GROUP_ID>.json).
DRAIN_DIR_ENV = "TPUFT_DRAIN_DIR"
# Default grace period in seconds for sources that carry no deadline of
# their own (SIGTERM, bare trigger calls).  30 s = the GCE spot notice.
DRAIN_GRACE_ENV = "TPUFT_DRAIN_GRACE_S"
# Override of the GCE metadata base URL (tests point this at a local stub).
GCE_METADATA_URL_ENV = "TPUFT_GCE_METADATA_URL"
# Opt-in for polling the real metadata server.
GCE_POLL_ENV = "TPUFT_GCE_DRAIN_POLL"

_DEFAULT_GRACE_S = 30.0
_GCE_DEFAULT_URL = "http://metadata.google.internal/computeMetadata/v1/instance"


@dataclass(frozen=True)
class DrainNotice:
    """One announced departure: where it came from and how long we have."""

    # "sigterm" | "gce-preemption" | "gce-maintenance" | "file" | explicit.
    source: str
    # Unix timestamp after which the process may be forcibly gone.
    deadline: float

    def remaining_s(self) -> float:
        return max(0.0, self.deadline - time.time())

    def deadline_ms_from_now(self) -> int:
        return int(self.remaining_s() * 1000)


class DrainWatcher:
    """Multiplexes drain-notice sources into one callback.

    Args:
        on_notice: called once, from whichever thread observed the notice
            first, with the :class:`DrainNotice`.  Must not block for long.
        group_id: replica group id used to derive the notice-file name;
            defaults to ``REPLICA_GROUP_ID`` (resolved at ``start()``, i.e.
            after hot-spare adoption has pinned the id).
        grace_s: deadline for sources without one (default: 30 s or
            ``TPUFT_DRAIN_GRACE_S``).
        sigterm: install the SIGTERM hook (main thread only; silently
            skipped elsewhere).
        drain_dir: notice-file directory (default: ``TPUFT_DRAIN_DIR``;
            no file polling when unset).
        gce_url: metadata base URL; polling runs when this is set
            explicitly, ``TPUFT_GCE_METADATA_URL`` is set, or
            ``TPUFT_GCE_DRAIN_POLL=1``.
        poll_interval_s: file/metadata poll period.
    """

    def __init__(
        self,
        on_notice: Optional[Callable[[DrainNotice], None]] = None,
        *,
        group_id: Optional[str] = None,
        grace_s: Optional[float] = None,
        sigterm: bool = True,
        drain_dir: Optional[str] = None,
        gce_url: Optional[str] = None,
        poll_interval_s: float = 0.25,
    ) -> None:
        self._on_notice = on_notice
        self._group_id = group_id
        if grace_s is None:
            try:
                grace_s = float(os.environ.get(DRAIN_GRACE_ENV, _DEFAULT_GRACE_S))
            except ValueError:
                grace_s = _DEFAULT_GRACE_S
        self._grace_s = grace_s
        self._sigterm = sigterm
        self._drain_dir = drain_dir if drain_dir is not None else os.environ.get(
            DRAIN_DIR_ENV
        )
        self._gce_url = gce_url or os.environ.get(GCE_METADATA_URL_ENV)
        self._gce_enabled = bool(
            gce_url
            or os.environ.get(GCE_METADATA_URL_ENV)
            or os.environ.get(GCE_POLL_ENV) == "1"
        )
        self._poll_interval_s = poll_interval_s

        self._lock = threading.Lock()
        self._notice: Optional[DrainNotice] = None
        self._fired = threading.Event()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._prev_sigterm = None
        self._started = False

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "DrainWatcher":
        if self._started:
            return self
        self._started = True
        if self._group_id is None:
            self._group_id = os.environ.get("REPLICA_GROUP_ID", "0")
        if self._sigterm:
            self._install_sigterm()
        if self._drain_dir:
            t = threading.Thread(
                target=self._file_loop, name="tpuft_drain_file", daemon=True
            )
            t.start()
            self._threads.append(t)
        if self._gce_enabled:
            t = threading.Thread(
                target=self._gce_loop, name="tpuft_drain_gce", daemon=True
            )
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = []
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass
            self._prev_sigterm = None

    # -- notice state -------------------------------------------------------

    @property
    def notice(self) -> Optional[DrainNotice]:
        return self._notice

    def drain_requested(self) -> bool:
        return self._fired.is_set()

    def wait(self, timeout: Optional[float] = None) -> Optional[DrainNotice]:
        """Blocks until a notice arrives (or timeout); returns it."""
        self._fired.wait(timeout)
        return self._notice

    def trigger(self, source: str = "manual", grace_s: Optional[float] = None) -> None:
        """Explicit (CLI/programmatic) drain trigger."""
        self._fire(
            DrainNotice(
                source=source,
                deadline=time.time() + (grace_s if grace_s is not None else self._grace_s),
            )
        )

    def _fire(self, notice: DrainNotice) -> None:
        with self._lock:
            if self._notice is not None:
                return  # first notice wins; a drain is not retractable
            self._notice = notice
        self._fired.set()
        logger.warning(
            "drain notice: source=%s deadline in %.1fs",
            notice.source, notice.remaining_s(),
        )
        if self._on_notice is not None:
            try:
                self._on_notice(notice)
            except Exception:  # noqa: BLE001 — a notice must never kill its source thread
                logger.exception("drain on_notice callback failed")

    # -- sources ------------------------------------------------------------

    def _install_sigterm(self) -> None:
        def handler(signum, frame):
            # _fire runs on a FRESH thread, never in the handler itself: a
            # signal handler executes on the main thread between bytecodes,
            # and the main thread may be holding non-reentrant locks the
            # notice path needs (MetricsLogger._lock during a commit emit,
            # this watcher's own _lock) — firing inline would deadlock the
            # very step the drain wants to finish.
            notice = DrainNotice(
                source="sigterm", deadline=time.time() + self._grace_s
            )
            threading.Thread(
                target=self._fire, args=(notice,),
                name="tpuft_drain_sigterm", daemon=True,
            ).start()
            prev = self._prev_sigterm
            if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
                prev(signum, frame)

        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM, handler)
        except ValueError:
            # Not the main thread: the orchestrator-facing channel degrades
            # to the file/metadata pollers.
            self._prev_sigterm = None
            logger.debug("not main thread; SIGTERM drain hook not installed")

    def notice_file_path(self) -> Optional[str]:
        if not self._drain_dir:
            return None
        return os.path.join(self._drain_dir, f"drain_{self._group_id}.json")

    def _file_loop(self) -> None:
        path = self.notice_file_path()
        junk_ticks = 0
        while path and not self._stop.is_set() and not self._fired.is_set():
            if os.path.exists(path):
                grace = self._grace_s
                source = "file"
                pid = None
                try:
                    with open(path, "r", encoding="utf-8") as f:
                        data = json.load(f)
                    grace = float(data.get("deadline_ms", grace * 1000)) / 1000.0
                    source = str(data.get("source", source))
                    pid = int(data["pid"]) if data.get("pid") is not None else None
                    junk_ticks = 0
                except (OSError, ValueError, TypeError, AttributeError):
                    # A bare `touch`, non-dict JSON, or junk fields: still a
                    # valid (unpinned) trigger — and never a reason to kill
                    # this poller thread.  But a supervisor writing the file
                    # non-atomically looks identical mid-write (empty or
                    # truncated JSON), so give it one poll tick to finish
                    # before consuming it as a touch-trigger — otherwise the
                    # notice fires without its deadline/source/pid payload.
                    junk_ticks += 1
                    if junk_ticks < 2:
                        self._stop.wait(self._poll_interval_s)
                        continue
                if pid is not None and pid != os.getpid():
                    # A notice addressed to the donor, observed by its
                    # replacement (same group id, same file name): not
                    # ours — keep watching.  The addressee (or the
                    # supervisor at reap time) deletes the file.
                    self._stop.wait(self._poll_interval_s)
                    continue
                if pid is None and os.environ.get("TPUFT_DRAIN_SUPERVISED") == "1":
                    # Under a supervising launcher, a pid-less file is an
                    # OPERATOR request addressed to the supervisor, which
                    # re-issues it pid-pinned after pre-warming the
                    # replacement; consuming it here would exit with
                    # nobody taking over.
                    self._stop.wait(self._poll_interval_s)
                    continue
                try:
                    # Consume the notice so a later incarnation of this
                    # group cannot replay it.
                    os.remove(path)
                except OSError:
                    pass
                self._fire(
                    DrainNotice(source=source, deadline=time.time() + grace)
                )
                return
            # File absent: any mid-write grace state is stale (the writer
            # aborted and removed it) — a future notice gets a fresh tick.
            junk_ticks = 0
            self._stop.wait(self._poll_interval_s)

    def _gce_fetch(self, endpoint: str) -> Optional[str]:
        import urllib.request

        base = self._gce_url or _GCE_DEFAULT_URL
        req = urllib.request.Request(
            f"{base}/{endpoint}", headers={"Metadata-Flavor": "Google"}
        )
        try:
            with urllib.request.urlopen(req, timeout=2.0) as resp:
                return resp.read().decode("utf-8", "replace").strip()
        except Exception:  # noqa: BLE001 — metadata server absent/slow is normal
            return None

    def _gce_loop(self) -> None:
        # The real metadata server supports hanging GETs (wait_for_change);
        # plain polling keeps the stub servers tests use trivial and is
        # plenty for a 30 s notice.
        interval = max(self._poll_interval_s, 0.25)
        while not self._stop.is_set() and not self._fired.is_set():
            preempted = self._gce_fetch("preempted")
            if preempted and preempted.upper() == "TRUE":
                # The ACTIVE spot notice: ~30 s until the VM is gone.
                self._fire(
                    DrainNotice(
                        source="gce-preemption", deadline=time.time() + 30.0
                    )
                )
                return
            event = self._gce_fetch("maintenance-event")
            if event and event.upper() not in ("", "NONE"):
                self._fire(
                    DrainNotice(
                        source="gce-maintenance",
                        deadline=time.time() + self._grace_s,
                    )
                )
                return
            self._stop.wait(interval)
