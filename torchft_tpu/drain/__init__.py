"""Cooperative drain: preemption-aware graceful handoff.

torchft's fault model treats every departure as a crash discovered via
heartbeat timeout, but on TPU fleets the majority of departures are
ANNOUNCED in advance: GCE maintenance events, spot/preemptible 30 s
notices, Kubernetes SIGTERM + grace period.  This subsystem turns those
notices into a zero-dead-time handoff instead of a post-mortem:

  1. :class:`DrainWatcher` multiplexes the signal sources — SIGTERM, the
     GCE metadata server's maintenance/preemption endpoints, and an
     explicit file/programmatic trigger — into one "drain notice with
     deadline" event.
  2. The notice reaches the :class:`~torchft_tpu.manager.Manager`
     (``begin_drain``): it tells the Lighthouse immediately over the
     ``Drain`` wire method (docs/wire.md, method 5) so the NEXT quorum
     excludes the departing group with no join/heartbeat-timeout wait,
     then finishes the in-flight step, votes commit, and exits cleanly
     (``complete_drain``).
  3. The supervisor (``torchft_tpu.launch.Launcher.drain``) pre-warms a
     spare the moment the notice arrives and hands it the departing
     group's id, so the replacement's init overlaps the donor's last step
     and it heals live through the existing checkpoint transports.

Observability: ``drain_notice`` / ``drain_handoff`` / ``drain_complete``
events in the metrics stream (torchft_tpu/metrics.py);
``bench.py --scenario drain`` measures the drain-path dead time next to
the SIGKILL numbers.
"""

from torchft_tpu.drain.watcher import (
    DRAIN_DIR_ENV,
    DRAIN_GRACE_ENV,
    GCE_METADATA_URL_ENV,
    GCE_POLL_ENV,
    DrainNotice,
    DrainWatcher,
)

__all__ = [
    "DRAIN_DIR_ENV",
    "DRAIN_GRACE_ENV",
    "GCE_METADATA_URL_ENV",
    "GCE_POLL_ENV",
    "DrainNotice",
    "DrainWatcher",
]
