"""ctypes bindings to the native coordination core (libtpuft.so).

The role of the reference's PyO3 binding layer (reference: src/lib.rs:710-726):
exposes ``LighthouseServer``, ``LighthouseClient``, ``ManagerServer``,
``ManagerClient``, ``QuorumResult`` plus tpu-ft's native ``StoreServer`` /
``StoreClient`` to Python.  Requests and responses cross the C ABI as
serialized protobuf bytes built/parsed with the generated ``tpuft_pb2``
module; ctypes drops the GIL for the duration of every native call, matching
the reference's ``py.allow_threads`` usage (src/lib.rs:186-200).

gRPC-style status codes CANCELLED/DEADLINE_EXCEEDED map to ``TimeoutError``
and everything else to ``RuntimeError`` (reference: StatusError mapping,
src/lib.rs:644-668).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from dataclasses import dataclass, field
from typing import List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LIB_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_lib", "libtpuft.so")
_BUILD_LOCK = threading.Lock()

# Wire status codes (native/src/wire.h).
_OK = 0
_CANCELLED = 1
_DEADLINE_EXCEEDED = 4

# Method ids (native/src/wire.h).
LIGHTHOUSE_QUORUM = 1
LIGHTHOUSE_HEARTBEAT = 2
LIGHTHOUSE_STATUS = 3
LIGHTHOUSE_EVICT = 4
LIGHTHOUSE_DRAIN = 5
LIGHTHOUSE_REPLICATE = 6
LIGHTHOUSE_LEADER_INFO = 7
LIGHTHOUSE_REGION_DIGEST = 8
LIGHTHOUSE_REGIONS = 9
MANAGER_QUORUM = 10
MANAGER_CHECKPOINT_METADATA = 11
MANAGER_SHOULD_COMMIT = 12
MANAGER_KILL = 13
STORE_SET = 20
STORE_GET = 21
STORE_ADD = 22
STORE_DELETE = 23


# The plain-g++ source set (native/gen_pb_local.py's docstring recipe);
# tests/test_native_core.py builds its test binary from the same list, so
# the two recipes cannot drift.
NATIVE_SOURCES = (
    "wire.cc",
    "http.cc",
    "flight.cc",
    "lighthouse.cc",
    "manager.cc",
    "store.cc",
    "ring.cc",
    "capi.cc",
)


def _build_native_gxx() -> None:
    """Toolchain-less fallback: gen_pb_local.py + plain g++ -shared (the
    recipe native/gen_pb_local.py documents).  Used when cmake/ninja are
    absent but g++ exists — the shape of the container this repo's CI
    runs in."""
    import sys

    native_dir = os.path.join(_REPO_ROOT, "native")
    subprocess.run(
        [sys.executable, os.path.join(native_dir, "gen_pb_local.py")],
        check=True,
        capture_output=True,
        timeout=120,
    )
    os.makedirs(os.path.dirname(_LIB_PATH), exist_ok=True)
    srcs = [os.path.join(native_dir, "src", f) for f in NATIVE_SOURCES]
    subprocess.run(
        # -O3, not -O2: GCC 10 only auto-vectorizes at -O3, and the ring
        # engine's f32 combine + wire-codec loops are the data plane's
        # arithmetic hot path.
        ["g++", "-std=c++17", "-O3", "-fPIC", "-shared",
         "-I", os.path.join(native_dir, "src"), "-I", "/tmp/tpuftpb",
         *srcs, "-o", _LIB_PATH, "-lpthread"],
        check=True,
        capture_output=True,
        timeout=600,
    )


def _build_native() -> None:
    """Builds libtpuft.so and the generated protobuf modules via cmake/ninja,
    falling back to the gen_pb_local.py + g++ recipe on toolchain-less
    containers."""
    import shutil

    if shutil.which("cmake") is None or shutil.which("ninja") is None:
        _build_native_gxx()
        return
    native_dir = os.path.join(_REPO_ROOT, "native")
    build_dir = os.path.join(native_dir, "build")
    subprocess.run(
        ["cmake", "-B", build_dir, "-G", "Ninja", native_dir],
        check=True,
        capture_output=True,
    )
    # Default target set (not just tpuft+py_proto): ALL includes tpuft_test,
    # so an out-of-the-box `ctest --test-dir native/build` passes with no
    # manual target — round 3 shipped a build dir where it reported Not Run.
    subprocess.run(["ninja", "-C", build_dir], check=True, capture_output=True)


def _ensure_built() -> None:
    pb2 = os.path.join(os.path.dirname(os.path.abspath(__file__)), "proto", "tpuft_pb2.py")
    if os.path.exists(_LIB_PATH) and os.path.exists(pb2):
        return
    with _BUILD_LOCK:
        if os.path.exists(_LIB_PATH) and os.path.exists(pb2):
            return
        _build_native()


_ensure_built()

from torchft_tpu.proto import tpuft_pb2 as pb  # noqa: E402


def _load_lib() -> ctypes.CDLL:
    lib = ctypes.CDLL(_LIB_PATH)
    lib.tf_free.argtypes = [ctypes.c_void_p]
    lib.tf_lighthouse_new.restype = ctypes.c_void_p
    lib.tf_lighthouse_new.argtypes = [
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_char_p),
    ]
    lib.tf_lighthouse_address.restype = ctypes.c_void_p
    lib.tf_lighthouse_address.argtypes = [ctypes.c_void_p]
    lib.tf_lighthouse_http_address.restype = ctypes.c_void_p
    lib.tf_lighthouse_http_address.argtypes = [ctypes.c_void_p]
    lib.tf_lighthouse_evict.restype = ctypes.c_int
    lib.tf_lighthouse_evict.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tf_lighthouse_drain.restype = ctypes.c_int
    lib.tf_lighthouse_drain.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    lib.tf_lighthouse_set_role.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_int64,
    ]
    lib.tf_lighthouse_role.restype = ctypes.c_int
    lib.tf_lighthouse_role.argtypes = [ctypes.c_void_p]
    lib.tf_lighthouse_leader_epoch.restype = ctypes.c_int64
    lib.tf_lighthouse_leader_epoch.argtypes = [ctypes.c_void_p]
    lib.tf_lighthouse_snapshot.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.tf_lighthouse_link_state.restype = ctypes.c_int
    lib.tf_lighthouse_link_state.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tf_lighthouse_flight_json.restype = ctypes.c_void_p
    lib.tf_lighthouse_flight_json.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    try:
        # Federation surface (docs/wire.md "Federation").  Declared inside a
        # probe: a stale .so without the symbols predates the two-tier
        # topology — LighthouseServer.set_federation raises a clear error
        # and regions_json degrades to an empty rollup.
        lib.tf_lighthouse_set_federation.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_char_p,
            ctypes.c_int64,
        ]
        lib.tf_lighthouse_regions_json.restype = ctypes.c_void_p
        lib.tf_lighthouse_regions_json.argtypes = [ctypes.c_void_p]
    except AttributeError:
        pass
    lib.tf_lighthouse_shutdown.argtypes = [ctypes.c_void_p]
    lib.tf_lighthouse_free.argtypes = [ctypes.c_void_p]
    lib.tf_manager_new.restype = ctypes.c_void_p
    lib.tf_manager_new.argtypes = [
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_char_p),
    ]
    lib.tf_manager_address.restype = ctypes.c_void_p
    lib.tf_manager_address.argtypes = [ctypes.c_void_p]
    lib.tf_manager_set_status.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_char_p,
        ctypes.c_double,
        ctypes.c_double,
        ctypes.c_double,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_double,
        ctypes.c_double,
        ctypes.c_double,
    ]
    lib.tf_manager_flight_json.restype = ctypes.c_void_p
    lib.tf_manager_flight_json.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    try:
        # Goodput-ledger push (heartbeat fields 14-16).  Declared inside a
        # probe: a stale .so without the symbol degrades to status-only
        # heartbeats (ManagerServer.set_ledger becomes a no-op) instead of
        # failing the module import.
        lib.tf_manager_set_ledger.argtypes = [
            ctypes.c_void_p,
            ctypes.c_double,
            ctypes.c_double,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int32,
        ]
    except AttributeError:
        pass
    lib.tf_manager_shutdown.argtypes = [ctypes.c_void_p]
    lib.tf_manager_free.argtypes = [ctypes.c_void_p]
    lib.tf_store_new.restype = ctypes.c_void_p
    lib.tf_store_new.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_char_p)]
    lib.tf_store_address.restype = ctypes.c_void_p
    lib.tf_store_address.argtypes = [ctypes.c_void_p]
    lib.tf_store_shutdown.argtypes = [ctypes.c_void_p]
    lib.tf_store_free.argtypes = [ctypes.c_void_p]
    lib.tf_client_new.restype = ctypes.c_void_p
    lib.tf_client_new.argtypes = [
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_char_p),
    ]
    lib.tf_client_call.restype = ctypes.c_int
    lib.tf_client_call.argtypes = [
        ctypes.c_void_p,
        ctypes.c_uint16,
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_size_t),
        ctypes.POINTER(ctypes.c_char_p),
    ]
    lib.tf_client_free.argtypes = [ctypes.c_void_p]
    return lib


_lib = _load_lib()


def _bind_ring(lib: ctypes.CDLL) -> Optional[str]:
    """Declares the tf_ring_* signatures; returns a human-readable reason
    when the loaded libtpuft.so predates the ring engine (stale build) —
    the capability probe TCPCollective's engine selection reads."""
    try:
        lib.tf_ring_new.restype = ctypes.c_void_p
        lib.tf_ring_new.argtypes = [ctypes.c_int32, ctypes.c_double, ctypes.c_double]
        lib.tf_ring_set_tier.restype = ctypes.c_int
        lib.tf_ring_set_tier.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.tf_ring_close.argtypes = [ctypes.c_void_p]
        lib.tf_ring_free.argtypes = [ctypes.c_void_p]
        lib.tf_ring_detach.restype = ctypes.c_int
        lib.tf_ring_detach.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.tf_ring_open_fds.restype = ctypes.c_int
        lib.tf_ring_open_fds.argtypes = [ctypes.c_void_p]
        lib.tf_ring_exchange.restype = ctypes.c_int
        lib.tf_ring_exchange.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_uint32,
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t),
            ctypes.c_double,
            ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.tf_ring_pass.restype = ctypes.c_int
        lib.tf_ring_pass.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_uint32,
            ctypes.c_uint32,
            ctypes.c_uint32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_double,
            ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.tf_ring_pass_multi.restype = ctypes.c_int
        lib.tf_ring_pass_multi.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint32,
            ctypes.c_uint32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_double,
            ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.tf_ring_set_shm.restype = ctypes.c_int
        lib.tf_ring_set_shm.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_char_p),
        ]
        lib.tf_ring_counters.restype = ctypes.c_int
        lib.tf_ring_counters.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int32,
        ]
        lib.tf_ring_shaper_counters.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.tf_ring_link_bytes.restype = ctypes.c_uint64
        lib.tf_ring_link_bytes.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
        ]
        # Data-plane flight recorder (hop telemetry, PR 14).  Declared with
        # the base ring symbols: a .so that has tf_ring_new but not these
        # is a stale build, and a silent half-capability engine would
        # break the cross-engine telemetry-parity contract.
        lib.tf_ring_set_hop.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
        ]
        lib.tf_ring_hop_stats.restype = ctypes.c_int
        lib.tf_ring_hop_stats.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.tf_ring_hop_records.restype = ctypes.c_int
        lib.tf_ring_hop_records.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int32,
        ]
        lib.tf_ring_shaper_wait_s.restype = ctypes.c_double
        lib.tf_ring_shaper_wait_s.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
        ]
        lib.tf_ring_set_shaper.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_double,
            ctypes.c_double,
        ]
    except AttributeError:
        return (
            f"libtpuft.so at {_LIB_PATH} lacks the ring-engine symbols "
            "(stale build predating native/src/ring.cc) — rebuild it: "
            "python native/gen_pb_local.py && the g++ recipe in that "
            "file's docstring (or cmake/ninja)"
        )
    return None


_RING_UNAVAILABLE: Optional[str] = _bind_ring(_lib)


def ring_engine_available() -> bool:
    """True when the loaded native library exports the GIL-free ring
    engine (tf_ring_*).  False means a stale libtpuft.so; see
    :func:`ring_engine_unavailable_reason`."""
    return _RING_UNAVAILABLE is None


def ring_engine_unavailable_reason() -> str:
    return _RING_UNAVAILABLE or ""


def _take_string(ptr: int) -> str:
    if not ptr:
        return ""
    value = ctypes.string_at(ptr).decode()
    _lib.tf_free(ptr)
    return value


def _take_error(err: "ctypes.c_char_p") -> str:
    if not err.value:
        return "unknown native error"
    msg = err.value.decode()
    _lib.tf_free(ctypes.cast(err, ctypes.c_void_p))
    return msg


def _raise_for_status(status: int, msg: str) -> None:
    exc: Exception
    if status in (_CANCELLED, _DEADLINE_EXCEEDED):
        exc = TimeoutError(msg)
    else:
        exc = RuntimeError(msg)
    # The wire status rides on the exception so failover-aware callers can
    # distinguish UNAVAILABLE (retry elsewhere) from application errors
    # like ABORTED "is draining" (final).
    exc.wire_status = status  # type: ignore[attr-defined]
    raise exc


# Wire status UNAVAILABLE (native/src/wire.h): transport failure or an HA
# standby's "not the leader" rejection — the two conditions a multi-address
# client fails over on.
_UNAVAILABLE = 14

# The HA standby-rejection contract (native/src/wire.h kNotLeaderPrefix):
# "not the leader; leader=<rpc_addr> http=<http_addr> epoch=<N>".
NOT_LEADER_PREFIX = "not the leader"


def parse_not_leader(msg: str) -> Optional[str]:
    """Returns the leader RPC address named by a standby rejection, ""
    when the standby knows no leader yet, or None when ``msg`` is not a
    not-leader rejection at all."""
    if not msg.startswith(NOT_LEADER_PREFIX):
        return None
    import re

    m = re.search(r"leader=(\S*)", msg)
    return m.group(1) if m else ""


class _Client:
    """Generic RPC client over the native connection (connect w/ retry+backoff,
    reference: src/net.rs:22-34)."""

    def __init__(self, addr: str, connect_timeout_ms: int = 10000) -> None:
        err = ctypes.c_char_p()
        self._ptr = _lib.tf_client_new(addr.encode(), connect_timeout_ms, ctypes.byref(err))
        if not self._ptr:
            raise TimeoutError(_take_error(err))
        self._addr = addr

    def call(self, method: int, request: bytes, timeout_ms: int) -> bytes:
        resp = ctypes.POINTER(ctypes.c_uint8)()
        resp_len = ctypes.c_size_t()
        err = ctypes.c_char_p()
        status = _lib.tf_client_call(
            self._ptr,
            method,
            request,
            len(request),
            max(0, int(timeout_ms)),
            ctypes.byref(resp),
            ctypes.byref(resp_len),
            ctypes.byref(err),
        )
        if status != _OK:
            _raise_for_status(status, _take_error(err))
        data = ctypes.string_at(resp, resp_len.value)
        _lib.tf_free(ctypes.cast(resp, ctypes.c_void_p))
        return data

    def close(self) -> None:
        if self._ptr:
            _lib.tf_client_free(self._ptr)
            self._ptr = None

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass


@dataclass
class QuorumResult:
    """Per-rank recovery plan returned by ``ManagerClient._quorum``.
    Reference parity: QuorumResult pyclass, src/lib.rs:275-308."""

    quorum_id: int = 0
    replica_rank: int = 0
    replica_world_size: int = 1
    recover_src_manager_address: str = ""
    recover_src_replica_rank: Optional[int] = None
    # PRIMARY-assignment destinations (what a point-to-point transport must
    # serve — its sends block until matched).
    recover_dst_replica_ranks: List[int] = field(default_factory=list)
    # Full recovering set (what a pull-based transport serves: every donor
    # opens its window for striped fetches).  Falls back to the primary
    # list against pre-multi-donor servers.
    recover_dst_replica_ranks_all: List[int] = field(default_factory=list)
    # Striped multi-donor healing (valid when heal): the full ordered donor
    # rotation — every healthy max-step group, primary first.  Falls back to
    # the singleton [recover_src_*] against pre-multi-donor servers so the
    # healing path can always iterate these.
    recover_src_replica_ranks: List[int] = field(default_factory=list)
    recover_src_manager_addresses: List[str] = field(default_factory=list)
    # Full sorted participant membership (fields 15-16; ALWAYS filled by
    # servers of this generation): shard holders for the erasure-coded
    # recovery fallback are any live participant, not just max-step donors.
    # Empty against pre-EC servers (the EC plane then keeps its last view).
    participant_replica_ranks: List[int] = field(default_factory=list)
    participant_manager_addresses: List[str] = field(default_factory=list)
    store_address: str = ""
    max_step: int = 0
    max_replica_rank: Optional[int] = None
    max_world_size: int = 1
    heal: bool = False


class LighthouseServer:
    """In-process native Lighthouse (reference: LighthouseServer, src/lib.rs:580-642)."""

    def __init__(
        self,
        bind: str = "[::]:0",
        min_replicas: int = 1,
        join_timeout_ms: int = 100,
        quorum_tick_ms: int = 100,
        heartbeat_timeout_ms: int = 5000,
        http_bind: str = "[::]:0",
    ) -> None:
        err = ctypes.c_char_p()
        self._ptr = _lib.tf_lighthouse_new(
            bind.encode(),
            http_bind.encode(),
            min_replicas,
            join_timeout_ms,
            quorum_tick_ms,
            heartbeat_timeout_ms,
            ctypes.byref(err),
        )
        if not self._ptr:
            raise RuntimeError(_take_error(err))

    def address(self) -> str:
        return _take_string(_lib.tf_lighthouse_address(self._ptr))

    def http_address(self) -> str:
        return _take_string(_lib.tf_lighthouse_http_address(self._ptr))

    def evict(self, replica_prefix: str) -> int:
        """Supervisor-assisted failure notification: drop the heartbeat and
        pending join of every replica id matching ``replica_prefix`` (a full
        id or a "<group>" family whose ids are "<group>:<uuid>").  The next
        quorum round then forms without spending join_timeout waiting for a
        process the supervisor already knows is dead.  Returns the number of
        ids dropped."""
        return int(_lib.tf_lighthouse_evict(self._ptr, replica_prefix.encode()))

    def drain(self, replica_prefix: str, deadline_ms: int = 0) -> int:
        """Cooperative drain: mark every replica id matching
        ``replica_prefix`` (full id or "<group>" uuid family) as a PLANNED
        departure — excluded from the next quorum immediately (no
        join/heartbeat-timeout wait) while its in-flight step finishes
        undisturbed, and tombstoned against late re-joins.  The replacement
        incarnation (fresh ":uuid" suffix) is admitted normally.
        ``deadline_ms`` is the advisory preemption deadline.  Returns the
        number of ids marked."""
        return int(
            _lib.tf_lighthouse_drain(self._ptr, replica_prefix.encode(), int(deadline_ms))
        )

    def set_role(
        self,
        leader: bool,
        leader_address: str = "",
        leader_http_address: str = "",
        epoch: int = 0,
        lease_expires_ms: int = 0,
    ) -> None:
        """HA role control (docs/wire.md "HA lighthouse").  A standalone
        lighthouse is a permanent leader; under the lease-based election
        (:mod:`torchft_tpu.ha`) the driver flips the role here on every
        lease transition.  As leader, ``lease_expires_ms`` (epoch ms) is
        the serve-time guard — once it passes without a renewed SetRole,
        Quorum/Heartbeat are refused so an expired-lease leader can never
        split-brain with the lease's next winner.  As follower, the
        leader_* fields are what the redirect rejections and HTTP 307s
        point clients at."""
        if self._ptr:
            _lib.tf_lighthouse_set_role(
                self._ptr,
                1 if leader else 0,
                leader_address.encode(),
                leader_http_address.encode(),
                int(epoch),
                int(lease_expires_ms),
            )

    def role(self) -> int:
        """1 = leader with a live lease, 0 = follower (or lapsed lease)."""
        return int(_lib.tf_lighthouse_role(self._ptr)) if self._ptr else 0

    def leader_epoch(self) -> int:
        return int(_lib.tf_lighthouse_leader_epoch(self._ptr)) if self._ptr else 0

    def set_federation(
        self, region: str, root_addrs: str, push_interval_ms: int = 500
    ) -> None:
        """Join a two-tier federation as the CHILD lighthouse for
        ``region`` (docs/wire.md "Federation").  This instance keeps
        owning heartbeats, sentinels, and the goodput ledger for its
        local replica groups, but stops forming quorums itself: a
        background loop pushes a membership + ledger digest to the ROOT
        at ``root_addrs`` (comma-separated, leader + standbys) every
        ``push_interval_ms`` and installs the global quorum the root
        returns.  Call after the server is up; the root needs no
        configuration — any lighthouse that receives digests serves as
        root.  Flat (non-federated) deployments never call this and
        behave exactly as before."""
        if not self._ptr:
            return
        if not hasattr(_lib, "tf_lighthouse_set_federation"):
            raise RuntimeError(
                "libtpuft.so predates the federation surface "
                "(tf_lighthouse_set_federation missing) — rebuild native/"
            )
        _lib.tf_lighthouse_set_federation(
            self._ptr, region.encode(), root_addrs.encode(), int(push_interval_ms)
        )

    def regions_json(self) -> str:
        """Federation rollup as a JSON document string — same payload as
        this lighthouse's ``GET /regions.json`` (docs/wire.md
        "Federation"): ``{"role", "region", "regions": [...]}`` where
        role is "root"/"child"/"flat".  A root lists one row per region
        with digest freshness and ledger rollups; a child lists its own
        region; a flat instance lists nothing."""
        if not self._ptr or not hasattr(_lib, "tf_lighthouse_regions_json"):
            return '{"role":"flat","region":"","regions":[]}'
        return _take_string(_lib.tf_lighthouse_regions_json(self._ptr))

    def regions(self) -> dict:
        """Parsed :meth:`regions_json`."""
        import json

        return json.loads(self.regions_json() or "{}")

    def flight_json(self, limit: int = 0) -> str:
        """Flight-recorder snapshot as a JSON document string (newest-first
        events; ``limit`` 0 = all retained).  Same payload as this
        lighthouse's ``GET /debug/flight.json`` (docs/wire.md "Flight
        recorder")."""
        if not self._ptr:
            return "{}"
        return _take_string(_lib.tf_lighthouse_flight_json(self._ptr, int(limit)))

    def flight(self, limit: int = 0) -> dict:
        """Parsed :meth:`flight_json` — ``{"server", "id", "capacity",
        "recorded", "dropped", "events": [...]}`` with events newest-first.
        Use :mod:`torchft_tpu.obs.flight` to reconstruct quorum-transition
        sequences or merge into a Perfetto trace."""
        import json

        return json.loads(self.flight_json(limit) or "{}")

    def link_state(self, replica_id: str) -> int:
        """Slow-link sentinel state of the replica's OUTBOUND edge (0
        healthy, 1 suspect, 2 degraded) — in-process introspection for
        tests; the wire surfaces are /metrics and /alerts.json."""
        if not self._ptr:
            return 0
        return int(_lib.tf_lighthouse_link_state(self._ptr, replica_id.encode()))

    def snapshot(self) -> bytes:
        """Serialized ``LighthouseReplicateRequest`` of the full replicable
        state (membership, live step/state, straggler-sentinel health,
        link-health, alerts, previous quorum + id) — what the HA election
        driver pushes to each standby over wire method 6."""
        if not self._ptr:
            return b""
        buf = ctypes.POINTER(ctypes.c_uint8)()
        length = ctypes.c_size_t()
        _lib.tf_lighthouse_snapshot(self._ptr, ctypes.byref(buf), ctypes.byref(length))
        data = ctypes.string_at(buf, length.value)
        _lib.tf_free(ctypes.cast(buf, ctypes.c_void_p))
        return data

    def shutdown(self) -> None:
        if self._ptr:
            _lib.tf_lighthouse_shutdown(self._ptr)

    def __del__(self) -> None:
        try:
            if self._ptr:
                _lib.tf_lighthouse_shutdown(self._ptr)
                _lib.tf_lighthouse_free(self._ptr)
                self._ptr = None
        except Exception:
            pass


class LighthouseClient:
    """Direct lighthouse access for tooling and LocalSGD-style algorithms
    (reference: LighthouseClient, src/lib.rs:475-565).

    ``addr`` may be a single ``host:port`` or a comma-separated list (an HA
    lighthouse replica set, docs/wire.md "HA lighthouse"): every call fails
    over across the list with decorrelated-jitter backoff, follows a
    standby's "not the leader; leader=<addr>" redirect straight to the
    leader, and raises a clean, actionable error naming every address when
    none is reachable within the connect timeout."""

    def __init__(self, addr: str, connect_timeout_ms: int = 10000) -> None:
        self._addrs = [a.strip() for a in addr.split(",") if a.strip()]
        if not self._addrs:
            raise ValueError("empty lighthouse address")
        self._connect_timeout_ms = connect_timeout_ms
        self._cur = 0
        self._leader_override: Optional[str] = None
        self._clients: dict = {}

    def _client_for(self, addr: str, budget_ms: int) -> _Client:
        client = self._clients.get(addr)
        if client is None:
            # Short per-attempt connect budget so one dead address cannot
            # eat the whole failover window before its siblings are tried.
            per = min(2000, max(250, budget_ms))
            client = _Client(addr, connect_timeout_ms=per)
            self._clients[addr] = client
        return client

    def _call_failover(self, method: int, payload: bytes, timeout_ms: int) -> bytes:
        """One logical RPC against the replica set: try the current (or
        redirect-learned leader) address; on UNAVAILABLE or a connect
        failure rotate/follow with decorrelated-jitter backoff until
        ``timeout_ms`` elapses.  Application-level errors (ABORTED "is
        draining", NOT_FOUND, server-side DEADLINE_EXCEEDED) are final."""
        import time as _time

        from torchft_tpu.ha.backoff import DecorrelatedBackoff

        deadline = _time.monotonic() + max(0.05, timeout_ms / 1e3)
        # Cap under a lease period (mirrors FailoverRpcClient): mid-election
        # every address rejects, and the sleep — not the rejections — would
        # otherwise become the failover latency floor.
        backoff = DecorrelatedBackoff(base_s=0.05, cap_s=0.5)
        last_exc: Optional[Exception] = None
        first = True
        while first or _time.monotonic() < deadline:
            first = False
            left_ms = max(250, int((deadline - _time.monotonic()) * 1e3))
            addr = self._leader_override or self._addrs[self._cur % len(self._addrs)]
            try:
                client = self._client_for(addr, min(self._connect_timeout_ms, left_ms))
                return client.call(method, payload, min(timeout_ms, left_ms))
            except TimeoutError as e:
                if getattr(e, "wire_status", None) is not None:
                    raise  # DEADLINE_EXCEEDED from a live server: final
                last_exc = e  # connect failure: rotate below
            except RuntimeError as e:
                if getattr(e, "wire_status", None) != _UNAVAILABLE:
                    raise  # application error (e.g. "is draining"): final
                last_exc = e
                leader = parse_not_leader(str(e))
                if leader and leader != addr:
                    # Redirect: jump straight to the named leader; the
                    # rejection proves the service is up, skip the backoff.
                    self._leader_override = leader
                    continue
            # Transport failure or a standby that knows no leader: drop a
            # learned leader (it may have just died) else rotate.
            if self._leader_override is not None:
                self._leader_override = None
            else:
                self._cur = (self._cur + 1) % len(self._addrs)
            sleep_s = backoff.next()
            if _time.monotonic() + sleep_s >= deadline:
                break
            _time.sleep(sleep_s)
        raise TimeoutError(
            "no lighthouse answered at any of ["
            + ", ".join(self._addrs)
            + f"] within {timeout_ms} ms — check TPUFT_LIGHTHOUSE and that "
            f"the lighthouse processes are running (last error: {last_exc})"
        )

    def quorum(
        self,
        replica_id: str,
        timeout_ms: int = 5000,
        address: str = "",
        store_address: str = "",
        step: int = 0,
        world_size: int = 1,
        shrink_only: bool = False,
        data: Optional[dict] = None,
        trace_id: str = "",
    ) -> "pb.Quorum":
        import json

        req = pb.LighthouseQuorumRequest()
        req.trace_id = trace_id
        m = req.requester
        m.replica_id = replica_id
        m.address = address
        m.store_address = store_address
        m.step = step
        m.world_size = world_size
        m.shrink_only = shrink_only
        if data is not None:
            m.data = json.dumps(data)
        resp = pb.LighthouseQuorumResponse()
        resp.ParseFromString(
            self._call_failover(LIGHTHOUSE_QUORUM, req.SerializeToString(), timeout_ms)
        )
        return resp.quorum

    def heartbeat(
        self,
        replica_id: str,
        timeout_ms: int = 5000,
        step: int = 0,
        state: str = "",
        step_time_ms_ewma: float = 0.0,
        step_time_ms_last: float = 0.0,
        trace_id: str = "",
        link_recv_gbps: float = 0.0,
        link_send_gbps: float = 0.0,
        link_hop_rtt_ms: float = 0.0,
    ) -> None:
        """One heartbeat; ``step``/``state`` feed the lighthouse's live
        per-replica observability (GET /metrics step lag, /status.json) and
        the step-time fields feed its straggler sentinel (fields 4-5,
        docs/wire.md).  ``trace_id`` stamps the causal trace of the step in
        flight (field 7).  The link fields (11-13) feed the slow-link
        sentinel; 0 = not reported."""
        req = pb.LighthouseHeartbeatRequest(
            replica_id=replica_id,
            step=int(step),
            state=state,
            step_time_ms_ewma=float(step_time_ms_ewma),
            step_time_ms_last=float(step_time_ms_last),
            trace_id=trace_id,
            link_recv_gbps=float(link_recv_gbps),
            link_send_gbps=float(link_send_gbps),
            link_hop_rtt_ms=float(link_hop_rtt_ms),
        )
        self._call_failover(LIGHTHOUSE_HEARTBEAT, req.SerializeToString(), timeout_ms)

    def evict(self, replica_prefix: str, timeout_ms: int = 5000) -> int:
        """Supervisor-assisted failure notification over the wire (method 4,
        docs/wire.md): drop + tombstone every replica id matching
        ``replica_prefix`` (full id or "<group>" uuid family) so the next
        quorum forms without waiting on a process the supervisor reaped."""
        req = pb.LighthouseEvictRequest(replica_prefix=replica_prefix)
        resp = pb.LighthouseEvictResponse()
        resp.ParseFromString(
            self._call_failover(LIGHTHOUSE_EVICT, req.SerializeToString(), timeout_ms)
        )
        return int(resp.evicted)

    def drain(
        self,
        replica_prefix: str,
        deadline_ms: int = 0,
        timeout_ms: int = 5000,
        trace_id: str = "",
    ) -> int:
        """Cooperative-drain notice over the wire (method 5, docs/wire.md):
        mark the matching replica ids as departing so the next quorum forms
        without them, while their in-flight step finishes undisturbed.
        This is what a departing Manager sends the moment its DrainWatcher
        fires (SIGTERM / GCE preemption notice / explicit trigger)."""
        req = pb.LighthouseDrainRequest(
            replica_prefix=replica_prefix,
            deadline_ms=int(deadline_ms),
            trace_id=trace_id,
        )
        resp = pb.LighthouseDrainResponse()
        resp.ParseFromString(
            self._call_failover(LIGHTHOUSE_DRAIN, req.SerializeToString(), timeout_ms)
        )
        return int(resp.drained)

    def status(self, timeout_ms: int = 5000) -> "pb.LighthouseStatusResponse":
        resp = pb.LighthouseStatusResponse()
        resp.ParseFromString(
            self._call_failover(LIGHTHOUSE_STATUS, b"", timeout_ms)
        )
        return resp

    def leader(self, timeout_ms: int = 5000) -> "pb.LighthouseLeaderInfoResponse":
        """Leader discovery (wire method 7): who the answering replica
        believes the leader is, plus its own role (1 leader, 0 follower).
        Answered by every replica — followers do not redirect this."""
        resp = pb.LighthouseLeaderInfoResponse()
        resp.ParseFromString(
            self._call_failover(LIGHTHOUSE_LEADER_INFO, b"", timeout_ms)
        )
        return resp

    def replicate(self, snapshot: bytes, timeout_ms: int = 5000) -> "pb.LighthouseReplicateResponse":
        """Pushes a ``LighthouseServer.snapshot()`` to the replica this
        client currently targets (wire method 6).  Used by the HA election
        driver; applied=False means the receiver holds a higher epoch and
        the SENDER should demote itself."""
        resp = pb.LighthouseReplicateResponse()
        resp.ParseFromString(
            self._call_failover(LIGHTHOUSE_REPLICATE, snapshot, timeout_ms)
        )
        return resp

    def close(self) -> None:
        for client in self._clients.values():
            try:
                client.close()
            except Exception:  # noqa: BLE001
                pass
        self._clients.clear()


class ManagerServer:
    """In-process native Manager server, run by the group's rank 0
    (reference: ManagerServer, src/lib.rs:73-135)."""

    def __init__(
        self,
        replica_id: str,
        lighthouse_addr: str,
        bind: str = "[::]:0",
        store_addr: str = "",
        world_size: int = 1,
        heartbeat_interval_ms: int = 100,
        connect_timeout_ms: int = 10000,
    ) -> None:
        err = ctypes.c_char_p()
        self._ptr = _lib.tf_manager_new(
            replica_id.encode(),
            lighthouse_addr.encode(),
            bind.encode(),
            store_addr.encode(),
            world_size,
            heartbeat_interval_ms,
            connect_timeout_ms,
            ctypes.byref(err),
        )
        if not self._ptr:
            raise RuntimeError(_take_error(err))

    def address(self) -> str:
        return _take_string(_lib.tf_manager_address(self._ptr))

    def set_status(
        self,
        step: int,
        state: str,
        step_time_ms_ewma: float = 0.0,
        step_time_ms_last: float = 0.0,
        allreduce_gb_per_s: float = -1.0,
        ec_shards_held: int = -1,
        ec_shard_step: int = -1,
        ec_k: int = -1,
        link_recv_gbps: float = -1.0,
        link_send_gbps: float = -1.0,
        link_hop_rtt_ms: float = -1.0,
    ) -> None:
        """Pushes live (step, state) into the heartbeat payload so the
        lighthouse's ``GET /metrics`` and ``/status.json`` show per-replica
        progress in real time (see docs/wire.md, Heartbeat fields).  The
        optional step-time telemetry (rolling busy-time EWMA + last
        observation, milliseconds) feeds the lighthouse's straggler
        sentinel; 0 keeps the previously pushed values.
        ``allreduce_gb_per_s`` (the last committed step's gradient
        data-plane throughput) feeds its ``tpuft_allreduce_gb_per_s``
        gauge — there 0 is an authoritative reading (a committed step that
        moved no gradient bytes) and only a negative value keeps the prior
        one, so status-only pushes must leave the default.
        ``ec_shards_held``/``ec_shard_step`` (heartbeat fields 8-9, the
        erasure-shard inventory feeding ``tpuft_ec_shard_coverage``)
        follow the same convention: 0 is an authoritative empty-store
        report, negative keeps the prior reading.  ``ec_k`` (field 10) is
        the EC geometry's data-shard count — the lighthouse coverage
        sentinel pages when per-step coverage drops below k + 1.
        The link-health EWMAs (heartbeat fields 11-13, the slow-link
        sentinel's feed) share the gauge convention: 0 is an
        authoritative "no observation" report, negative keeps the prior
        reading."""
        if self._ptr:
            _lib.tf_manager_set_status(
                self._ptr,
                int(step),
                state.encode(),
                float(step_time_ms_ewma),
                float(step_time_ms_last),
                float(allreduce_gb_per_s),
                int(ec_shards_held),
                int(ec_shard_step),
                int(ec_k),
                float(link_recv_gbps),
                float(link_send_gbps),
                float(link_hop_rtt_ms),
            )

    def set_ledger(
        self,
        goodput_ratio: float,
        compute_seconds: float,
        lost_seconds: "list[float]",
    ) -> None:
        """Pushes the goodput ledger's cumulative counters onto heartbeat
        fields 14-16 (docs/wire.md "Goodput ledger"): the replica's
        productive fraction, productive seconds, and per-cause lost
        seconds in the PINNED taxonomy order
        (:data:`torchft_tpu.obs.ledger.LOST_CAUSES`).  Called once per
        commit vote; counters are monotonic per incarnation.  No-op
        against a stale libtpuft.so without the symbol."""
        if not self._ptr or not hasattr(_lib, "tf_manager_set_ledger"):
            return
        arr = (ctypes.c_double * len(lost_seconds))(*lost_seconds)
        _lib.tf_manager_set_ledger(
            self._ptr,
            float(goodput_ratio),
            float(compute_seconds),
            arr,
            len(lost_seconds),
        )

    def flight_json(self, limit: int = 0) -> str:
        """Flight-recorder snapshot (newest-first JSON document; ``limit``
        0 = all retained).  Managers serve no HTTP, so this accessor and
        the ``TPUFT_FLIGHT_DIR`` shutdown dump are the read paths."""
        if not self._ptr:
            return "{}"
        return _take_string(_lib.tf_manager_flight_json(self._ptr, int(limit)))

    def flight(self, limit: int = 0) -> dict:
        """Parsed :meth:`flight_json` (see ``LighthouseServer.flight``)."""
        import json

        return json.loads(self.flight_json(limit) or "{}")

    def shutdown(self) -> None:
        if self._ptr:
            _lib.tf_manager_shutdown(self._ptr)

    def __del__(self) -> None:
        try:
            if self._ptr:
                _lib.tf_manager_shutdown(self._ptr)
                _lib.tf_manager_free(self._ptr)
                self._ptr = None
        except Exception:
            pass


class ManagerClient:
    """Client used by every local rank to talk to its group's Manager
    (reference: ManagerClient, src/lib.rs:144-273)."""

    def __init__(self, addr: str, connect_timeout_ms: int = 10000) -> None:
        self._client = _Client(addr, connect_timeout_ms)

    def _quorum(
        self,
        group_rank: int,
        step: int,
        checkpoint_metadata: str,
        shrink_only: bool,
        timeout_ms: int,
        init_sync: bool = True,
        commit_failures: int = 0,
        trace_id: str = "",
    ) -> QuorumResult:
        req = pb.ManagerQuorumRequest(
            group_rank=group_rank,
            step=step,
            checkpoint_metadata=checkpoint_metadata,
            shrink_only=shrink_only,
            init_sync=init_sync,
            commit_failures=commit_failures,
            trace_id=trace_id,
        )
        resp = pb.ManagerQuorumResponse()
        resp.ParseFromString(
            self._client.call(MANAGER_QUORUM, req.SerializeToString(), timeout_ms)
        )
        donor_ranks = list(resp.recover_src_replica_ranks)
        donor_addrs = list(resp.recover_src_manager_addresses)
        if resp.heal and not donor_addrs and resp.recover_src_manager_address:
            # Pre-multi-donor server: degrade to the single assigned donor.
            donor_ranks = [resp.recover_src_replica_rank]
            donor_addrs = [resp.recover_src_manager_address]
        return QuorumResult(
            quorum_id=resp.quorum_id,
            replica_rank=resp.replica_rank,
            replica_world_size=resp.replica_world_size,
            recover_src_manager_address=resp.recover_src_manager_address,
            recover_src_replica_rank=resp.recover_src_replica_rank if resp.heal else None,
            recover_dst_replica_ranks=list(resp.recover_dst_replica_ranks),
            recover_dst_replica_ranks_all=(
                list(resp.recover_dst_replica_ranks_all)
                or list(resp.recover_dst_replica_ranks)
            ),
            recover_src_replica_ranks=donor_ranks if resp.heal else [],
            recover_src_manager_addresses=donor_addrs if resp.heal else [],
            participant_replica_ranks=list(resp.participant_replica_ranks),
            participant_manager_addresses=list(resp.participant_manager_addresses),
            store_address=resp.store_address,
            max_step=resp.max_step,
            max_replica_rank=resp.max_replica_rank if resp.max_replica_rank >= 0 else None,
            max_world_size=resp.max_world_size,
            heal=resp.heal,
        )

    def _checkpoint_metadata(
        self, rank: int, timeout_ms: int, trace_id: str = ""
    ) -> str:
        req = pb.CheckpointMetadataRequest(group_rank=rank, trace_id=trace_id)
        resp = pb.CheckpointMetadataResponse()
        resp.ParseFromString(
            self._client.call(MANAGER_CHECKPOINT_METADATA, req.SerializeToString(), timeout_ms)
        )
        return resp.checkpoint_metadata

    def should_commit(
        self,
        group_rank: int,
        step: int,
        should_commit: bool,
        timeout_ms: int,
        trace_id: str = "",
    ) -> bool:
        req = pb.ShouldCommitRequest(
            group_rank=group_rank,
            step=step,
            should_commit=should_commit,
            trace_id=trace_id,
        )
        resp = pb.ShouldCommitResponse()
        resp.ParseFromString(
            self._client.call(MANAGER_SHOULD_COMMIT, req.SerializeToString(), timeout_ms)
        )
        return resp.should_commit

    def close(self) -> None:
        self._client.close()


class RingEngine:
    """GIL-free ring data plane (native/src/ring.h).

    Owns dup()'d copies of TCPCollective's established lane sockets and runs
    the entire per-hop hot loop natively: scatter-gather socket I/O over the
    caller's flat f32 buffers, the leader/follower tag demux, the
    per-direction virtual-time link pacing, and the bf16/int8 wire codecs —
    all bit-identical to the Python engine (the two interoperate on one
    ring).  Every method releases the GIL for its full duration (ctypes),
    which is the point: a striped allreduce keeps exactly zero interpreter
    work on the wire path.

    Tiers: 0 = flat ring, 1 = ring2d row, 2 = ring2d column.  Directions:
    0 = next (sends), 1 = prev (receives).
    """

    TIER_FLAT = 0
    TIER_ROW = 1
    TIER_COL = 2
    # Ring-pass modes / ops / wires (native/src/ring.h enums).
    PASS_FULL = 0
    PASS_RS = 1
    PASS_AG = 2
    OP_SUM = 0
    OP_MAX = 1
    OP_MIN = 2
    WIRE_RAW = 0
    WIRE_BF16 = 1
    WIRE_INT8 = 2
    WIRE_INT4 = 3

    def __init__(self, lanes: int, shaper_mbps: float = 0.0, shaper_rtt_ms: float = 0.0) -> None:
        if _RING_UNAVAILABLE is not None:
            raise RuntimeError(_RING_UNAVAILABLE)
        self._ptr = _lib.tf_ring_new(int(lanes), float(shaper_mbps), float(shaper_rtt_ms))
        self._lanes = int(lanes)
        # Python→native boundary crossings on the data path (ring_pass +
        # ring_pass_multi calls).  The multi_stripe bench cell asserts this
        # drops to one per op when the batched entry point is in use.
        self.pass_calls = 0

    def set_tier(self, tier: int, next_fds: List[int], prev_fds: List[int]) -> None:
        """Registers one tier's lane sockets (the engine dup()s them; the
        Python sockets stay owned — and closed — by the collective)."""
        n = len(next_fds)
        assert len(prev_fds) == n
        nxt = (ctypes.c_int32 * n)(*next_fds)
        prv = (ctypes.c_int32 * n)(*prev_fds)
        err = ctypes.c_char_p()
        rc = _lib.tf_ring_set_tier(self._ptr, int(tier), n, nxt, prv, ctypes.byref(err))
        if rc != 0:
            raise RuntimeError(_take_error(err))

    @staticmethod
    def _raise(rc: int, err: "ctypes.c_char_p") -> None:
        msg = _take_error(err)
        if rc == 1:
            raise TimeoutError(msg)
        if rc == 2:
            raise ConnectionError(msg)
        raise RuntimeError(msg)

    def exchange(self, tier: int, lane: int, tag: int, payload: bytes, timeout_s: float) -> bytes:
        """Full-duplex framed exchange on (tier, lane): send ``payload``
        under ``tag`` to the next neighbor while receiving the same tag
        from the previous one.  The whole-frame path the Python-orchestrated
        ops (allgather/broadcast/alltoall/barrier, non-f32 fallbacks) ride
        so every read of a lane socket goes through ONE demux."""
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        err = ctypes.c_char_p()
        rc = _lib.tf_ring_exchange(
            self._ptr, int(tier), int(lane), int(tag) & 0xFFFFFFFF,
            payload, len(payload), ctypes.byref(out), ctypes.byref(out_len),
            float(timeout_s), ctypes.byref(err),
        )
        if rc != 0:
            self._raise(rc, err)
        data = ctypes.string_at(out, out_len.value)
        _lib.tf_free(ctypes.cast(out, ctypes.c_void_p))
        return data

    def ring_pass(
        self,
        tier: int,
        lane: int,
        n: int,
        rank: int,
        tag_base: int,
        rs_sub: int,
        ag_sub: int,
        mode: int,
        op: int,
        wire: int,
        chunk_ptrs: List[int],
        chunk_elems: List[int],
        timeout_s: float,
    ) -> None:
        """One ring pass over ``n`` chunk views (raw addresses + element
        counts into the caller's contiguous f32 buffer), IN PLACE.  The
        caller guarantees the buffer outlives the call (it does: the call
        blocks) and that chunk boundaries were cut identically on every
        rank (np.array_split math, same as the Python engine)."""
        ptrs = (ctypes.c_uint64 * n)(*chunk_ptrs)
        elems = (ctypes.c_uint64 * n)(*chunk_elems)
        err = ctypes.c_char_p()
        self.pass_calls += 1
        rc = _lib.tf_ring_pass(
            self._ptr, int(tier), int(lane), int(n), int(rank),
            int(tag_base) & 0xFFFFFFFF, int(rs_sub), int(ag_sub),
            int(mode), int(op), int(wire), ptrs, elems,
            float(timeout_s), ctypes.byref(err),
        )
        if rc != 0:
            self._raise(rc, err)

    def ring_pass_multi(
        self,
        tier: int,
        nstripes: int,
        n: int,
        rank: int,
        lanes: List[int],
        tag_bases: List[int],
        rs_sub: int,
        ag_sub: int,
        mode: int,
        op: int,
        wire: int,
        chunk_ptrs: List[int],
        chunk_elems: List[int],
        timeout_s: float,
    ) -> None:
        """One batched ring pass over a whole stripe set: ``nstripes``
        independent ring passes, stripe ``s`` on lane ``lanes[s]`` under
        ``tag_bases[s]``, each over ``n`` chunk views laid out row-major in
        ``chunk_ptrs``/``chunk_elems`` (stripe s owns slots [s*n, s*n+n)).
        The per-stripe fan-out runs on the engine's internal worker pool so
        Python crosses the capi boundary ONCE per allreduce; a failure on
        any stripe poisons the tier (all stripes + the peer fail fast) and
        the first error is raised."""
        total = int(nstripes) * int(n)
        assert len(chunk_ptrs) == total and len(chunk_elems) == total
        assert len(lanes) == nstripes and len(tag_bases) == nstripes
        lanes_a = (ctypes.c_int32 * nstripes)(*lanes)
        tags_a = (ctypes.c_uint32 * nstripes)(*(int(t) & 0xFFFFFFFF for t in tag_bases))
        ptrs = (ctypes.c_uint64 * total)(*chunk_ptrs)
        elems = (ctypes.c_uint64 * total)(*chunk_elems)
        err = ctypes.c_char_p()
        self.pass_calls += 1
        rc = _lib.tf_ring_pass_multi(
            self._ptr, int(tier), int(nstripes), int(n), int(rank),
            lanes_a, tags_a, int(rs_sub), int(ag_sub),
            int(mode), int(op), int(wire), ptrs, elems,
            float(timeout_s), ctypes.byref(err),
        )
        if rc != 0:
            self._raise(rc, err)

    def set_shm(self, tier: int, direction: int, lane: int, path: str, token: int) -> None:
        """Attaches one lane link to a shared-memory SPSC ring segment
        (created + negotiated by the Python rendezvous).  The link's frames
        move through the segment from then on; the TCP socket stays open as
        the liveness/abort channel.  Raises if the segment's magic or
        generation token doesn't match (stale segment from a dead peer)."""
        err = ctypes.c_char_p()
        rc = _lib.tf_ring_set_shm(
            self._ptr, int(tier), int(direction), int(lane),
            path.encode(), int(token) & 0xFFFFFFFFFFFFFFFF, ctypes.byref(err),
        )
        if rc != 0:
            raise RuntimeError(_take_error(err))

    def counters(self, tier: int) -> "tuple[List[int], List[int]]":
        """(sent, recv) wire-byte counters per lane of one tier (headers
        included) — lane_stats' feed under the native engine."""
        cap = self._lanes
        sent = (ctypes.c_uint64 * cap)()
        recv = (ctypes.c_uint64 * cap)()
        got = _lib.tf_ring_counters(self._ptr, int(tier), sent, recv, cap)
        return list(sent[:got]), list(recv[:got])

    def shaper_counters(self, tier: int, direction: int) -> "tuple[int, int]":
        """(bytes, frames) admitted through one tier-direction's shared
        virtual-time pacer — LinkShaper.bytes_sent/frames_sent parity."""
        b = ctypes.c_uint64()
        f = ctypes.c_uint64()
        _lib.tf_ring_shaper_counters(self._ptr, int(tier), int(direction),
                                     ctypes.byref(b), ctypes.byref(f))
        return int(b.value), int(f.value)

    def link_bytes(self, tier: int, direction: int, lane: int) -> int:
        return int(_lib.tf_ring_link_bytes(self._ptr, int(tier), int(direction), int(lane)))

    def set_hop(self, sample: int, cap: int = 0) -> None:
        """Configures the data-plane flight recorder: record every
        ``sample``-th hop into the bounded timeline ring (0 disables the
        timeline; the per-tier stall aggregates stay on).  ``cap`` > 0
        resizes (and clears) the ring."""
        _lib.tf_ring_set_hop(self._ptr, int(sample), int(cap))

    def hop_stats(self, tier: int) -> "dict":
        """Per-tier stall aggregates: ``{"hops", "send_block_s",
        "recv_wait_s", "combine_s"}`` — lane_stats' native hop feed."""
        out = (ctypes.c_double * 4)()
        _lib.tf_ring_hop_stats(self._ptr, int(tier), out)
        return {
            "hops": int(out[0]),
            "send_block_s": float(out[1]),
            "recv_wait_s": float(out[2]),
            "combine_s": float(out[3]),
        }

    def hop_records(self, cap: int = 4096) -> "List[dict]":
        """The retained hop timeline, oldest first, as dicts with EXACTLY
        the Python engine's HopRecorder keys (collectives
        HOP_RECORD_FIELDS — the cross-engine schema contract)."""
        buf = (ctypes.c_double * (8 * max(1, cap)))()
        n = _lib.tf_ring_hop_records(self._ptr, buf, int(cap))
        records = []
        for i in range(n):
            o = buf[i * 8 : i * 8 + 8]
            records.append(
                {
                    "ts": float(o[0]),
                    "tier": int(o[1]),
                    "lane": int(o[2]),
                    "tag": int(o[3]),
                    "send_s": float(o[4]),
                    "recv_s": float(o[5]),
                    "comb_s": float(o[6]),
                    "nbytes": int(o[7]),
                }
            )
        return records

    def shaper_wait_s(self, tier: int, direction: int) -> float:
        """Seconds one tier-direction's pacer actually slept — the
        "shaping" bucket of the link_attribution split."""
        return float(_lib.tf_ring_shaper_wait_s(self._ptr, int(tier), int(direction)))

    def set_shaper(self, tier: int, direction: int, mbps: float, rtt_ms: float) -> None:
        """Mid-run re-shaping of one tier-direction's pacer (the slow-link
        bench degrades ONE peer link without a reconfigure)."""
        _lib.tf_ring_set_shaper(self._ptr, int(tier), int(direction), float(mbps), float(rtt_ms))

    def open_fd_count(self) -> int:
        """Dup'd lane fds still open — 0 after close() (the native half of
        the no-leaked-fds sweep)."""
        return int(_lib.tf_ring_open_fds(self._ptr)) if self._ptr else 0

    def close(self) -> None:
        """Shutdown + close every dup'd lane fd and join the sender
        threads; idempotent, safe mid-op (blocked ops fail fast)."""
        if self._ptr:
            _lib.tf_ring_close(self._ptr)

    def detach(self) -> None:
        """Quiescent teardown for incremental reconfiguration: releases
        the dup'd lane fds WITHOUT socket shutdown, so the collective's
        surviving sockets stay connected for the next engine generation
        (shm segment files persist too; only the mappings drop).  Raises
        if ops were in flight — the caller must then treat the lanes as
        dead and take the full-rendezvous path."""
        if self._ptr:
            err = ctypes.c_char_p()
            rc = _lib.tf_ring_detach(self._ptr, ctypes.byref(err))
            if rc != 0:
                raise RuntimeError(_take_error(err))

    def __del__(self) -> None:
        try:
            if self._ptr:
                _lib.tf_ring_close(self._ptr)
                _lib.tf_ring_free(self._ptr)
                self._ptr = None
        except Exception:
            pass


class StoreServer:
    """Native key-value rendezvous store server."""

    def __init__(self, bind: str = "[::]:0") -> None:
        err = ctypes.c_char_p()
        self._ptr = _lib.tf_store_new(bind.encode(), ctypes.byref(err))
        if not self._ptr:
            raise RuntimeError(_take_error(err))

    def address(self) -> str:
        return _take_string(_lib.tf_store_address(self._ptr))

    def shutdown(self) -> None:
        if self._ptr:
            _lib.tf_store_shutdown(self._ptr)

    def __del__(self) -> None:
        try:
            if self._ptr:
                _lib.tf_store_shutdown(self._ptr)
                _lib.tf_store_free(self._ptr)
                self._ptr = None
        except Exception:
            pass


class StoreClient:
    """Client for the rendezvous store, with optional key prefixing
    (the PrefixStore analogue, torchft/process_group.py:96-104)."""

    def __init__(self, addr: str, prefix: str = "", connect_timeout_ms: int = 10000) -> None:
        # "host:port/prefix" is accepted like the reference's
        # create_store_client (torchft/process_group.py:85-104).
        if "/" in addr:
            addr, extra = addr.split("/", 1)
            prefix = extra + "/" + prefix if prefix else extra
        self._client = _Client(addr, connect_timeout_ms)
        self._prefix = prefix
        self._addr = addr

    def sub_store(self, prefix: str) -> "StoreClient":
        child = StoreClient.__new__(StoreClient)
        child._client = self._client
        child._addr = self._addr
        child._prefix = f"{self._prefix}/{prefix}" if self._prefix else prefix
        return child

    def _key(self, key: str) -> str:
        return f"{self._prefix}/{key}" if self._prefix else key

    def set(self, key: str, value: bytes, timeout_ms: int = 10000) -> None:
        req = pb.StoreSetRequest(key=self._key(key), value=value)
        self._client.call(STORE_SET, req.SerializeToString(), timeout_ms)

    def get(self, key: str, wait: bool = True, timeout_ms: int = 10000) -> Optional[bytes]:
        req = pb.StoreGetRequest(key=self._key(key), wait=wait)
        resp = pb.StoreGetResponse()
        resp.ParseFromString(self._client.call(STORE_GET, req.SerializeToString(), timeout_ms))
        return resp.value if resp.found else None

    def add(self, key: str, delta: int, timeout_ms: int = 10000) -> int:
        req = pb.StoreAddRequest(key=self._key(key), delta=delta)
        resp = pb.StoreAddResponse()
        resp.ParseFromString(self._client.call(STORE_ADD, req.SerializeToString(), timeout_ms))
        return resp.value

    def delete(self, key: str, timeout_ms: int = 10000) -> None:
        req = pb.StoreDeleteRequest(key=self._key(key))
        self._client.call(STORE_DELETE, req.SerializeToString(), timeout_ms)

    def close(self) -> None:
        self._client.close()
