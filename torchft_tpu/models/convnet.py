"""Small conv net on 32x32x3 inputs — the train_ddp example's model class.

Reference parity: the reference example trains a CIFAR-10 CNN
(train_ddp.py:116-130 at the reference root); this is the first-party
equivalent so the example and tests share one definition.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp


def init_convnet_params(key: jax.Array, n_classes: int = 10) -> Dict[str, Any]:
    """Initializes the example CIFAR-class convnet parameters."""
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "conv": jax.random.normal(k1, (3, 3, 3, 16), jnp.float32) * 0.1,
        "w1": jax.random.normal(k2, (16 * 16 * 16, 64), jnp.float32) * 0.02,
        "b1": jnp.zeros((64,), jnp.float32),
        "w2": jax.random.normal(k3, (64, n_classes), jnp.float32) * 0.02,
        "b2": jnp.zeros((n_classes,), jnp.float32),
    }


def convnet_forward(params: Dict[str, Any], x: jax.Array) -> jax.Array:
    """x: [B, 32, 32, 3] -> logits [B, n_classes]."""
    h = jax.lax.conv_general_dilated(
        x, params["conv"], window_strides=(2, 2), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    h = jax.nn.relu(h)
    h = h.reshape(h.shape[0], -1)
    h = jax.nn.relu(h @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def convnet_loss(params: Dict[str, Any], x: jax.Array, y: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy of convnet_forward logits vs labels."""
    import optax

    logits = convnet_forward(params, x)
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
