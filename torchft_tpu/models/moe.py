"""Mixture-of-Experts FFN with expert parallelism.

Capability beyond the reference: torchft has no EP anywhere (SURVEY.md §2.3
— PP/CP/EP absent); this is part of the TPU build's first-class parallelism
surface alongside ring/Ulysses sequence parallelism.

TPU-first design (GShard/Switch style, arXiv:2006.16668):
  - routing builds dense dispatch/combine tensors ([T, n_exp, capacity])
    with STATIC shapes — no sorting, no ragged buffers, nothing
    data-dependent for XLA to choke on; over-capacity tokens are dropped
    (their residual path carries them, standard MoE practice);
  - expert compute is a batched einsum over experts *stacked on a leading
    axis* (one compiled FFN body for all experts — same trick as the
    scan-over-layers transformer);
  - expert parallelism is pure annotation: the stacked expert axis maps to
    the "expert" mesh axis (parallel/sharding.py); the dispatch/combine
    einsums then compile to the all-to-all exchanges, inserted by XLA/GSPMD
    rather than hand-placed.

The load-balance auxiliary loss (mean fraction * mean router prob per
expert, scaled by n_exp^2) follows Switch Transformer (arXiv:2101.03961).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from torchft_tpu.parallel.sharding import ShardingRules, constrain


def moe_capacity(tokens: int, n_experts: int, top_k: int, capacity_factor: float) -> int:
    """Static per-expert token capacity, padded to the 8-sublane boundary."""
    cap = int(tokens * top_k * capacity_factor / n_experts) + 1
    return max(8, -(-cap // 8) * 8)


def moe_ffn(
    x: jax.Array,
    router: jax.Array,
    w_gate: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    *,
    top_k: int = 2,
    capacity_factor: float = 1.25,
    dtype: Any = jnp.bfloat16,
    mesh=None,
    rules: Optional[ShardingRules] = None,
) -> Tuple[jax.Array, jax.Array]:
    """MoE feed-forward.

    Args:
        x: [B, S, E] activations.
        router: [E, n_exp] routing weights (kept f32 — routing logits are
            numerically sensitive).
        w_gate/w_up: [n_exp, E, F]; w_down: [n_exp, F, E] stacked experts.

    Returns:
        (y, aux_loss): y [B, S, E]; aux_loss scalar f32 load-balance term.
    """
    rules = rules or ShardingRules()
    B, S, E = x.shape
    n_exp = router.shape[1]
    T = B * S
    C = moe_capacity(T, n_exp, top_k, capacity_factor)

    xf = x.reshape(T, E)
    logits = xf.astype(jnp.float32) @ router.astype(jnp.float32)  # [T, n_exp]
    probs = jax.nn.softmax(logits, axis=-1)

    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)  # [T, k]
    # Renormalize the kept gates so the combine is a convex mixture.
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9
    )

    # Position of each (token, choice) in its expert's capacity buffer:
    # choices are prioritized k-major (all rank-0 choices first), so a
    # token's primary expert wins buffer slots over anyone's secondary.
    onehot = jax.nn.one_hot(gate_idx, n_exp, dtype=jnp.float32)  # [T, k, n_exp]
    flat = onehot.transpose(1, 0, 2).reshape(top_k * T, n_exp)    # k-major
    pos_flat = jnp.cumsum(flat, axis=0) - 1.0                     # [kT, n_exp]
    pos = pos_flat.reshape(top_k, T, n_exp).transpose(1, 0, 2)    # [T, k, n_exp]
    within = (pos < C) & (onehot > 0)

    # dispatch[t, e, c] = 1 where token t landed in slot c of expert e;
    # combine carries the gate weight instead.
    slot = jax.nn.one_hot(
        jnp.where(within, pos, -1).astype(jnp.int32).max(axis=-1).clip(0),
        C,
        dtype=jnp.float32,
    )  # [T, k, C] (clip is safe: masked rows are zeroed below)
    kept = within.any(axis=-1).astype(jnp.float32)                 # [T, k]
    expert_oh = onehot * within.astype(jnp.float32)                # [T, k, n_exp]
    dispatch = jnp.einsum("tke,tkc,tk->tec", expert_oh, slot, kept)
    combine = jnp.einsum("tke,tkc,tk->tec", expert_oh, slot, kept * gate_vals)

    # Dispatch -> stacked expert FFN -> combine.  The "expert" leading axis
    # is sharded over the expert mesh axis; these einsums ARE the
    # all-to-alls once partitioned.
    xin = jnp.einsum("tec,td->ecd", dispatch.astype(dtype), xf.astype(dtype))
    xin = constrain(xin, ("expert", None, "embed"), mesh, rules)
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xin, w_gate.astype(dtype)))
    h = h * jnp.einsum("ecd,edf->ecf", xin, w_up.astype(dtype))
    h = constrain(h, ("expert", None, "mlp"), mesh, rules)
    out = jnp.einsum("ecf,efd->ecd", h, w_down.astype(dtype))
    out = constrain(out, ("expert", None, "embed"), mesh, rules)
    y = jnp.einsum("tec,ecd->td", combine.astype(dtype), out)

    # Switch-style load balance: encourage uniform (tokens, probability)
    # mass per expert.  f = fraction of primary-choice tokens per expert.
    primary = onehot[:, 0, :]                                      # [T, n_exp]
    f = jnp.mean(primary, axis=0)
    p = jnp.mean(probs, axis=0)
    aux = n_exp * jnp.sum(f * p)

    return y.reshape(B, S, E).astype(x.dtype), aux.astype(jnp.float32)
