"""Flagship model: decoder-only transformer LM (Llama-3-class shape).

TPU-first design choices:
  - parameters are plain pytrees of jax.Arrays with per-layer weights
    *stacked* along a leading "layers" axis so the decoder runs as one
    ``lax.scan`` — one compiled layer body instead of L unrolled copies;
  - compute in bfloat16 (MXU-native), parameters and reductions in float32;
  - hot ops route through torchft_tpu.ops: fused pallas RMSNorm and flash
    attention; ring attention over the "sequence" mesh axis for long
    context;
  - ``jax.checkpoint`` on the layer body: rematerialize instead of storing
    per-layer activations (HBM is the bottleneck);
  - every array axis has a logical name; sharding is applied by annotation
    (parallel/sharding.py), never hand-placed collectives.

Reference parity note: torchft trains user torch models (CIFAR CNN in
train_ddp.py; Llama via torchtitan, README.md:67-74); this module is the TPU
build's first-party equivalent of that model class.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from torchft_tpu.ops import flash_attention, rms_norm
from torchft_tpu.parallel.sharding import ShardingRules, constrain


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8
    d_ff: int = 1408
    max_seq: int = 2048
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16          # activation/compute dtype (MXU-native)
    param_dtype: Any = jnp.float32
    remat: bool = True
    # Attention backend: "flash" (pallas kernel / XLA fallback), "ring"
    # (sequence-parallel K/V rotation), or "ulysses" (all-to-all head<->seq
    # resharding) — the latter two engage over the mesh "sequence" axis.
    attention: str = "flash"
    # Sequence layout for attention="ring": "contiguous" or "zigzag"
    # (balanced causal work, ops/ring_attention.py).  With "zigzag" the
    # CALLER feeds tokens/targets already permuted by
    # ops.ring_attention.to_zigzag(..., n_shards=mesh sequence size); the
    # model ropes with the matching original positions internally, and the
    # mean CE loss is permutation-invariant so training needs no other
    # change.
    ring_layout: str = "contiguous"
    # Unroll factor for the scan-over-layers (1 = pure scan).  Unrolling
    # lets XLA fuse/pipeline across layer boundaries at the cost of compile
    # time; worthwhile on the perf path, keep 1 for fast test iteration.
    # >= n_layers switches to a static Python loop (constant-folded layer
    # indexing — see forward_with_aux), the fastest measured form.
    scan_unroll: int = 1
    # Mixture-of-experts: > 0 replaces the dense MLP with moe_experts
    # experts (stacked, shardable over the "expert" mesh axis).
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01

    def __post_init__(self) -> None:
        assert self.attention in ("flash", "ring", "ulysses"), (
            f"unknown attention backend {self.attention!r}; "
            "expected 'flash', 'ring', or 'ulysses'"
        )
        assert self.ring_layout in ("contiguous", "zigzag"), (
            f"unknown ring_layout {self.ring_layout!r}"
        )

    @property
    def d_head(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


# Logical axis names for every parameter (see parallel/sharding.py).
def param_axes(cfg: TransformerConfig) -> Dict[str, Any]:
    """Logical axis names for every parameter, keyed like init_params'
    tree — feed to FTMesh.shard_params to place the model on a mesh."""
    layer = {
        "attn_norm": ("layers", "embed"),
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv_heads"),
        "wv": ("layers", "embed", "kv_heads"),
        "wo": ("layers", "heads", "embed"),
        "mlp_norm": ("layers", "embed"),
        "w_gate": ("layers", "embed", "mlp"),
        "w_up": ("layers", "embed", "mlp"),
        "w_down": ("layers", "mlp", "embed"),
    }
    if cfg.moe_experts > 0:
        layer.update(
            {
                "router": ("layers", "embed", "expert"),
                "w_gate": ("layers", "expert", "embed", "mlp"),
                "w_up": ("layers", "expert", "embed", "mlp"),
                "w_down": ("layers", "expert", "mlp", "embed"),
            }
        )
    return {
        "embed": ("vocab", "embed"),
        "layers": layer,
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }


def init_params(key: jax.Array, cfg: TransformerConfig) -> Dict[str, Any]:
    """Initializes the transformer parameter pytree (layers stacked on a
    leading axis for the scan-over-layers; param_dtype precision)."""
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    pd = cfg.param_dtype
    E, H, KV, Dh, F, L = (
        cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.d_head, cfg.d_ff,
        cfg.n_layers,
    )

    def norm_init(k, shape, fan_in):
        return (jax.random.normal(k, shape, pd) * (fan_in ** -0.5)).astype(pd)

    ks = jax.random.split(k_layers, 8)
    layers = {
        "attn_norm": jnp.ones((L, E), pd),
        "wq": norm_init(ks[0], (L, E, H * Dh), E),
        "wk": norm_init(ks[1], (L, E, KV * Dh), E),
        "wv": norm_init(ks[2], (L, E, KV * Dh), E),
        "wo": norm_init(ks[3], (L, H * Dh, E), H * Dh),
        "mlp_norm": jnp.ones((L, E), pd),
    }
    if cfg.moe_experts > 0:
        X = cfg.moe_experts
        kr, kg, ku, kd = jax.random.split(ks[7], 4)
        layers.update(
            {
                "router": norm_init(kr, (L, E, X), E),
                "w_gate": norm_init(kg, (L, X, E, F), E),
                "w_up": norm_init(ku, (L, X, E, F), E),
                "w_down": norm_init(kd, (L, X, F, E), F),
            }
        )
    else:
        layers.update(
            {
                "w_gate": norm_init(ks[4], (L, E, F), E),
                "w_up": norm_init(ks[5], (L, E, F), E),
                "w_down": norm_init(ks[6], (L, F, E), F),
            }
        )
    return {
        "embed": norm_init(k_embed, (cfg.vocab_size, E), E),
        "layers": layers,
        "final_norm": jnp.ones((E,), pd),
        "lm_head": norm_init(k_head, (E, cfg.vocab_size), E),
    }


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding; x: [B, S, H, Dh], positions: [B, S] (global)."""
    d_half = x.shape[-1] // 2
    freqs = theta ** (-jnp.arange(0, d_half, dtype=jnp.float32) / d_half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, d/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _attention(cfg: TransformerConfig, mesh, q, k, v):
    """q/k/v: [B, H|KV, S, Dh] head-major."""
    seq_parallel = (
        cfg.attention in ("ring", "ulysses")
        and mesh is not None
        and "sequence" in mesh.axis_names
        and mesh.shape["sequence"] > 1
    )
    if cfg.attention != "flash" and not seq_parallel:
        # Trace-time (once per compile), not per step.
        import warnings

        warnings.warn(
            f"attention={cfg.attention!r} requested but the mesh has no "
            ">1-sized 'sequence' axis; falling back to single-shard flash "
            "attention",
            stacklevel=2,
        )
    if seq_parallel:
        if cfg.attention == "ring":
            from torchft_tpu.ops.ring_attention import ring_attention_sharded as fn

            # The ring body assumes equal q/kv head counts.
            broadcast_gqa = cfg.n_kv_heads != cfg.n_heads
        else:
            from torchft_tpu.ops.ulysses import ulysses_attention_sharded as fn

            # Ulysses keeps GQA compressed through the all_to_all (the local
            # flash kernel broadcasts groups afterwards) unless the kv heads
            # PER TENSOR-PARALLEL SHARD don't tile the sequence axis — the
            # divisibility the local body actually requires.
            tp = mesh.shape.get("tensor", 1) if "tensor" in mesh.axis_names else 1
            broadcast_gqa = (
                cfg.n_kv_heads != cfg.n_heads
                and (cfg.n_kv_heads // tp) % mesh.shape["sequence"] != 0
            )
        if broadcast_gqa:
            rep = cfg.n_heads // cfg.n_kv_heads
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        kwargs = {}
        if cfg.attention == "ring":
            kwargs["layout"] = cfg.ring_layout
        return fn(
            mesh, q, k, v, causal=True,
            batch_axis="data" if "data" in mesh.axis_names else None,
            head_axis="tensor" if "tensor" in mesh.axis_names else None,
            seq_axis="sequence",
            **kwargs,
        )
    return flash_attention(q, k, v, causal=True)


def _layer(cfg: TransformerConfig, mesh, rules: ShardingRules, x, w, positions):
    """One decoder block; x: [B, S, E]."""
    B, S, E = x.shape
    H, KV, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.d_head

    h = rms_norm(x, w["attn_norm"])
    q = (h @ w["wq"].astype(cfg.dtype)).reshape(B, S, H, Dh)
    k = (h @ w["wk"].astype(cfg.dtype)).reshape(B, S, KV, Dh)
    v = (h @ w["wv"].astype(cfg.dtype)).reshape(B, S, KV, Dh)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    q = constrain(q.transpose(0, 2, 1, 3), ("batch", "heads", "seq", None), mesh, rules)
    k = constrain(k.transpose(0, 2, 1, 3), ("batch", "kv_heads", "seq", None), mesh, rules)
    v = constrain(v.transpose(0, 2, 1, 3), ("batch", "kv_heads", "seq", None), mesh, rules)
    attn = _attention(cfg, mesh, q, k, v)            # [B, H, S, Dh]
    attn = attn.transpose(0, 2, 1, 3).reshape(B, S, H * Dh)
    x = x + (attn @ w["wo"].astype(cfg.dtype))
    x = constrain(x, ("batch", "seq", "embed"), mesh, rules)

    h = rms_norm(x, w["mlp_norm"])
    if cfg.moe_experts > 0:
        from torchft_tpu.models.moe import moe_ffn

        y, aux = moe_ffn(
            h,
            w["router"],
            w["w_gate"],
            w["w_up"],
            w["w_down"],
            top_k=cfg.moe_top_k,
            capacity_factor=cfg.moe_capacity_factor,
            dtype=cfg.dtype,
            mesh=mesh,
            rules=rules,
        )
        x = x + y
    else:
        gate = jax.nn.silu(h @ w["w_gate"].astype(cfg.dtype))
        up = h @ w["w_up"].astype(cfg.dtype)
        x = x + ((gate * up) @ w["w_down"].astype(cfg.dtype))
        aux = jnp.zeros((), jnp.float32)
    return constrain(x, ("batch", "seq", "embed"), mesh, rules), aux


def _decoder(
    params: Dict[str, Any],
    tokens: jax.Array,
    cfg: TransformerConfig,
    mesh=None,
    rules: Optional[ShardingRules] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Embedding + decoder stack (everything before the lm head).
    tokens: [B, S] int32 -> (hidden [B, S, E], aux scalar f32 — the summed
    MoE load-balance loss; zero for dense models)."""
    rules = rules or ShardingRules()
    B, S = tokens.shape
    pos = jnp.arange(S, dtype=jnp.int32)
    if (
        cfg.attention == "ring"
        and cfg.ring_layout == "zigzag"
        and mesh is not None
        and "sequence" in mesh.axis_names
        and mesh.shape["sequence"] > 1
    ):
        # Tokens arrive zigzag-permuted (see TransformerConfig.ring_layout);
        # rope must see each slot's ORIGINAL position.
        from torchft_tpu.ops.ring_attention import zigzag_permutation

        pos = jnp.asarray(
            zigzag_permutation(S, mesh.shape["sequence"]), dtype=jnp.int32
        )
    positions = jnp.broadcast_to(pos, (B, S))

    x = params["embed"].astype(cfg.dtype)[tokens]
    x = constrain(x, ("batch", "seq", "embed"), mesh, rules)

    def body(x, w):
        x, aux = _layer(cfg, mesh, rules, x, w, positions)
        return x, aux

    if cfg.remat:
        body = jax.checkpoint(body)
    if cfg.scan_unroll > 1 and cfg.scan_unroll >= cfg.n_layers:
        # Full unroll as a STATIC Python loop rather than lax.scan(unroll=L):
        # scan's internal layer slicing survives as dynamic-update-slice
        # fusions in the backward (profiled: ~17 ms/step of DUS on the v5e
        # flagship config); static integer indexing lets XLA constant-fold
        # the slices and fold the per-layer grad writes, measured ~4 ms/step
        # faster end-to-end.  Same math, different op association — results
        # agree with the scan path to fusion-order rounding, not bitwise
        # (pinned by test_scan_unroll_matches_scan).
        aux_total = jnp.zeros((), jnp.float32)
        for i in range(cfg.n_layers):
            w_i = jax.tree.map(lambda a, i=i: a[i], params["layers"])
            x, aux = body(x, w_i)
            aux_total = aux_total + aux
        return x, aux_total
    x, aux_layers = jax.lax.scan(
        body, x, params["layers"], unroll=cfg.scan_unroll
    )
    return x, jnp.sum(aux_layers)


def forward_with_aux(
    params: Dict[str, Any],
    tokens: jax.Array,
    cfg: TransformerConfig,
    mesh=None,
    rules: Optional[ShardingRules] = None,
) -> Tuple[jax.Array, jax.Array]:
    """tokens: [B, S] int32 -> (logits [B, S, vocab] f32, aux scalar f32 —
    the summed MoE load-balance loss; zero for dense models)."""
    x, aux = _decoder(params, tokens, cfg, mesh, rules)
    return head(params, x, cfg, mesh, rules), aux


def head(
    params: Dict[str, Any],
    x: jax.Array,
    cfg: TransformerConfig,
    mesh=None,
    rules: Optional[ShardingRules] = None,
) -> jax.Array:
    """Final norm + lm head: decoder output [B, S, E] -> logits [B, S, V].

    Shared by the dense path (forward_with_aux) and the pipelined path
    (parallel/pipeline.pipeline_loss_fn) so the two can never diverge."""
    x = rms_norm(x, params["final_norm"])
    # bf16 operands on the MXU, f32 accumulation/output: full systolic-array
    # rate with f32 logits (an f32xf32 matmul runs at a fraction of MXU peak).
    logits = jnp.matmul(
        x, params["lm_head"].astype(cfg.dtype), preferred_element_type=jnp.float32
    )
    return constrain(logits, ("batch", "seq", "vocab"), mesh, rules)


def token_cross_entropy(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean next-token CE, computed as logsumexp - target_logit rather than
    materializing the full [B, S, vocab] log-softmax: the logits array is
    the single biggest activation, and one extra copy is pure HBM traffic."""
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    lse = jax.nn.logsumexp(logits, axis=-1)
    return jnp.mean(lse - tgt)


def forward(
    params: Dict[str, Any],
    tokens: jax.Array,
    cfg: TransformerConfig,
    mesh=None,
    rules: Optional[ShardingRules] = None,
) -> jax.Array:
    """tokens: [B, S] int32 -> logits [B, S, vocab] (f32)."""
    return forward_with_aux(params, tokens, cfg, mesh, rules)[0]


def lm_head_loss(
    params: Dict[str, Any],
    x: jax.Array,
    cfg: TransformerConfig,
    targets: jax.Array,
    mesh=None,
    rules: Optional[ShardingRules] = None,
) -> jax.Array:
    """Mean next-token CE from decoder output x [B, S, E].

    On a single TPU device this fuses the lm-head matmul with the CE
    reduction (ops/cross_entropy.py) so the f32 [B, S, vocab] logits —
    the single biggest activation, ~2 GB at the flagship config — never
    reach HBM in either direction of autodiff.  Sharded meshes and
    off-TPU backends keep the plain XLA formulation, whose shardings
    (e.g. vocab-parallel logsumexp) propagate natively."""
    from torchft_tpu.ops.cross_entropy import (
        fused_ce_applicable,
        fused_linear_cross_entropy,
    )

    B, S, E = x.shape
    if fused_ce_applicable(B * S, E, cfg.vocab_size, mesh):
        h = rms_norm(x, params["final_norm"])
        w = params["lm_head"].astype(cfg.dtype)
        return fused_linear_cross_entropy(
            h.reshape(B * S, E), w, targets.reshape(B * S)
        )
    return token_cross_entropy(head(params, x, cfg, mesh, rules), targets)


def loss_fn(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: TransformerConfig,
    mesh=None,
    rules: Optional[ShardingRules] = None,
) -> jax.Array:
    """Next-token cross entropy; batch: {"tokens": [B,S], "targets": [B,S]}.

    MoE configs add moe_aux_coef * load-balance loss (Switch-style).
    """
    x, aux = _decoder(params, batch["tokens"], cfg, mesh, rules)
    ce = lm_head_loss(params, x, cfg, batch["targets"], mesh, rules)
    if cfg.moe_experts > 0:
        ce = ce + cfg.moe_aux_coef * aux
    return ce
