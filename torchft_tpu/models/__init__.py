"""Model zoo: TPU-first reference models for the framework.

The torchft reference trains user-supplied torch models (its examples use a
CIFAR CNN, and its README targets Llama-class models through torchtitan
HSDP, README.md:67-74).  This package provides the equivalent first-party
models for the TPU build: a decoder-only transformer LM (the flagship, the
Llama-3-class shape), a mixture-of-experts variant (expert parallelism), and
a small conv net (the train_ddp example class).
"""

from torchft_tpu.models.convnet import (
    convnet_forward,
    convnet_loss,
    init_convnet_params,
)
from torchft_tpu.models.moe import moe_ffn
from torchft_tpu.models.transformer import (
    TransformerConfig,
    forward,
    forward_with_aux,
    init_params,
    loss_fn,
)

__all__ = [
    "TransformerConfig",
    "init_params",
    "loss_fn",
    "forward",
    "forward_with_aux",
    "moe_ffn",
    "convnet_forward",
    "convnet_loss",
    "init_convnet_params",
]
