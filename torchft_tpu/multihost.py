"""Multi-host slice bootstrap: wire one replica group across N hosts.

On a TPU pod, one *replica group* (the fault-tolerance unit the Manager
coordinates over DCN) is typically one multi-host *slice*: N host
processes, each owning its local chips, joined into a single JAX runtime by
``jax.distributed.initialize`` so that ``jax.devices()`` sees the whole
slice and XLA collectives ride ICI.  This module is the bootstrap glue
between the launcher's replica-group env and that per-slice JAX init.

Reference parity: the reference's per-group bootstrap is torchrun's
TCPStore rendezvous (torchft/torchx.py:11-80 builds one torchrun role per
group; torchft/manager.py:88-245 then rendezvouses ranks through the
store).  The TPU design splits the same two layers:

  - WITHIN a slice: ``initialize_slice`` — rank 0 publishes a coordinator
    address through the group's Store (the same framed-TCP store the
    Manager uses), every host calls ``jax.distributed.initialize``; XLA
    owns all intra-slice communication from then on.  No per-op process
    group exists, because intra-slice collectives are compiled into the
    program (SURVEY.md §2.4).
  - ACROSS slices: the Manager + Lighthouse + TCPCollective path,
    unchanged — only host-level code talks DCN.

Env contract (set by the cluster scheduler / pod launcher — the local
``torchft_tpu.launch`` supervisor runs single-host groups and does not set
these):

  TPUFT_HOST_RANK        this process's host index within its slice
  TPUFT_NUM_HOSTS        hosts per slice (1 = single-host: init is a no-op
                         unless forced)
  TPUFT_STORE            host:port of the group's StoreServer (rendezvous)
  TPUFT_COORD_PORT       port rank 0 binds for the JAX coordinator
                         (default 8476)
  TPUFT_SLICE_GEN        restart generation (the supervisor's attempt
                         counter).  The Store outlives the group's
                         processes, so without a generation in the
                         rendezvous key a restarted slice would read the
                         PREVIOUS incarnation's coordinator address and
                         dial a dead host.
"""

from __future__ import annotations

import os
import socket
from dataclasses import dataclass
from typing import Optional

__all__ = ["SliceConfig", "slice_config_from_env", "initialize_slice"]


@dataclass(frozen=True)
class SliceConfig:
    host_rank: int
    num_hosts: int
    store_addr: Optional[str]
    coord_port: int = 8476
    # Restart incarnation; part of the rendezvous key so a restarted slice
    # never reads a previous incarnation's coordinator from the long-lived
    # Store (cf. the per-generation store prefix in Collective.configure).
    generation: int = 0

    @property
    def is_multihost(self) -> bool:
        return self.num_hosts > 1


def slice_config_from_env(env: Optional[dict] = None) -> SliceConfig:
    """Builds a SliceConfig from the TPUFT_HOST_RANK/TPUFT_NUM_HOSTS/
    TPUFT_STORE/TPUFT_COORD_PORT/TPUFT_SLICE_GEN environment contract."""
    e = os.environ if env is None else env
    return SliceConfig(
        host_rank=int(e.get("TPUFT_HOST_RANK", 0)),
        num_hosts=int(e.get("TPUFT_NUM_HOSTS", 1)),
        store_addr=e.get("TPUFT_STORE") or None,
        coord_port=int(e.get("TPUFT_COORD_PORT", 8476)),
        generation=int(e.get("TPUFT_SLICE_GEN", 0)),
    )


def _local_address(port: int) -> str:
    """Best-effort routable address for this host's coordinator."""
    host = socket.gethostname()
    try:
        # A UDP "connect" performs routing without sending anything; the
        # bound source address is what peers should dial.
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            host = s.getsockname()[0]
    except OSError:
        pass
    return f"{host}:{port}"


def initialize_slice(
    cfg: Optional[SliceConfig] = None,
    *,
    key_prefix: str = "tpuft_slice",
    timeout_ms: int = 60000,
    _initialize=None,
) -> Optional[str]:
    """Joins this host process into its slice's JAX runtime.

    Rank 0 publishes ``<key_prefix>/coordinator`` in the group Store; every
    host blocks on that key, then calls ``jax.distributed.initialize``
    (``_initialize`` is injectable for tests).  Must run before the first
    touch of the JAX backend, same constraint as jax.distributed itself.

    Returns the coordinator address used, or None when single-host (no-op).
    """
    cfg = cfg or slice_config_from_env()
    if not cfg.is_multihost:
        return None
    if _initialize is None:
        import jax

        _initialize = jax.distributed.initialize

    if cfg.store_addr is None:
        raise RuntimeError(
            "multi-host slice bootstrap needs TPUFT_STORE (the replica "
            "group's StoreServer address) for coordinator rendezvous"
        )

    from torchft_tpu.coordination import StoreClient

    store = StoreClient(cfg.store_addr)
    key = f"{key_prefix}/gen{cfg.generation}/coordinator"
    if cfg.host_rank == 0:
        coordinator = _local_address(cfg.coord_port)
        store.set(key, coordinator.encode(), timeout_ms=timeout_ms)
    else:
        raw = store.get(key, wait=True, timeout_ms=timeout_ms)
        if raw is None:
            raise TimeoutError(
                f"no coordinator published at {key!r} within {timeout_ms} ms"
            )
        coordinator = raw.decode()

    _initialize(
        coordinator_address=coordinator,
        num_processes=cfg.num_hosts,
        process_id=cfg.host_rank,
    )
    return coordinator
