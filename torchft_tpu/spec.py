"""Scheduler job-spec generation: the cluster-grade launch story.

Reference parity: torchft/torchx.py:11-80 — the reference ships a TorchX
component that renders its launch contract (one role per replica group,
REPLICA_GROUP_ID / NUM_REPLICA_GROUPS / lighthouse address env) into
scheduler job specs.  The TPU-native equivalent targets GKE's JobSet API
(the canonical way to run multi-host / multi-slice TPU jobs, and what XPK
generates under the hood): ``jobset_spec`` renders the SAME env contract
``torchft_tpu.launch`` + ``torchft_tpu.multihost`` define —

  per group:  REPLICA_GROUP_ID, NUM_REPLICA_GROUPS, TPUFT_LIGHTHOUSE
  per host:   TPUFT_HOST_RANK, TPUFT_NUM_HOSTS, TPUFT_STORE,
              TPUFT_SLICE_GEN (the scheduler's retry counter)

— onto one JobSet: a lighthouse replicated-job plus ``num_groups``
replicated TPU-slice Jobs (Indexed completion = host rank; JobSet's
headless service gives every pod a stable DNS name, which is how each
group's hosts find their rank-0 Store and every group finds the
lighthouse).  ``python -m torchft_tpu.launch --dump-spec ...`` prints the
manifest; it is a starting point to edit, not a turnkey operator.
"""

from __future__ import annotations

import shlex
from typing import Dict, List, Optional

__all__ = ["jobset_spec", "dump_yaml"]

_LIGHTHOUSE_PORT = 29510
_STORE_PORT = 29500
_MASTER_PORT = 29400


def _worker_script(cmd: List[str], name: str) -> str:
    """Shell prologue deriving the per-pod env contract from what the
    scheduler provides, then exec'ing the user command.

    JobSet injects JOB_COMPLETION_INDEX (Indexed Jobs) and the
    jobset.sigs.k8s.io/job-index annotation (surfaced below via the
    downward API as TPUFT_GROUP_INDEX); pod DNS is
    ``<jobset>-<job>-<jobindex>-<podindex>.<jobset>`` on the JobSet's
    headless service."""
    user = " ".join(shlex.quote(c) for c in cmd)
    # Pod DNS: <jobset>-<replicatedjob>-<jobindex>-<podindex>.<jobset>; the
    # group's host-rank-0 pod is pod index 0 of job index REPLICA_GROUP_ID.
    rank0 = f"{name}-group-${{REPLICA_GROUP_ID}}-0.{name}"
    return "\n".join(
        [
            "set -eu",
            "export REPLICA_GROUP_ID=\"${TPUFT_GROUP_INDEX}\"",
            "export TPUFT_HOST_RANK=\"${JOB_COMPLETION_INDEX}\"",
            # Each group's hosts rendezvous through a Store SERVED by the
            # group's host-rank-0 pod: initialize_slice is a client only,
            # so rank 0 runs the standalone store_cli in the background
            # before exec'ing the trainer.
            f'export TPUFT_STORE="{rank0}:{_STORE_PORT}"',
            'if [ "${TPUFT_HOST_RANK}" = "0" ] && [ "${TPUFT_NUM_HOSTS}" != "1" ]; then',
            f"  python -m torchft_tpu.store_cli --bind \"[::]:{_STORE_PORT}\" &",
            "fi",
            # The group Manager's rank-0 endpoint (manager.py MASTER_* contract).
            f'export MASTER_ADDR="{rank0}"',
            f"export MASTER_PORT=\"{_MASTER_PORT}\"",
            # The scheduler's retry counter becomes the restart generation,
            # so a restarted slice never reads a stale coordinator key.
            "export TPUFT_SLICE_GEN=\"${JOBSET_RESTART_ATTEMPT:-0}\"",
            f"exec {user}",
        ]
    )


def jobset_spec(
    cmd: List[str],
    *,
    name: str = "tpuft",
    num_groups: int = 2,
    hosts_per_group: int = 1,
    image: str = "REPLACE_ME_IMAGE",
    tpu_accelerator: str = "tpu-v5-lite-podslice",
    tpu_topology: str = "2x4",
    chips_per_host: int = 4,
    max_restarts: int = 10,
    min_replicas: int = 1,
    env: Optional[Dict[str, str]] = None,
) -> dict:
    """Renders the launch env contract as a JobSet manifest (a dict ready
    for YAML/JSON serialization).

    Args mirror the reference component's knobs (replicas /
    workers_per_replica / max_restarts / image, torchft/torchx.py:11-24)
    plus the TPU slice shape GKE schedules on.
    """
    if num_groups < 1 or hosts_per_group < 1:
        raise ValueError("num_groups and hosts_per_group must be >= 1")
    if not cmd:
        raise ValueError("cmd must be the replica-group argv")

    lighthouse_addr = f"{name}-lighthouse-0-0.{name}:{_LIGHTHOUSE_PORT}"
    common_env = [
        {"name": "NUM_REPLICA_GROUPS", "value": str(num_groups)},
        {"name": "TPUFT_NUM_HOSTS", "value": str(hosts_per_group)},
        {"name": "TPUFT_LIGHTHOUSE", "value": lighthouse_addr},
        {
            "name": "TPUFT_GROUP_INDEX",
            "valueFrom": {
                "fieldRef": {
                    "fieldPath": "metadata.annotations['jobset.sigs.k8s.io/job-index']"
                }
            },
        },
        # The JobSet controller stamps its restart counter on every pod as
        # the restart-attempt annotation (there is no JOBSET_RESTART_ATTEMPT
        # env var injected by anything); surfacing it through the downward
        # API is what makes the worker script's TPUFT_SLICE_GEN a real
        # generation instead of a constant 0.
        {
            "name": "JOBSET_RESTART_ATTEMPT",
            "valueFrom": {
                "fieldRef": {
                    "fieldPath": "metadata.annotations['jobset.sigs.k8s.io/restart-attempt']"
                }
            },
        },
    ] + [{"name": k, "value": v} for k, v in (env or {}).items()]

    worker_job = {
        "name": "group",
        "replicas": num_groups,
        "template": {
            "spec": {
                "backoffLimit": max_restarts,
                "completions": hosts_per_group,
                "parallelism": hosts_per_group,
                "completionMode": "Indexed",
                "template": {
                    "spec": {
                        "restartPolicy": "Never",
                        "nodeSelector": {
                            "cloud.google.com/gke-tpu-accelerator": tpu_accelerator,
                            "cloud.google.com/gke-tpu-topology": tpu_topology,
                        },
                        "containers": [
                            {
                                "name": "worker",
                                "image": image,
                                "command": ["/bin/sh", "-c"],
                                "args": [_worker_script(cmd, name)],
                                "env": common_env,
                                "ports": [
                                    {"containerPort": _STORE_PORT},
                                    {"containerPort": _MASTER_PORT},
                                ],
                                "resources": {
                                    "limits": {"google.com/tpu": chips_per_host}
                                },
                            }
                        ],
                    }
                },
            }
        },
    }

    lighthouse_job = {
        "name": "lighthouse",
        "replicas": 1,
        "template": {
            "spec": {
                "backoffLimit": max_restarts,
                "completions": 1,
                "parallelism": 1,
                "completionMode": "Indexed",
                "template": {
                    "spec": {
                        "restartPolicy": "Never",
                        "containers": [
                            {
                                "name": "lighthouse",
                                "image": image,
                                "command": [
                                    "python",
                                    "-m",
                                    "torchft_tpu.lighthouse_cli",
                                    "--bind",
                                    f"[::]:{_LIGHTHOUSE_PORT}",
                                    "--min_replicas",
                                    str(min_replicas),
                                ],
                                "ports": [{"containerPort": _LIGHTHOUSE_PORT}],
                            }
                        ],
                    }
                },
            }
        },
    }

    return {
        "apiVersion": "jobset.x-k8s.io/v1alpha2",
        "kind": "JobSet",
        "metadata": {"name": name},
        "spec": {
            # JobSet restart semantics are TWO-LEVEL, and this policy is
            # the outer level: a pod that dies is first retried inside its
            # own child Job up to that Job's backoffLimit (set above to
            # max_restarts) — during those retries the other groups keep
            # training and the restarted group heals live, which is the
            # common path.  Only when a child Job FAILS outright (pod
            # retries exhausted) does this failurePolicy act, and its
            # default action recreates the WHOLE JobSet (all groups, the
            # lighthouse included) up to maxRestarts times, bumping the
            # restart-attempt annotation that becomes TPUFT_SLICE_GEN —
            # a full cold start recovered via disk checkpoints, not live
            # healing.
            "failurePolicy": {"maxRestarts": max_restarts},
            "network": {"enableDNSHostnames": True},
            "replicatedJobs": [lighthouse_job, worker_job],
        },
    }


def dump_yaml(spec: dict) -> str:
    import yaml

    return yaml.safe_dump(spec, sort_keys=False, default_flow_style=False)
