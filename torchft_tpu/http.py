"""IPv6 threading HTTP server with a deep accept queue.

Reference parity: torchft/http.py:5-7.
"""

import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional


class ThreadingHTTPServerV6(ThreadingHTTPServer):
    address_family = socket.AF_INET6
    request_queue_size = 1024
    daemon_threads = True


def serve_text_exposition(
    render: Callable[[], str],
    port: int,
    bind: str = "::1",
    path: str = "/metrics",
    thread_name: str = "tpuft_metrics",
) -> Optional[ThreadingHTTPServerV6]:
    """Starts a daemon HTTP server answering ``GET <path>`` with
    ``render()``'s text (Prometheus exposition content type) — THE shared
    scaffolding of every Python-side metrics endpoint, so v6 handling and
    accept-queue behavior cannot drift between them.  ``bind`` defaults to
    loopback: the endpoints are unauthenticated, so wider binds are an
    explicit operator choice.  Returns the server (its bound port is
    ``server.server_address[1]``) or None on any failure — metrics must
    never be able to fail training."""
    try:
        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib API
                if self.path != path:
                    self.send_response(404)
                    self.end_headers()
                    return
                body = render().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-scrape stderr
                pass

        server = ThreadingHTTPServerV6((bind, port), Handler)
        threading.Thread(
            target=server.serve_forever, name=thread_name, daemon=True
        ).start()
        return server
    except Exception:  # noqa: BLE001 — see docstring
        return None
