"""IPv6 threading HTTP server with a deep accept queue.

Reference parity: torchft/http.py:5-7.
"""

import socket
from http.server import ThreadingHTTPServer


class ThreadingHTTPServerV6(ThreadingHTTPServer):
    address_family = socket.AF_INET6
    request_queue_size = 1024
    daemon_threads = True
