"""Flash attention: pallas TPU forward + backward kernels.

Design notes (MXU/HBM-minded):
  - forward streams K/V blocks through VMEM with the classic online-softmax
    accumulator, so HBM traffic is O(S*D) instead of materializing the
    O(S^2) score matrix;
  - the log-sum-exp per query row is saved, and the backward pass recomputes
    scores blockwise from (q, k, lse) — the flash recompute trade: extra
    FLOPs on the MXU instead of an O(S^2) residual in HBM.  On TPU the
    backward is ONE merged pallas kernel for typical shapes (q axis
    innermost; dk/dv accumulate in VMEM scratch, dq is emitted as
    per-kv-block f32 partials in HBM and summed in XLA — the s/p/dp/ds
    tile work that dominates on the VPU is computed once).  When num_k
    exceeds _DQ_PARTIAL_MAX_K the partials' (num_k, BH, S, D) transient
    would dwarf dq itself, so long-context shapes switch to two passes
    (dk/dv with q innermost, dq with kv innermost, both O(S*D) memory).
    Off-TPU the same math is expressed in XLA with the scores
    materialized;
  - grid layout (batch*heads, outer_blocks, inner_blocks) with the
    reduction axis innermost: TPU executes the innermost grid dimension
    sequentially, which is what makes the VMEM scratch accumulator legal.

Falls back to reference XLA attention off-TPU (CPU test mesh) or for shapes
the kernel does not tile (seq not divisible by the block size).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from torchft_tpu.ops._pallas_util import row_stat_col

_NEG_INF = -1e30
_LANE = 128  # TPU lane width: scratch row-stats are kept (block_q, 128)


def _use_pallas(seq_q: int, seq_k: int, head_dim: int) -> bool:
    try:
        if jax.default_backend() != "tpu":
            return False
    except Exception:  # noqa: BLE001
        return False
    bq, bk = _block_sizes(seq_q, seq_k)
    return (
        seq_q % bq == 0
        and seq_k % bk == 0
        and head_dim % _LANE == 0
    )


def _block_sizes(seq_q: int, seq_k: int) -> Tuple[int, int]:
    # 512x512: these kernels are VPU-bound on the S^2 elementwise tile, so
    # the finest block that keeps the MXU fed wins — fatter q blocks were
    # measured slower because causal masking can only skip whole blocks
    # (a 1024-row block straddling the diagonal computes 33% more masked
    # elements at the flagship seq=1024 than two 512-row blocks).
    return min(512, seq_q), min(512, seq_k)


def _fa_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, scale: float, causal: bool, block_q: int, block_k: int, num_k: int,
):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Causal: kv blocks strictly above the diagonal contribute nothing.
    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _step():
        # Matmuls run in the INPUT dtype with f32 accumulation: bf16 model
        # activations hit the MXU at full rate (an f32xf32 matmul runs at a
        # fraction of it); softmax statistics stay f32 throughout.
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_k] f32
        if causal:
            # Unconditional mask: branching per block via lax.cond measured
            # ~3 ms/step SLOWER than these VPU passes (Mosaic conditional
            # overhead exceeds the saved work at flagship shapes).
            rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)

        m_prev = m_scr[:, :1]                      # [block_q, 1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_cur)                     # [block_q, block_k]
        alpha = jnp.exp(m_prev - m_cur)            # rescale old accumulator
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        # Partial column stores: broadcasting the stats across the full
        # (block_q, 128) scratch measured ~19% of the kernel.
        m_scr[:, 0:1] = m_cur
        l_scr[:, 0:1] = l_new

    @pl.when(ki == num_k - 1)
    def _emit():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / safe_l).astype(o_ref.dtype)
        # lse output is lane-padded to (block_q, _LANE) to satisfy TPU tiling.
        lse_ref[0] = jnp.broadcast_to(
            m_scr[:, :1] + jnp.log(safe_l), lse_ref.shape[1:]
        ).astype(lse_ref.dtype)


def _fa_pallas_call(q, k, v, scale: float, causal: bool, interpret: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    block_q, block_k = _block_sizes(seq_q, seq_k)
    num_k = seq_k // block_k
    grid = (bh, seq_q // block_q, num_k)
    kernel = functools.partial(
        _fa_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, num_k=num_k,
    )
    out, lse_padded = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, seq_q, _LANE), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANE), lambda b, i, j: (b, i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANE), jnp.float32),  # running max
            pltpu.VMEM((block_q, _LANE), jnp.float32),  # running sum
            pltpu.VMEM((block_q, d), jnp.float32),      # output accumulator
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse_padded[:, :, 0]


# Above this many kv blocks the merged backward's per-kv-block dq partials
# ((num_k, BH, S, D) f32 transient in HBM) cost more than a second
# recompute pass; long-context shapes switch to the two-kernel form.
_DQ_PARTIAL_MAX_K = 4


def _bwd_block(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qi, ki,
               *, scale, causal, block_q, block_k):
    """Shared flash-backward block body: recomputes p and ds for the
    (q-block qi, kv-block ki) tile.  Matmul operands stay in the input
    dtype (bf16 on the model path = full MXU rate); probabilities and
    statistics are f32.  Returns (p, ds) with ds cast to the input dtype
    for the downstream MXU products."""
    q = q_ref[0]
    k = k_ref[0]
    do = do_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale                                   # [block_q, block_k] f32
    p = jnp.exp(s - row_stat_col(lse_ref, qi, block_q))
    if causal:
        rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        p = jnp.where(rows >= cols, p, 0.0)
    dp = jax.lax.dot_general(
        do, v_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                           # [block_q, block_k]
    ds = (p * (dp - row_stat_col(delta_ref, qi, block_q)) * scale).astype(q.dtype)
    return p, ds


def _fa_bwd_dkdv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *rest,
    scale: float, causal: bool, block_q: int, block_k: int, num_q: int,
    emit_dq: bool,
):
    """Flash backward with the q axis innermost: dk/dv accumulate in VMEM
    scratch across the sequential inner q dimension.  With emit_dq (the
    merged one-pass form for typical shapes) the dq contribution of this
    kv block is additionally emitted to a per-kv-block f32 partial (one
    visit per output block, summed in XLA) — the s/p/dp/ds tile work that
    dominates on the VPU is then computed once instead of twice."""
    from jax.experimental import pallas as pl

    if emit_dq:
        dqp_ref, dk_scr, dv_scr = rest
    else:
        dk_scr, dv_scr = rest

    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    # Causal: a q block strictly above this kv block's diagonal contributes
    # nothing — but its dq partial (if any) must still be zeroed.
    run = (qi * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(run)
    def _step():
        p, ds = _bwd_block(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qi, ki,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        )
        do = do_ref[0]
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                           # p^T @ do: [block_k, d]
        dk_scr[...] += jax.lax.dot_general(
            ds, q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                           # ds^T @ q: [block_k, d]
        if emit_dq:
            dqp_ref[0, 0] = jax.lax.dot(
                ds, k_ref[0], preferred_element_type=jnp.float32
            ).astype(dqp_ref.dtype)                 # ds @ k: [block_q, d]

    if emit_dq and causal:
        @pl.when(jnp.logical_not(run))
        def _zero():
            dqp_ref[0, 0] = jnp.zeros_like(dqp_ref[0, 0])

    @pl.when(qi == num_q - 1)
    def _emit():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _fa_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
    *, scale: float, causal: bool, block_q: int, block_k: int, num_k: int,
):
    """dq-only pass for the long-context form, kv axis innermost: dq
    accumulates in f32 VMEM scratch, so memory stays O(S*D) regardless of
    num_k (at the price of recomputing p/ds once more)."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _step():
        _, ds = _bwd_block(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qi, ki,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        )
        dq_scr[...] += jax.lax.dot(
            ds, k_ref[0], preferred_element_type=jnp.float32
        )

    @pl.when(ki == num_k - 1)
    def _emit():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _fa_bwd_pallas(q, k, v, o, lse, g, scale: float, causal: bool,
                   interpret: bool = False):
    """Flash backward on TPU; q/k/v/o/g: [BH, S, D], lse: [BH, S] f32."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    block_q, block_k = _block_sizes(seq_q, seq_k)
    num_q, num_k = seq_q // block_q, seq_k // block_k
    # Row stats as [BH, 1, S]: whole row per visit (4 KB).  delta_i =
    # rowsum(do * o) is O(S*D) and computed once here instead of per tile.
    lse = lse[:, None, :]
    delta = jnp.sum(
        g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )[:, None, :]

    qo_spec_ji = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0))
    kv_spec_ji = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))
    row_spec_ji = pl.BlockSpec((1, 1, seq_q), lambda b, j, i: (b, 0, 0))
    in_specs_ji = [qo_spec_ji, kv_spec_ji, kv_spec_ji, qo_spec_ji,
                   row_spec_ji, row_spec_ji]
    dkdv_scratch = [
        pltpu.VMEM((block_k, d), jnp.float32),
        pltpu.VMEM((block_k, d), jnp.float32),
    ]
    merged = num_k <= _DQ_PARTIAL_MAX_K
    out_shape = [
        jax.ShapeDtypeStruct(k.shape, k.dtype),
        jax.ShapeDtypeStruct(v.shape, v.dtype),
    ]
    out_specs = [kv_spec_ji, kv_spec_ji]
    if merged:
        # dq as f32 per-kv-block partials: the cross-block sum loses no
        # precision vs the f32 XLA backward this replaced.
        out_shape.append(
            jax.ShapeDtypeStruct(
                (num_k, bh, seq_q, d), q.dtype if num_k == 1 else jnp.float32
            )
        )
        out_specs.append(
            pl.BlockSpec((1, 1, block_q, d), lambda b, j, i: (j, b, i, 0))
        )
    outs = pl.pallas_call(
        functools.partial(
            _fa_bwd_dkdv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, num_q=num_q, emit_dq=merged,
        ),
        out_shape=tuple(out_shape),
        grid=(bh, num_k, num_q),
        in_specs=in_specs_ji,
        out_specs=tuple(out_specs),
        scratch_shapes=dkdv_scratch,
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    if merged:
        dk, dv, dq_part = outs
        if num_k == 1:
            dq = dq_part[0]
        else:
            dq = jnp.sum(dq_part, axis=0).astype(q.dtype)
        return dq, dk, dv
    dk, dv = outs

    # Long-context second pass: dq with the kv axis innermost.
    qo_spec_ij = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    kv_spec_ij = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))
    row_spec_ij = pl.BlockSpec((1, 1, seq_q), lambda b, i, j: (b, 0, 0))
    dq = pl.pallas_call(
        functools.partial(
            _fa_bwd_dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, num_k=num_k,
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(bh, num_q, num_k),
        in_specs=[qo_spec_ij, kv_spec_ij, kv_spec_ij, qo_spec_ij,
                  row_spec_ij, row_spec_ij],
        out_specs=qo_spec_ij,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


def _fa_reference(q, k, v, scale: float, causal: bool):
    """Stable XLA attention returning (out, lse); q/k/v: [BH, S, D]."""
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * scale
    if causal:
        seq_q, seq_k = s.shape[-2], s.shape[-1]
        rows = jax.lax.broadcasted_iota(jnp.int32, (seq_q, seq_k), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (seq_q, seq_k), 1)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bqk,bkd->bqd", (p / l).astype(v.dtype), v)
    lse = (m + jnp.log(l))[..., 0]
    return o.astype(q.dtype), lse


def _fa_forward(q, k, v, scale: float, causal: bool):
    if _use_pallas(q.shape[1], k.shape[1], q.shape[2]):
        return _fa_pallas_call(q, k, v, scale, causal)
    return _fa_reference(q, k, v, scale, causal)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, scale: float, causal: bool):
    o, _ = _fa_forward(q, k, v, scale, causal)
    return o


def _flash_fwd(q, k, v, scale, causal):
    o, lse = _fa_forward(q, k, v, scale, causal)
    return o, (q, k, v, o, lse)


def _flash_bwd(scale, causal, res, g):
    q, k, v, o, lse = res
    if _use_pallas(q.shape[1], k.shape[1], q.shape[2]):
        return _fa_bwd_pallas(q, k, v, o, lse, g, scale, causal)
    return _fa_bwd_xla(q, k, v, o, lse, g, scale, causal)


def _fa_bwd_xla(q, k, v, o, lse, g, scale, causal):
    """Off-TPU backward: same math with the scores materialized in XLA.
    Also the oracle the pallas backward kernels are tested against."""
    qf, kf, vf, gf = (t.astype(jnp.float32) for t in (q, k, v, g))
    s = jnp.einsum("bqd,bkd->bqk", qf, kf) * scale
    if causal:
        seq_q, seq_k = s.shape[-2], s.shape[-1]
        rows = jax.lax.broadcasted_iota(jnp.int32, (seq_q, seq_k), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (seq_q, seq_k), 1)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    p = jnp.exp(s - lse[..., None])                     # recompute softmax
    dv = jnp.einsum("bqk,bqd->bkd", p, gf)
    dp = jnp.einsum("bqd,bkd->bqk", gf, vf)
    delta = jnp.sum(gf * o.astype(jnp.float32), axis=-1, keepdims=True)
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("bqk,bkd->bqd", ds, kf)
    dk = jnp.einsum("bqk,bqd->bkd", ds, qf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: float | None = None,
) -> jax.Array:
    """Multi-head attention; q: [B, Hq, S, D], k/v: [B, Hkv, S, D].

    GQA: Hkv may divide Hq; kv heads are broadcast to query groups.
    """
    b, hq, sq, d = q.shape
    hkv = k.shape[1]
    if hkv != hq:
        assert hq % hkv == 0, "query heads must be a multiple of kv heads"
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    scale = scale if scale is not None else d ** -0.5
    out = _flash(
        q.reshape(b * hq, sq, d),
        k.reshape(b * hq, k.shape[2], d),
        v.reshape(b * hq, v.shape[2], d),
        scale,
        causal,
    )
    return out.reshape(b, hq, sq, d)
