"""Flash attention: pallas TPU forward kernel + flash-style XLA backward.

Design notes (MXU/HBM-minded):
  - forward streams K/V blocks through VMEM with the classic online-softmax
    accumulator, so HBM traffic is O(S*D) instead of materializing the
    O(S^2) score matrix;
  - the log-sum-exp per query row is saved, and the backward pass recomputes
    scores blockwise in XLA from (q, k, lse) — the flash recompute trade:
    extra FLOPs on the MXU instead of an O(S^2) residual in HBM;
  - grid layout (batch*heads, q_blocks, kv_blocks) with the kv axis
    innermost: TPU executes the innermost grid dimension sequentially, which
    is what makes the VMEM scratch accumulator across kv blocks legal.

Falls back to reference XLA attention off-TPU (CPU test mesh) or for shapes
the kernel does not tile (seq not divisible by the block size).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

_NEG_INF = -1e30
_LANE = 128  # TPU lane width: scratch row-stats are kept (block_q, 128)


def _use_pallas(seq_q: int, seq_k: int, head_dim: int) -> bool:
    try:
        if jax.default_backend() != "tpu":
            return False
    except Exception:  # noqa: BLE001
        return False
    bq, bk = _block_sizes(seq_q, seq_k)
    return (
        seq_q % bq == 0
        and seq_k % bk == 0
        and head_dim % _LANE == 0
    )


def _block_sizes(seq_q: int, seq_k: int) -> Tuple[int, int]:
    return min(512, seq_q), min(512, seq_k)


def _fa_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, scale: float, causal: bool, block_q: int, block_k: int, num_k: int,
):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Causal: kv blocks strictly above the diagonal contribute nothing.
    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_k]
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)

        m_prev = m_scr[:, :1]                      # [block_q, 1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_cur)                     # [block_q, block_k]
        alpha = jnp.exp(m_prev - m_cur)            # rescale old accumulator
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        m_scr[...] = jnp.broadcast_to(m_cur, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == num_k - 1)
    def _emit():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / safe_l).astype(o_ref.dtype)
        # lse output is lane-padded to (block_q, _LANE) to satisfy TPU tiling.
        lse_ref[0] = jnp.broadcast_to(
            m_scr[:, :1] + jnp.log(safe_l), lse_ref.shape[1:]
        ).astype(lse_ref.dtype)


def _fa_pallas_call(q, k, v, scale: float, causal: bool, interpret: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    block_q, block_k = _block_sizes(seq_q, seq_k)
    num_k = seq_k // block_k
    grid = (bh, seq_q // block_q, num_k)
    kernel = functools.partial(
        _fa_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, num_k=num_k,
    )
    out, lse_padded = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, seq_q, _LANE), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANE), lambda b, i, j: (b, i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANE), jnp.float32),  # running max
            pltpu.VMEM((block_q, _LANE), jnp.float32),  # running sum
            pltpu.VMEM((block_q, d), jnp.float32),      # output accumulator
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse_padded[:, :, 0]


def _fa_reference(q, k, v, scale: float, causal: bool):
    """Stable XLA attention returning (out, lse); q/k/v: [BH, S, D]."""
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * scale
    if causal:
        seq_q, seq_k = s.shape[-2], s.shape[-1]
        rows = jax.lax.broadcasted_iota(jnp.int32, (seq_q, seq_k), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (seq_q, seq_k), 1)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bqk,bkd->bqd", (p / l).astype(v.dtype), v)
    lse = (m + jnp.log(l))[..., 0]
    return o.astype(q.dtype), lse


def _fa_forward(q, k, v, scale: float, causal: bool):
    if _use_pallas(q.shape[1], k.shape[1], q.shape[2]):
        return _fa_pallas_call(q, k, v, scale, causal)
    return _fa_reference(q, k, v, scale, causal)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, scale: float, causal: bool):
    o, _ = _fa_forward(q, k, v, scale, causal)
    return o


def _flash_fwd(q, k, v, scale, causal):
    o, lse = _fa_forward(q, k, v, scale, causal)
    return o, (q, k, v, o, lse)


def _flash_bwd(scale, causal, res, g):
    q, k, v, o, lse = res
    qf, kf, vf, gf = (t.astype(jnp.float32) for t in (q, k, v, g))
    s = jnp.einsum("bqd,bkd->bqk", qf, kf) * scale
    if causal:
        seq_q, seq_k = s.shape[-2], s.shape[-1]
        rows = jax.lax.broadcasted_iota(jnp.int32, (seq_q, seq_k), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (seq_q, seq_k), 1)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    p = jnp.exp(s - lse[..., None])                     # recompute softmax
    dv = jnp.einsum("bqk,bqd->bkd", p, gf)
    dp = jnp.einsum("bqd,bkd->bqk", gf, vf)
    delta = jnp.sum(gf * o.astype(jnp.float32), axis=-1, keepdims=True)
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("bqk,bkd->bqd", ds, kf)
    dk = jnp.einsum("bqk,bqd->bkd", ds, qf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: float | None = None,
) -> jax.Array:
    """Multi-head attention; q: [B, Hq, S, D], k/v: [B, Hkv, S, D].

    GQA: Hkv may divide Hq; kv heads are broadcast to query groups.
    """
    b, hq, sq, d = q.shape
    hkv = k.shape[1]
    if hkv != hq:
        assert hq % hkv == 0, "query heads must be a multiple of kv heads"
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    scale = scale if scale is not None else d ** -0.5
    out = _flash(
        q.reshape(b * hq, sq, d),
        k.reshape(b * hq, k.shape[2], d),
        v.reshape(b * hq, v.shape[2], d),
        scale,
        causal,
    )
    return out.reshape(b, hq, sq, d)
