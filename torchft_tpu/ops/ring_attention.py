"""Ring attention: causal attention over a sequence-sharded mesh axis.

Long-context path: Q/K/V are sharded along sequence across the ``sequence``
mesh axis; each device keeps its Q shard resident and K/V shards rotate
around the ring with ``lax.ppermute`` (one neighbor hop per step — this is
ICI-topology-friendly: traffic only crosses adjacent links).  Blockwise
attention per incoming K/V shard is merged with the running accumulator via
the online log-sum-exp recurrence, so no device ever materializes more than
one [S_local x S_local] score block.

Reference: the torchft reference has no sequence parallelism (SURVEY.md
§2.3); this is a capability the TPU build adds because long-context is
first-class here.  Algorithm: Ring Attention (arXiv:2310.01889).

Two sequence layouts:

- ``contiguous`` (default): device i holds positions [i*S/N, (i+1)*S/N).
  Simple, but causal skipping is imbalanced: the device below the diagonal
  does up to ~2x the work of the one above (the ring's wall-clock is the
  max, not the mean).
- ``zigzag``: the sequence is split into 2N chunks and device i holds
  chunks (i, 2N-1-i) — one early, one late.  Causal work is then EXACTLY
  balanced, and off-diagonal rounds need no masking at all: with incoming
  K/V from source j, either j < i and the local Q (both chunks) attends
  only j's early chunk, or j > i and only the local late chunk attends
  both of j's chunks — either way half a block of unmasked work per round
  on every device.  Callers permute the sequence once with
  ``zigzag_permutation`` / ``to_zigzag`` (and permute targets/positions
  identically); attention output comes back in the same zigzag order.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _block_attn(q, k, v, scale, row0, col0, causal):
    """One [Sq_local x Sk_local] attention block with global causal masking.

    Returns unnormalized out, running max m and sum l (stats f32).
    q/k/v: [BH, S, D] in the INPUT dtype — matmuls run at bf16 MXU rate on
    the model path with f32 accumulation; row0/col0: global block offsets.
    """
    s = jnp.einsum("bqd,bkd->bqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        rows = row0 + jax.lax.broadcasted_iota(jnp.int32, s.shape[-2:], 0)
        cols = col0 + jax.lax.broadcasted_iota(jnp.int32, s.shape[-2:], 1)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    # Rows with every position masked: exp(-inf - -inf) traps; clamp m.
    m_safe = jnp.maximum(m, -1e29)
    p = jnp.exp(s - m_safe)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum(
        "bqk,bkd->bqd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    )
    return o, m_safe, l


def zigzag_permutation(seq_len: int, n_shards: int):
    """Positions (original order) in zigzag order, as a numpy int array.

    ``x[..., perm, ...]`` reorders a sequence axis so a plain contiguous
    shard over ``n_shards`` devices gives device i the original chunks
    (i, 2N-1-i).  Apply the same permutation to targets / position ids;
    invert with ``inverse_zigzag_permutation``."""
    import numpy as np

    if seq_len % (2 * n_shards) != 0:
        raise ValueError(
            f"zigzag needs seq_len divisible by 2*n_shards, got {seq_len} vs "
            f"{n_shards}"
        )
    c = seq_len // (2 * n_shards)
    chunks = []
    for i in range(n_shards):
        chunks.append(np.arange(i * c, (i + 1) * c))
        j = 2 * n_shards - 1 - i
        chunks.append(np.arange(j * c, (j + 1) * c))
    return np.concatenate(chunks)


def inverse_zigzag_permutation(seq_len: int, n_shards: int):
    """Inverse of ``zigzag_permutation``: maps zigzag order back to the
    original sequence order."""
    import numpy as np

    perm = zigzag_permutation(seq_len, n_shards)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(seq_len)
    return inv


def to_zigzag(x: jax.Array, n_shards: int, axis: int) -> jax.Array:
    """Permute a sequence axis into zigzag order (host-level, before
    sharding)."""
    return jnp.take(x, zigzag_permutation(x.shape[axis], n_shards), axis=axis)


def from_zigzag(x: jax.Array, n_shards: int, axis: int) -> jax.Array:
    """Undo ``to_zigzag``: permute a zigzag-ordered sequence axis back to
    the original order."""
    return jnp.take(
        x, inverse_zigzag_permutation(x.shape[axis], n_shards), axis=axis
    )


def _merge(acc, m, l, o_t, m_t, l_t):
    """Online log-sum-exp merge of one block contribution."""
    m_new = jnp.maximum(m, m_t)
    alpha = jnp.exp(m - m_new)
    beta = jnp.exp(m_t - m_new)
    return acc * alpha + o_t * beta, m_new, l * alpha + l_t * beta


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    axis_size: int,
    causal: bool = True,
    scale: Optional[float] = None,
    layout: str = "contiguous",
) -> jax.Array:
    """Local ring-attention body — call inside shard_map.

    q/k/v: the local sequence shards, [B, H, S_local, D] (kv heads must
    already match q heads — broadcast GQA groups before sharding).
    layout: 'contiguous' or 'zigzag' (see module docstring; zigzag expects
    the caller to have permuted the sequence with to_zigzag and equalizes
    causal work across the ring).
    """
    if layout == "zigzag" and causal:
        return _ring_attention_zigzag(q, k, v, axis_name, axis_size, scale)
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown ring layout {layout!r}")
    # Non-causal attention is position-independent, so the zigzag layout
    # needs no special schedule: every block is unmasked either way.
    b, h, s_local, d = q.shape
    scale = scale if scale is not None else d ** -0.5
    idx = jax.lax.axis_index(axis_name)

    qf = q.reshape(b * h, s_local, d)
    kf = k.reshape(b * h, s_local, d)
    vf = v.reshape(b * h, s_local, d)

    row0 = idx * s_local
    acc = jnp.zeros((b * h, s_local, d), jnp.float32)
    m = jnp.full((b * h, s_local, 1), _NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((b * h, s_local, 1), dtype=jnp.float32)

    # axis_size is static: unrolled ring. Step t sees the K/V block that
    # started life on device (idx - t) mod n.
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    for t in range(axis_size):
        col_block = (idx - t) % axis_size

        def do_block(kf=kf, vf=vf, col_block=col_block):
            return _block_attn(
                qf, kf, vf, scale, row0, col_block * s_local, causal
            )

        if causal:
            # A K/V block strictly above this Q shard's diagonal is fully
            # masked — skip its two matmuls entirely (the contiguous layout
            # gives some devices more skips than others; zigzag balancing
            # is the known future fix, see module docstring).
            # Skip-branch outputs are derived from the (mesh-varying) q
            # shard so both cond branches have the same varying-axes type
            # under shard_map.
            zero_col = (0.0 * qf[..., :1]).astype(jnp.float32)
            o_t, m_t, l_t = jax.lax.cond(
                col_block > idx,
                lambda: (
                    (0.0 * qf).astype(jnp.float32),
                    zero_col + _NEG_INF / 10,
                    zero_col,
                ),
                do_block,
            )
        else:
            o_t, m_t, l_t = do_block()
        m_new = jnp.maximum(m, m_t)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_t - m_new)
        acc = acc * alpha + o_t * beta
        l = l * alpha + l_t * beta
        m = m_new
        if t != axis_size - 1:
            kf = jax.lax.ppermute(kf, axis_name, perm)
            vf = jax.lax.ppermute(vf, axis_name, perm)

    out = acc / jnp.where(l == 0.0, 1.0, l)
    return out.reshape(b, h, s_local, d).astype(q.dtype)


def _ring_attention_zigzag(q, k, v, axis_name, axis_size, scale):
    """Balanced causal ring body for the zigzag layout.

    Device i's local [2c] sequence is (early chunk i, late chunk 2N-1-i) of
    the zigzag-permuted global order.  Visibility is static per round:

      t = 0      : early-vs-early causal, late-vs-(early|late-causal);
      t > 0, j<i : BOTH local q chunks see ONLY the incoming early chunk
                   (the incoming late chunk 2N-1-j is later than every
                   local position) — one unmasked [2c x c] block;
      t > 0, j>i : ONLY the local late chunk sees the full incoming pair
                   (the local early chunk i precedes both) — one unmasked
                   [c x 2c] block.

    Every round is exactly half a block on every device, so the ring's
    wall-clock equals its mean work (the contiguous layout's max/mean is
    ~2x at large N).
    """
    b, h, s_local, d = q.shape
    scale = scale if scale is not None else d ** -0.5
    if s_local % 2 != 0:
        raise ValueError("zigzag layout needs an even local sequence length")
    c = s_local // 2
    idx = jax.lax.axis_index(axis_name)

    qf = q.reshape(b * h, s_local, d)
    kf = k.reshape(b * h, s_local, d)
    vf = v.reshape(b * h, s_local, d)
    qa, qb = qf[:, :c], qf[:, c:]

    # The two local chunks keep SEPARATE accumulators: j>i rounds touch only
    # the late chunk, so padded full-row merges / concatenations per round
    # would be pure overhead (measured 1.5x total work on the layout bench).
    accA = jnp.zeros((b * h, c, d), jnp.float32)
    mA = jnp.full((b * h, c, 1), _NEG_INF, dtype=jnp.float32)
    lA = jnp.zeros((b * h, c, 1), dtype=jnp.float32)
    accB, mB, lB = accA, mA, lA
    # Neutral merge element for the early chunk on j>i rounds, derived from
    # the (mesh-varying) q shard so both cond branches carry the same
    # varying-axes type under shard_map (same trick as the contiguous path).
    zero_col = (0.0 * qa[..., :1]).astype(jnp.float32)
    neutral = ((0.0 * qa).astype(jnp.float32), zero_col + _NEG_INF / 10, zero_col)

    # t = 0: the diagonal.  Early rows vs early cols is plain causal; late
    # rows see all of early plus causal-within-late, which is exactly the
    # rows>=cols mask with rows offset by c (late positions follow early
    # ones in the original order regardless of i).
    o_aa, m_aa, l_aa = _block_attn(qa, kf[:, :c], vf[:, :c], scale, 0, 0, True)
    accA, mA, lA = _merge(accA, mA, lA, o_aa, m_aa, l_aa)
    o_b, m_b, l_b = _block_attn(qb, kf, vf, scale, c, 0, True)
    accB, mB, lB = _merge(accB, mB, lB, o_b, m_b, l_b)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    for t in range(1, axis_size):
        kf = jax.lax.ppermute(kf, axis_name, perm)
        vf = jax.lax.ppermute(vf, axis_name, perm)
        j = (idx - t) % axis_size

        def earlier_source(kf=kf, vf=vf):
            # j < i: both local chunks are later than j's early chunk and
            # earlier than j's late chunk — attend the early half only.
            ka, va = kf[:, :c], vf[:, :c]
            return (
                _block_attn(qa, ka, va, scale, 0, 0, False)
                + _block_attn(qb, ka, va, scale, 0, 0, False)
            )

        def later_source(kf=kf, vf=vf):
            # j > i: only the local late chunk (2N-1-i) postdates both of
            # j's chunks (j and 2N-1-j, since j > i <=> 2N-1-j < 2N-1-i);
            # the early chunk contributes nothing (neutral merge, O(c*d)).
            return neutral + _block_attn(qb, kf, vf, scale, 0, 0, False)

        oa, ma, la, ob, mb, lb = jax.lax.cond(j < idx, earlier_source, later_source)
        accA, mA, lA = _merge(accA, mA, lA, oa, ma, la)
        accB, mB, lB = _merge(accB, mB, lB, ob, mb, lb)

    out = jnp.concatenate(
        [accA / jnp.where(lA == 0.0, 1.0, lA), accB / jnp.where(lB == 0.0, 1.0, lB)],
        axis=1,
    )
    return out.reshape(b, h, s_local, d).astype(q.dtype)


def ring_attention_sharded(
    mesh,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    batch_axis: str = "data",
    head_axis: str = "tensor",
    seq_axis: str = "sequence",
    layout: str = "contiguous",
):
    """shard_map wrapper: batch over `batch_axis`, heads over `head_axis`,
    sequence ring over `seq_axis`.  With layout='zigzag' the inputs must
    already be in zigzag order along the sequence axis (``to_zigzag``);
    the output is returned in the same order."""
    from jax.sharding import PartitionSpec as P

    from torchft_tpu.ops._shard_map import shard_map

    axis_size = mesh.shape[seq_axis]
    spec = P(batch_axis, head_axis, seq_axis, None)
    fn = shard_map(
        functools.partial(
            ring_attention,
            axis_name=seq_axis,
            axis_size=axis_size,
            causal=causal,
            scale=scale,
            layout=layout,
        ),
        mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
