"""Ring attention: causal attention over a sequence-sharded mesh axis.

Long-context path: Q/K/V are sharded along sequence across the ``sequence``
mesh axis; each device keeps its Q shard resident and K/V shards rotate
around the ring with ``lax.ppermute`` (one neighbor hop per step — this is
ICI-topology-friendly: traffic only crosses adjacent links).  Blockwise
attention per incoming K/V shard is merged with the running accumulator via
the online log-sum-exp recurrence, so no device ever materializes more than
one [S_local x S_local] score block.

Reference: the torchft reference has no sequence parallelism (SURVEY.md
§2.3); this is a capability the TPU build adds because long-context is
first-class here.  Algorithm: Ring Attention (arXiv:2310.01889) with plain
contiguous sequence partitioning (the causal-skip load imbalance is accepted
for simplicity; a zigzag layout is a future optimization).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _block_attn(q, k, v, scale, row0, col0, causal):
    """One [Sq_local x Sk_local] attention block with global causal masking.

    Returns unnormalized out, running max m and sum l (stats f32).
    q/k/v: [BH, S, D] in the INPUT dtype — matmuls run at bf16 MXU rate on
    the model path with f32 accumulation; row0/col0: global block offsets.
    """
    s = jnp.einsum("bqd,bkd->bqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        rows = row0 + jax.lax.broadcasted_iota(jnp.int32, s.shape[-2:], 0)
        cols = col0 + jax.lax.broadcasted_iota(jnp.int32, s.shape[-2:], 1)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    # Rows with every position masked: exp(-inf - -inf) traps; clamp m.
    m_safe = jnp.maximum(m, -1e29)
    p = jnp.exp(s - m_safe)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum(
        "bqk,bkd->bqd", p.astype(v.dtype), v, preferred_element_type=jnp.float32
    )
    return o, m_safe, l


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    axis_size: int,
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """Local ring-attention body — call inside shard_map.

    q/k/v: the local sequence shards, [B, H, S_local, D] (kv heads must
    already match q heads — broadcast GQA groups before sharding).
    """
    b, h, s_local, d = q.shape
    scale = scale if scale is not None else d ** -0.5
    idx = jax.lax.axis_index(axis_name)

    qf = q.reshape(b * h, s_local, d)
    kf = k.reshape(b * h, s_local, d)
    vf = v.reshape(b * h, s_local, d)

    row0 = idx * s_local
    acc = jnp.zeros((b * h, s_local, d), jnp.float32)
    m = jnp.full((b * h, s_local, 1), _NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((b * h, s_local, 1), dtype=jnp.float32)

    # axis_size is static: unrolled ring. Step t sees the K/V block that
    # started life on device (idx - t) mod n.
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    for t in range(axis_size):
        col_block = (idx - t) % axis_size

        def do_block(kf=kf, vf=vf, col_block=col_block):
            return _block_attn(
                qf, kf, vf, scale, row0, col_block * s_local, causal
            )

        if causal:
            # A K/V block strictly above this Q shard's diagonal is fully
            # masked — skip its two matmuls entirely (the contiguous layout
            # gives some devices more skips than others; zigzag balancing
            # is the known future fix, see module docstring).
            # Skip-branch outputs are derived from the (mesh-varying) q
            # shard so both cond branches have the same varying-axes type
            # under shard_map.
            zero_col = (0.0 * qf[..., :1]).astype(jnp.float32)
            o_t, m_t, l_t = jax.lax.cond(
                col_block > idx,
                lambda: (
                    (0.0 * qf).astype(jnp.float32),
                    zero_col + _NEG_INF / 10,
                    zero_col,
                ),
                do_block,
            )
        else:
            o_t, m_t, l_t = do_block()
        m_new = jnp.maximum(m, m_t)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_t - m_new)
        acc = acc * alpha + o_t * beta
        l = l * alpha + l_t * beta
        m = m_new
        if t != axis_size - 1:
            kf = jax.lax.ppermute(kf, axis_name, perm)
            vf = jax.lax.ppermute(vf, axis_name, perm)

    out = acc / jnp.where(l == 0.0, 1.0, l)
    return out.reshape(b, h, s_local, d).astype(q.dtype)


def ring_attention_sharded(
    mesh,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    batch_axis: str = "data",
    head_axis: str = "tensor",
    seq_axis: str = "sequence",
):
    """shard_map wrapper: batch over `batch_axis`, heads over `head_axis`,
    sequence ring over `seq_axis`."""
    from jax.sharding import PartitionSpec as P

    from torchft_tpu.ops._shard_map import shard_map

    axis_size = mesh.shape[seq_axis]
    spec = P(batch_axis, head_axis, seq_axis, None)
    fn = shard_map(
        functools.partial(
            ring_attention,
            axis_name=seq_axis,
            axis_size=axis_size,
            causal=causal,
            scale=scale,
        ),
        mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
