"""Ulysses sequence parallelism: all-to-all head<->sequence resharding.

The second long-context strategy next to ring attention (the torchft
reference has neither — SURVEY.md §5 long-context "not present").  Where the
ring keeps Q resident and rotates K/V shard-by-shard (n-1 neighbor hops,
one block in flight), Ulysses (arXiv:2309.14509) does two all-to-alls: swap
the sharded axis from *sequence* to *heads*, run ordinary full-sequence
attention on a head subset — the pallas flash kernel applies unchanged —
and swap back.  Cheaper in latency terms when the head count divides the
mesh axis (2 collectives instead of n-1 hops) and composes with any local
attention kernel; the ring wins when heads < devices or memory for a full
K/V sequence per device is the constraint.  Both are exposed; the
transformer selects via ``TransformerConfig.attention``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax

from torchft_tpu.ops.attention import flash_attention


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """Local body — call inside shard_map over the sequence mesh axis.

    q/k/v: local sequence shards [B, H, S_local, D]; the q and kv head
    counts must each be divisible by the axis size.  GQA stays compressed
    through the all_to_all (k/v may have fewer heads than q); the local
    flash kernel broadcasts groups after the exchange.
    """
    # [B, H, S_local, D] -> all_to_all -> [B, H/n, S, D]: the head axis is
    # scattered across the axis while sequence gathers.
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name, tiled=True)
    q = a2a(q, split_axis=1, concat_axis=2)
    k = a2a(k, split_axis=1, concat_axis=2)
    v = a2a(v, split_axis=1, concat_axis=2)
    out = flash_attention(q, k, v, causal=causal, scale=scale)
    # [B, H/n, S, D] -> [B, H, S_local, D]
    return a2a(out, split_axis=2, concat_axis=1)


def ulysses_attention_sharded(
    mesh,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    batch_axis: Optional[str] = "data",
    head_axis: Optional[str] = "tensor",
    seq_axis: str = "sequence",
) -> jax.Array:
    """shard_map wrapper mirroring ring_attention_sharded: batch over
    `batch_axis`, heads over `head_axis` (TP), sequence over `seq_axis`."""
    from jax.sharding import PartitionSpec as P

    from torchft_tpu.ops._shard_map import shard_map

    n = mesh.shape[seq_axis]
    tp = max(1, mesh.shape.get(head_axis, 1) if head_axis else 1)
    for name, heads in (("q", q.shape[1]), ("kv", k.shape[1])):
        # Guard TP divisibility first (e.g. 2 kv heads over tp=4): without
        # it, heads//tp floors to 0, 0 % n == 0 passes the check below, and
        # the misconfiguration surfaces later as an opaque shard_map
        # partitioning error instead of this message.
        assert heads % tp == 0, (
            f"Ulysses needs {name} heads ({heads}) divisible by the "
            f"'{head_axis}' axis ({tp}); use ring attention otherwise"
        )
        heads_local = heads // tp
        assert heads_local % n == 0, (
            f"Ulysses needs {name} heads-per-TP-shard ({heads_local}) divisible "
            f"by the sequence axis ({n}); use ring attention otherwise"
        )
    spec = P(batch_axis, head_axis, seq_axis, None)
    fn = shard_map(
        functools.partial(
            ulysses_attention, axis_name=seq_axis, causal=causal, scale=scale
        ),
        mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
