"""Fused lm-head + cross-entropy: pallas TPU kernels, never materializing
the f32 [N, vocab] logits in HBM.

Motivation (flagship profile, v5e): the unfused path — bf16 [N, E] @ [E, V]
matmul to f32 logits, logsumexp, target gather, then the backward's softmax
recompute and two grad matmuls — moves the 2.1 GB f32 logits array through
HBM repeatedly (~18 ms/step of pure bandwidth), and holds it as an autodiff
residual.  The fused op:

  forward   — one kernel, grid (row_blocks, vocab_blocks) with vocab
              innermost: online logsumexp in VMEM scratch; only the O(N)
              lse ever reaches HBM.  The target logit is extracted
              OUTSIDE the kernel as rowsum(x * w.T[targets]) — an O(N*E)
              gather+reduce in XLA — because the in-kernel
              iota/compare/select variant added ~4 VPU passes over the
              full [N, V] tile stream (measured slower than the XLA
              gather by ~1 ms).
  backward  — one kernel recomputes the logits block, forms the scaled
              bf16 dlogits = (softmax - onehot) * g/N tile, and writes it
              once; dx and dw are then plain XLA bf16 matmuls (XLA runs
              them near MXU peak, which hand-written accumulation kernels
              measured 2x worse at).  Peak transient is the bf16 [N, V]
              dlogits (half the f32 logits the unfused path keeps alive),
              and the f32 logits never exist.

The reference has no analogue (torch CE over materialized logits); this op
exists because the TPU build owns its compute path.  Off-TPU (CPU test
mesh) and for shapes the kernels do not tile, callers should use the plain
XLA formulation (see models/transformer.lm_head_loss) — this module only
decides applicability via `fused_ce_applicable`.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from torchft_tpu.ops._pallas_util import on_tpu, row_stat_col

_LANE = 128

# Per-operand VMEM budgets the block sizes are solved against (double
# buffering means each block effectively costs ~2x its size; the f32
# logits tile [block_rows, block_v] is the largest single allocation).
_X_BLOCK_BYTES = 2 * 1024 * 1024
_W_BLOCK_BYTES = 3 * 1024 * 1024


def _block_v(v: int, e: int) -> Optional[int]:
    """Largest multiple of 128 dividing V whose [E, block_v] bf16 tile
    fits the weight budget, capped at 2048."""
    cap = min(2048, _W_BLOCK_BYTES // (2 * e) // _LANE * _LANE)
    best = None
    for mult in range(1, max(cap, _LANE) // _LANE + 1):
        cand = mult * _LANE
        if v % cand == 0:
            best = cand
    return best


def _block_rows(n: int, e: int) -> Optional[int]:
    """Largest power-of-two row block whose [block_rows, E] bf16 tile
    fits the activation budget."""
    cap = _X_BLOCK_BYTES // (2 * e)
    for cand in (1024, 512, 256, 128):
        if cand <= cap and n % cand == 0:
            return cand
    return None


def fused_ce_applicable(n: int, e: int, v: int, mesh=None) -> bool:
    """True when the pallas kernels can and should run.

    mesh.size > 1 is excluded: a pallas custom call has no SPMD
    partitioning rule, so under a real multi-device mesh XLA would
    all-gather the operands to run it replicated — correct but a perf
    cliff.  Sharded configurations keep the plain XLA formulation, which
    propagates shardings (vocab-parallel logsumexp etc.) natively.
    """
    if not on_tpu():
        return False
    if mesh is None:
        # Callers that omit mesh (e.g. single-arg loss_fn closures) may
        # still be tracing under a multi-device GSPMD jit; fall back to
        # the ambient abstract mesh, then the process device count.  The
        # device-count check also turns the kernel off for a genuinely
        # single-device jit on a multi-chip host, which is a deliberate
        # asymmetric trade: the unfused XLA path is wall-neutral there
        # (docs/architecture.md — the fusion's win is HBM residency),
        # while running the pallas custom call replicated under a
        # sharded jit is a large silent cliff.  Multi-chip callers that
        # want the kernel single-device pass mesh explicitly.
        amesh = jax.sharding.get_abstract_mesh()
        if amesh is not None and not amesh.empty and amesh.size > 1:
            return False
        if jax.device_count() > 1:
            return False
    elif getattr(mesh, "size", 1) > 1:
        return False
    # Blocks are solved against explicit per-operand VMEM budgets, so the
    # gate is simply "a valid tiling exists" — no separate size check that
    # could drift from what the kernels actually allocate.
    return (
        _block_v(v, e) is not None
        and _block_rows(n, e) is not None
        and e % _LANE == 0
    )


def _ce_lse_kernel(
    x_ref, w_ref, lse_ref, m_scr, l_scr, *, num_v: int,
):
    from jax.experimental import pallas as pl

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -1e30)
        l_scr[...] = jnp.zeros_like(l_scr)

    # Logits block in the input dtype (bf16 = full MXU rate), f32 accum.
    s = jax.lax.dot(
        x_ref[...], w_ref[...], preferred_element_type=jnp.float32
    )                                              # [block_rows, block_v]
    m_prev = m_scr[:, :1]
    m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_cur)
    l_new = alpha * l_scr[:, :1] + jnp.sum(
        jnp.exp(s - m_cur), axis=-1, keepdims=True
    )
    # Partial column stores: broadcasting across the (rows, 128) scratch
    # measured ~19% of the attention kernel's time; same pattern here.
    m_scr[:, 0:1] = m_cur
    l_scr[:, 0:1] = l_new

    @pl.when(j == num_v - 1)
    def _emit():
        lse = m_scr[:, :1] + jnp.log(l_scr[:, :1])     # (block_rows, 1)
        lse_ref[0, 0:1, :] = jnp.transpose(lse, (1, 0))


def _ce_dlogits_kernel(
    x_ref, w_ref, tgt_ref, lse_ref, scale_ref, dl_ref,
    *, block_rows: int, block_v: int,
):
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    j = pl.program_id(1)

    s = jax.lax.dot(
        x_ref[...], w_ref[...], preferred_element_type=jnp.float32
    )
    p = jnp.exp(s - row_stat_col(lse_ref, i, block_rows))
    tg = row_stat_col(tgt_ref, i, block_rows)
    cols = j * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    p = jnp.where(cols == tg, p - 1.0, p)          # softmax - onehot
    dl_ref[...] = (p * scale_ref[0, 0]).astype(dl_ref.dtype)


def _ce_lse_pallas(x, w, interpret: bool = False):
    """x: [N, E], w: [E, V] (same dtype as x) -> lse [N] f32."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, e = x.shape
    v = w.shape[1]
    br, bv = _block_rows(n, e), _block_v(v, e)
    num_i, num_v = n // br, v // bv

    lse = pl.pallas_call(
        functools.partial(_ce_lse_kernel, num_v=num_v),
        out_shape=jax.ShapeDtypeStruct((1, 1, n), jnp.float32),
        grid=(num_i, num_v),
        in_specs=[
            pl.BlockSpec((br, e), lambda i, j: (i, 0)),        # x
            pl.BlockSpec((e, bv), lambda i, j: (0, j)),        # w
        ],
        out_specs=pl.BlockSpec((1, 1, br), lambda i, j: (0, 0, i)),
        scratch_shapes=[
            pltpu.VMEM((br, _LANE), jnp.float32),   # running max
            pltpu.VMEM((br, _LANE), jnp.float32),   # running sumexp
        ],
        interpret=interpret,
    )(x, w)
    return lse[0, 0]


def _ce_dlogits_pallas(x, w, targets, lse, scale, interpret: bool = False):
    """Scaled bf16 dlogits = (softmax(x@w) - onehot(targets)) * scale.
    scale is a traced scalar (folded in here so no extra [N, V] pass)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, e = x.shape
    v = w.shape[1]
    br, bv = _block_rows(n, e), _block_v(v, e)
    num_i, num_v = n // br, v // bv
    tgt = targets.astype(jnp.int32)[None, None, :]
    lse3 = lse[None, None, :]
    scale2 = jnp.asarray(scale, jnp.float32).reshape(1, 1)

    return pl.pallas_call(
        functools.partial(_ce_dlogits_kernel, block_rows=br, block_v=bv),
        out_shape=jax.ShapeDtypeStruct((n, v), x.dtype),
        grid=(num_i, num_v),
        in_specs=[
            pl.BlockSpec((br, e), lambda i, j: (i, 0)),        # x
            pl.BlockSpec((e, bv), lambda i, j: (0, j)),        # w
            pl.BlockSpec((1, 1, n), lambda i, j: (0, 0, 0)),   # targets
            pl.BlockSpec((1, 1, n), lambda i, j: (0, 0, 0)),   # lse
            pl.BlockSpec(memory_space=pltpu.SMEM),             # scale
        ],
        out_specs=pl.BlockSpec((br, bv), lambda i, j: (i, j)),
        interpret=interpret,
    )(x, w, tgt, lse3, scale2)


def _target_logit(x, w, targets):
    """rowsum(x * w[:, t]): O(N*E) gather + reduce, no [N, V] involved.
    w.T is materialized so the gather reads contiguous rows."""
    wt = jnp.transpose(w)[targets]                 # [N, E]
    return jnp.einsum(
        "ne,ne->n", x, wt, preferred_element_type=jnp.float32
    )


@jax.custom_vjp
def fused_linear_cross_entropy(x, w, targets):
    """Mean cross-entropy of softmax(x @ w) against integer targets,
    computed blockwise on TPU so the f32 [N, V] logits never reach HBM.

    x: [N, E] (bf16 on the model path), w: [E, V] same dtype, targets:
    [N] integer.  Returns a f32 scalar.  Callers gate on
    fused_ce_applicable; off-TPU the same math runs as one materialized
    XLA computation (used by the correctness tests)."""
    lse, tl = _ce_fwd(x, w, targets)
    return jnp.mean(lse - tl)


def _ce_fwd(x, w, targets, interpret: bool = False):
    if on_tpu() or interpret:
        lse = _ce_lse_pallas(x, w, interpret=interpret)
        return lse, _target_logit(x, w, targets)
    logits = jax.lax.dot(x, w, preferred_element_type=jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    tl = jnp.take_along_axis(logits, targets[:, None], axis=-1)[:, 0]
    return lse, tl


def _ce_vjp_fwd(x, w, targets):
    lse, tl = _ce_fwd(x, w, targets)
    return jnp.mean(lse - tl), (x, w, targets, lse)


def _ce_vjp_bwd(res, g):
    x, w, targets, lse = res
    n = x.shape[0]
    scale = g / n
    if on_tpu():
        # dlogits tile-by-tile in bf16 (pallas) — the f32 logits never
        # exist in HBM.
        dl = _ce_dlogits_pallas(x, w, targets, lse, scale)
    else:
        logits = jax.lax.dot(x, w, preferred_element_type=jnp.float32)
        p = jnp.exp(logits - lse[:, None])
        p = p - jax.nn.one_hot(targets, w.shape[1], dtype=jnp.float32)
        dl = (p * scale).astype(x.dtype)
    # Two plain XLA matmuls — XLA runs these bf16 matmuls near MXU peak,
    # which hand-written scratch-accumulation kernels measured 2x worse at.
    dx = jax.lax.dot_general(
        dl, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dw = jax.lax.dot_general(
        x, dl, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return (
        dx.astype(x.dtype),
        dw.astype(w.dtype),
        np.zeros(targets.shape, jax.dtypes.float0),
    )


fused_linear_cross_entropy.defvjp(_ce_vjp_fwd, _ce_vjp_bwd)
