"""TPU-native hot ops: pallas kernels with XLA fallbacks.

The reference has no custom kernels (it delegates compute to torch); these
exist because the TPU build's compute path is our own.  Each op provides a
pallas TPU kernel for the forward pass and an XLA-expressed backward
(flash-style recompute), and falls back to pure-XLA reference math off-TPU
so the same model code runs under the CPU test mesh.
"""

from torchft_tpu.ops.attention import flash_attention
from torchft_tpu.ops.cross_entropy import (
    fused_ce_applicable,
    fused_linear_cross_entropy,
)
from torchft_tpu.ops.ring_attention import ring_attention
from torchft_tpu.ops.rmsnorm import rms_norm, rms_norm_pallas
from torchft_tpu.ops.ulysses import ulysses_attention

__all__ = [
    "flash_attention",
    "fused_ce_applicable",
    "fused_linear_cross_entropy",
    "ring_attention",
    "rms_norm",
    "rms_norm_pallas",
    "ulysses_attention",
]
