"""RMSNorm: XLA-fused default + a pallas kernel variant.

Two implementations, chosen by measurement:

- ``rms_norm`` (the default, what the models use): plain XLA ops under
  autodiff.  XLA fuses the normalization into the neighboring matmul
  prologue/epilogue, so it costs ~no extra HBM pass.  Measured on v5e in
  the full flagship model (12L d768 b16 s1024): 133.6 ms/step vs 137.6
  with the hand-written kernel below — a custom kernel is a fusion
  BARRIER, and for a memory-light op that costs more than the kernel
  saves.
- ``rms_norm_pallas``: single-kernel forward (one HBM read of x, one
  write) with a custom VJP.  Wins when the norm genuinely stands alone
  (no adjacent op to fuse into) or under compilers that fail to fuse;
  kept tested (interpret mode on CPU) and exported for such workloads.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["rms_norm", "rms_norm_pallas"]


def rms_norm(x: jax.Array, w: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm over the last axis: ``x * rsqrt(mean(x^2)+eps) * w``.

    f32 statistics regardless of input dtype; differentiable by autodiff
    (no custom VJP — XLA's fused backward is the fast path, see module
    docstring)."""
    xf = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * inv * w.astype(jnp.float32)).astype(x.dtype)


# -- pallas kernel variant ---------------------------------------------------


def _use_pallas() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # noqa: BLE001
        return False


def _rms_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    o_ref[...] = (x * inv * w_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _rms_pallas(
    x: jax.Array, w: jax.Array, eps: float, interpret: bool = False
) -> jax.Array:
    from jax.experimental import pallas as pl

    rows = x.shape[0]
    d = x.shape[-1]
    # One grid row per block of token rows; whole feature dim in VMEM (the
    # reduction axis must be resident).
    block_rows = max(1, min(rows, 512))
    grid = (pl.cdiv(rows, block_rows),)
    return pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        interpret=interpret,
    )(x, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm_pallas(x: jax.Array, w: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm as one pallas kernel (TPU) with a hand-written backward;
    XLA fallback off-TPU.  See module docstring for when to prefer this."""
    return _rms_forward_impl(x, w, eps)


def _rms_forward_impl(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    if _use_pallas() and x.ndim >= 2:
        flat = x.reshape(-1, x.shape[-1])
        return _rms_pallas(flat, w, eps).reshape(x.shape)
    return rms_norm(x, w, eps)


def _rms_fwd(x, w, eps):
    return _rms_forward_impl(x, w, eps), (x, w)


def _rms_bwd(eps, res, g):
    x, w = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    xhat = xf * inv
    # d/dx of x*inv(x)*w: inv * (g*w - xhat * mean(g*w*xhat))
    gw = gf * wf
    dx = inv * (gw - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    dw = jnp.sum(gf * xhat, axis=tuple(range(x.ndim - 1)))
    return dx.astype(x.dtype), dw.astype(w.dtype)


rms_norm_pallas.defvjp(_rms_fwd, _rms_bwd)
