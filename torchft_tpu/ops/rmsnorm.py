"""Fused RMSNorm.

Forward is a single pallas kernel (one HBM read of x, one write) on TPU;
backward is expressed in XLA from the saved inverse-rms — cheaper than
saving normalized activations and fully fusable into neighboring matmuls.
Falls back to pure XLA off-TPU (the CPU test mesh runs the same model code).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _use_pallas() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # noqa: BLE001
        return False


def _rms_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    o_ref[...] = (x * inv * w_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _rms_pallas(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    from jax.experimental import pallas as pl

    rows = x.shape[0]
    d = x.shape[-1]
    # One grid row per block of token rows; whole feature dim in VMEM (the
    # reduction axis must be resident).
    block_rows = max(1, min(rows, 512))
    grid = (pl.cdiv(rows, block_rows),)
    return pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
    )(x, w)


def _rms_reference(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * inv * w.astype(jnp.float32)).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm(x: jax.Array, w: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm over the last axis: ``x * rsqrt(mean(x^2)+eps) * w``.

    Accepts any leading shape; the reduction axis is the last one.
    """
    return _rms_forward_impl(x, w, eps)


def _rms_forward_impl(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    if _use_pallas() and x.ndim >= 2:
        flat = x.reshape(-1, x.shape[-1])
        return _rms_pallas(flat, w, eps).reshape(x.shape)
    return _rms_reference(x, w, eps)


def _rms_fwd(x, w, eps):
    return _rms_forward_impl(x, w, eps), (x, w)


def _rms_bwd(eps, res, g):
    x, w = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    xhat = xf * inv
    # d/dx of x*inv(x)*w: inv * (g*w - xhat * mean(g*w*xhat))
    gw = gf * wf
    dx = inv * (gw - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    dw = jnp.sum(gf * xhat, axis=tuple(range(x.ndim - 1)))
    return dx.astype(x.dtype), dw.astype(w.dtype)


rms_norm.defvjp(_rms_fwd, _rms_bwd)
