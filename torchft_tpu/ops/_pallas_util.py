"""Small helpers shared by the pallas TPU kernels."""

from __future__ import annotations

import jax
import jax.numpy as jnp

LANE = 128  # TPU lane width


def on_tpu() -> bool:
    """True when the default backend is a real TPU (pallas kernels apply)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # noqa: BLE001
        return False


def row_stat_col(ref, idx, block: int):
    """Row-stat block (1, 1, N) -> column (block, 1) for row-block idx.

    Row statistics (lse, delta, targets) enter kernels as compact
    [.., 1, N] arrays (4 KB per visit) instead of the official kernels'
    lane-padded [.., N, 128] layout (260 KB per visit); the in-kernel
    slice + lane->sublane relayout of `block` elements is measured noise."""
    from jax.experimental import pallas as pl

    seg = ref[0, 0:1, pl.ds(idx * block, block)]  # (1, block)
    return jnp.transpose(seg, (1, 0))
