"""One shard_map import/compat shim for every sharded op.

JAX moved shard_map from jax.experimental to the top level, and renamed
its replication-check kwarg (check_rep -> check_vma) along the way; this
helper resolves whichever this jaxlib has so call sites stay
version-agnostic.
"""

from __future__ import annotations

import inspect
from typing import Any, Optional


def shard_map(
    f,
    mesh,
    in_specs: Any,
    out_specs: Any,
    check: Optional[bool] = None,
):
    """shard_map(f) bound to ``mesh`` with the given specs.

    ``check=None`` keeps the library default replication checking;
    False/True pins it via whichever kwarg (check_vma / check_rep) this
    JAX version accepts.
    """
    try:
        from jax import shard_map as _sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm

    kwargs = {}
    if check is not None:
        params = inspect.signature(_sm).parameters
        if "check_vma" in params:
            kwargs["check_vma"] = check
        elif "check_rep" in params:
            kwargs["check_rep"] = check
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)
