"""Decorrelated-jitter backoff: the retry pacing for lighthouse failover.

When the active lighthouse dies, EVERY replica group in the cluster loses
it at the same instant, and plain exponential backoff keeps their retries
phase-locked: each round, N managers slam the new leader simultaneously —
the classic thundering herd.  Decorrelated jitter (sleep_{k+1} =
uniform(base, 3 * sleep_k), capped) spreads each client's next attempt
across the whole interval, so the reconnect wave arrives smeared instead
of spiked.

The native analogue is ``ExponentialBackoff`` in ``native/src/retry.h`` —
the two implementations follow the same algorithm; keep them in sync.
Used by the lighthouse reconnect loops in :mod:`torchft_tpu._native`
(``LighthouseClient`` failover), :mod:`torchft_tpu.manager` (drain-notice
delivery), and the HA election driver (:mod:`torchft_tpu.ha.replica`).
"""

from __future__ import annotations

import random

__all__ = ["DecorrelatedBackoff"]


class DecorrelatedBackoff:
    """sleep_{k+1} = min(cap, uniform(base, 3 * sleep_k)).

    Args:
        base_s: minimum (and first) sleep, seconds.
        cap_s: maximum sleep, seconds.
        rng: injectable ``random.Random`` for deterministic tests.
    """

    def __init__(
        self,
        base_s: float = 0.05,
        cap_s: float = 2.0,
        rng: random.Random | None = None,
    ) -> None:
        if base_s <= 0:
            raise ValueError("base_s must be > 0")
        self._base = base_s
        self._cap = max(cap_s, base_s)
        self._prev = base_s
        self._rng = rng or random.Random()

    def next(self) -> float:
        """The next sleep duration in seconds (does not sleep)."""
        sleep = self._rng.uniform(self._base, max(self._base, self._prev * 3.0))
        sleep = min(self._cap, sleep)
        self._prev = max(self._base, sleep)
        return sleep

    def reset(self) -> None:
        self._prev = self._base
