"""Lease-based leader election over a shared file.

The lighthouse HA group needs exactly one leader and needs it without a
consensus protocol: the reference abandoned its Raft ``CoordinatorService``
(PAPER.md §1) and tpu-ft keeps that pragmatism — a lease in a shared file
(one local FS in the bench; NFS/GCS-fuse/a PVC in a real deployment) is
the entire election substrate.

Protocol (all writes are atomic tmp + ``os.replace``):

- The lease file holds one record: ``epoch``, ``owner``, the owner's RPC
  and HTTP addresses, and ``expires_ms`` (epoch milliseconds).
- **Renewal** (leader, every ~lease/3): re-read first — if the file no
  longer names this owner at this epoch, the lease was taken (e.g. this
  process stalled past expiry and a rival won): return ``None`` and the
  caller must demote *immediately*.  Otherwise rewrite with a fresh
  expiry.
- **Acquisition** (candidate, when the record is missing or expired):
  write a candidacy record with ``epoch + 1``, sleep a short *settle*
  delay (jittered — two candidates racing must not re-read in lockstep),
  then re-read: whoever's record survived the race is leader; the loser
  reads the winner's record and follows.  Converges on exactly one leader
  because ``os.replace`` is atomic and last-writer-wins: after the settle
  window only one record exists, and every candidate judges itself against
  that one record.
- **Serve-time guard** (not in this file): holding the lease only matters
  while it is unexpired — the native lighthouse refuses authoritative
  answers once ``expires_ms`` passes without a renewal, which closes the
  stalled-leader window the file protocol alone cannot.

Clock discipline: expiries compare wall clocks across processes, so the
protocol assumes hosts are synced to well under the lease duration (the
same assumption the heartbeat timeout already makes).  ``clock`` is
injectable for boundary tests.
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass
from typing import Callable, Optional

__all__ = ["LeaseRecord", "FileLease"]


@dataclass
class LeaseRecord:
    """One parsed lease-file record."""

    epoch: int
    owner: str
    rpc_address: str
    http_address: str
    expires_ms: int

    def expired(self, now_ms: int) -> bool:
        return now_ms >= self.expires_ms


class FileLease:
    """One participant's view of the shared lease file.

    Args:
        path: the shared lease file (its directory must exist).
        lease_ms: lease duration; a leader that cannot renew within this
            window loses leadership.  The failover floor: a standby can
            take over at most one lease period after the leader dies.
        owner_id: unique id of this participant (e.g. ``host:port`` of its
            RPC server).
        clock: seconds-since-epoch callable (injectable for tests).
        sleep: sleep callable (injectable for tests).
        settle_s: candidacy settle delay before the confirm re-read;
            defaults to min(150 ms, lease/4) plus up to 50% jitter.
    """

    def __init__(
        self,
        path: str,
        lease_ms: int,
        owner_id: str,
        clock: Callable[[], float] = time.time,
        sleep: Callable[[float], None] = time.sleep,
        settle_s: Optional[float] = None,
        rng: Optional[random.Random] = None,
    ) -> None:
        if lease_ms <= 0:
            raise ValueError("lease_ms must be > 0")
        self.path = path
        self.lease_ms = int(lease_ms)
        self.owner_id = owner_id
        self._clock = clock
        self._sleep = sleep
        self._settle_s = settle_s
        self._rng = rng or random.Random()

    # -- record I/O ---------------------------------------------------------

    def _now_ms(self) -> int:
        return int(self._clock() * 1000)

    def _settle_floor_ms(self) -> int:
        """The un-jittered settle minimum — the stall budget a candidate's
        read->write gap must stay under for settle-and-confirm to cover
        it (see try_acquire)."""
        settle = self._settle_s
        if settle is None:
            settle = min(0.15, self.lease_ms / 1000.0 / 4.0)
        # At least one wall-clock tick so an explicit settle_s=0 (boundary
        # tests with fake clocks) never self-aborts on rounding.
        return max(1, int(settle * 1000))

    def read(self) -> Optional[LeaseRecord]:
        """The current record, or None when missing/corrupt (a torn write
        cannot happen — writes are atomic replaces — but a manually
        truncated or garbage file must read as 'no lease', not crash the
        election)."""
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                lines = f.read().splitlines()
        except OSError:
            return None
        if len(lines) < 5:
            return None
        try:
            return LeaseRecord(
                epoch=int(lines[0]),
                owner=lines[1],
                rpc_address=lines[2],
                http_address=lines[3],
                expires_ms=int(lines[4]),
            )
        except ValueError:
            return None

    def _write(self, rec: LeaseRecord) -> None:
        tmp = f"{self.path}.{self.owner_id.replace('/', '_').replace(':', '_')}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(
                f"{rec.epoch}\n{rec.owner}\n{rec.rpc_address}\n"
                f"{rec.http_address}\n{rec.expires_ms}\n"
            )
        os.replace(tmp, self.path)  # atomic: readers see whole records

    # -- protocol -----------------------------------------------------------

    def try_acquire(
        self, rpc_address: str, http_address: str
    ) -> Optional[LeaseRecord]:
        """One acquisition attempt.  Returns the record this participant
        now leads under, or None (a live lease exists, or a rival won the
        race).  Call only when :meth:`read` shows no live lease — calling
        against a live lease is a no-op returning None."""
        now = self._now_ms()
        current = self.read()
        if current is not None and not current.expired(now):
            return None
        candidacy = LeaseRecord(
            epoch=(current.epoch if current else 0) + 1,
            owner=self.owner_id,
            rpc_address=rpc_address,
            http_address=http_address,
            expires_ms=now + self.lease_ms,
        )
        # Stall guard: the settle-and-confirm window only covers candidates
        # whose expired-read -> candidacy-write delay is under the settle
        # minimum — a rival that read before OUR write and writes after OUR
        # confirm must have stalled at least one settle period in between
        # (GC pause, frozen VM, slow shared FS).  Abort this attempt when
        # we ARE that stalled candidate: a late write here would overwrite
        # a rival's already-confirmed lease at the same epoch and dual-serve
        # until its next renewal.  (The residual race — a stall landing
        # between this check and the rename — is the irreducible cost of a
        # CAS-free file protocol; this shrinks it from arbitrary to tiny.)
        if self._now_ms() - now > self._settle_floor_ms():
            return None
        self._write(candidacy)
        # Settle: let the other candidates' writes land, then judge against
        # the one surviving record.  Jittered so racing candidates do not
        # re-read in lockstep (and so back-to-back retries decorrelate).
        settle = self._settle_s
        if settle is None:
            settle = min(0.15, self.lease_ms / 1000.0 / 4.0)
        self._sleep(settle * (1.0 + 0.5 * self._rng.random()))
        after = self.read()
        if (
            after is not None
            and after.owner == self.owner_id
            and after.epoch == candidacy.epoch
        ):
            # Won the race.  The settle delay ate into the lease; the
            # expiry stands as written (renewal extends it immediately).
            return after
        return None  # lost: `after` names the winner to follow

    def renew(self, held: LeaseRecord) -> Optional[LeaseRecord]:
        """Extends a held lease.  Returns the renewed record, or None when
        the lease was lost — the file no longer names this owner/epoch
        (stolen after an expiry we slept through), or the lease already
        expired (renewing an expired lease would race a candidate's
        acquisition; the holder must demote and re-acquire instead)."""
        now = self._now_ms()
        current = self.read()
        if (
            current is None
            or current.owner != self.owner_id
            or current.epoch != held.epoch
        ):
            return None  # stolen (or deleted): demote immediately
        if current.expired(now):
            return None  # lapsed: a candidate may be mid-acquisition
        if self._now_ms() - now > self._settle_floor_ms():
            # Stalled between the read and the write (same hole as in
            # try_acquire): the lease may have lapsed and been taken during
            # the stall — a late rewrite would clobber the new holder's
            # record with THIS stale epoch.  Demote instead.
            return None
        renewed = LeaseRecord(
            epoch=held.epoch,
            owner=self.owner_id,
            rpc_address=held.rpc_address,
            http_address=held.http_address,
            expires_ms=now + self.lease_ms,
        )
        self._write(renewed)
        return renewed

    def release(self, held: LeaseRecord) -> None:
        """Clean handoff on shutdown: expire the held lease NOW so a
        standby takes over without waiting out the remaining lease.  A
        no-op when the lease is no longer ours."""
        current = self.read()
        if (
            current is None
            or current.owner != self.owner_id
            or current.epoch != held.epoch
        ):
            return
        expired = LeaseRecord(
            epoch=held.epoch,
            owner=self.owner_id,
            rpc_address=held.rpc_address,
            http_address=held.http_address,
            expires_ms=self._now_ms(),
        )
        self._write(expired)
