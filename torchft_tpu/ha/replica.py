"""One lighthouse replica of a highly-available lighthouse group.

``HALighthouse`` wraps the native :class:`~torchft_tpu._native.LighthouseServer`
with the two loops that turn N independent processes into one logical
service:

- **election** — a lease in a shared file (:class:`torchft_tpu.ha.lease.FileLease`):
  the leader renews at ~lease/3 and pushes the renewed expiry into the
  native server (whose serve-time guard refuses Quorum/Heartbeat once the
  expiry passes — a stalled renewal thread cannot leave a zombie leader
  answering); a follower polls the file and takes over the moment the
  lease expires, bumping the epoch;
- **replication** — on every leader tick, the full lighthouse state
  (membership + live step/state, straggler-sentinel health, alerts,
  previous quorum + id) is serialized by the native server and pushed to
  every peer over wire method 6, so the standby that wins the next
  election resumes with the dead leader's exact view: quorum formation
  restarts on the fast-quorum path with an UNCHANGED quorum id (managers
  do not even reconfigure), and /metrics history has no reset.

A follower keeps its native server in the follower role, which answers
``Quorum``/``Heartbeat`` with ``"not the leader; leader=<addr> ..."`` and
HTTP with a 307 to the leader — clients (the managers' failover clients)
redirect instead of split-braining.

Takeovers are visible in the observability stream: when a replica wins an
election at epoch > 1 it emits a ``lighthouse_failover`` event (with the
new ``leader_epoch``) through :class:`~torchft_tpu.metrics.MetricsLogger`,
which ``obs/report.py`` uses to charge the election window like quorum
wait rather than a worker fault.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional, Sequence

from torchft_tpu.ha.backoff import DecorrelatedBackoff
from torchft_tpu.ha.lease import FileLease, LeaseRecord

logger = logging.getLogger(__name__)

__all__ = ["HALighthouse"]


class HALighthouse:
    """One replica of an HA lighthouse group.

    Args:
        lease_path: shared lease file (same path for every replica).
        peers: RPC addresses of the OTHER replicas (the replication push
            targets); entries matching this replica's own address are
            dropped, so passing the full group list is fine.
        lease_ms: lease duration — the failover floor (a standby takes
            over at most one lease period after the leader dies) and the
            serve-time guard horizon.
        replicate_interval_ms: leader-to-standby push cadence (default
            lease/3, the renewal cadence — state on a standby is at most
            this stale at takeover).
        bind / http_bind / min_replicas / join_timeout_ms / quorum_tick_ms
            / heartbeat_timeout_ms: forwarded to the native server.
        owner_id: stable id in the lease file (defaults to the bound RPC
            address).
    """

    def __init__(
        self,
        lease_path: str,
        peers: Sequence[str] = (),
        lease_ms: int = 2000,
        bind: str = "127.0.0.1:0",
        http_bind: str = "127.0.0.1:0",
        min_replicas: int = 1,
        join_timeout_ms: int = 60000,
        quorum_tick_ms: int = 100,
        heartbeat_timeout_ms: int = 5000,
        replicate_interval_ms: Optional[int] = None,
        owner_id: Optional[str] = None,
    ) -> None:
        import os as _os

        from torchft_tpu._native import LighthouseServer

        # A fresh replica must never answer authoritatively before the
        # election says so: the env flag makes the native server START in
        # the follower role — before its listeners open — instead of the
        # standalone-leader default (a set_role(False) after construction
        # would leave a brief authoritative window while clients are
        # already hammering every address of the replica set).  Scoped to
        # this construction: a standalone LighthouseServer built later in
        # the same process must keep its permanent-leader default.
        prev_flag = _os.environ.get("TPUFT_HA_START_FOLLOWER")
        _os.environ["TPUFT_HA_START_FOLLOWER"] = "1"
        try:
            self._server = LighthouseServer(
                bind=bind,
                min_replicas=min_replicas,
                join_timeout_ms=join_timeout_ms,
                quorum_tick_ms=quorum_tick_ms,
                heartbeat_timeout_ms=heartbeat_timeout_ms,
                http_bind=http_bind,
            )
        finally:
            if prev_flag is None:
                _os.environ.pop("TPUFT_HA_START_FOLLOWER", None)
            else:
                _os.environ["TPUFT_HA_START_FOLLOWER"] = prev_flag
        self._addr = self._server.address()
        self._http = self._server.http_address()
        # Redundant with the env flag, but keeps the role state coherent
        # (no known leader yet) for servers built before the flag existed.
        self._server.set_role(False, "", "", 0, 0)
        self._owner = owner_id or self._addr
        self._lease = FileLease(lease_path, lease_ms, self._owner)
        self._lease_ms = int(lease_ms)
        self._peers = [p.strip() for p in peers if p.strip() and p.strip() != self._addr]
        self._replicate_s = (
            (replicate_interval_ms if replicate_interval_ms else max(50, lease_ms // 3))
            / 1000.0
        )
        self._held: Optional[LeaseRecord] = None
        # Serializes every (_held, native role) transition: the replication
        # thread demotes on a higher-epoch fencing response while the
        # election thread promotes/renews — unsynchronized, a renew landing
        # just after a fencing demotion would re-promote a deposed leader.
        self._role_lock = threading.Lock()
        self._peer_clients: Dict[str, object] = {}
        self._stop = threading.Event()
        self._backoff = DecorrelatedBackoff(
            base_s=max(0.02, lease_ms / 1000.0 / 20.0),
            cap_s=max(0.1, lease_ms / 1000.0 / 3.0),
        )
        from torchft_tpu.metrics import MetricsLogger

        self._metrics = MetricsLogger.from_env(f"lighthouse:{self._owner}")
        self._thread = threading.Thread(
            target=self._election_loop, name="tpuft_ha_election", daemon=True
        )
        self._thread.start()
        # Replication runs on its OWN thread: a push to a dead standby
        # blocks on its connect timeout, and eating that stall inside the
        # election loop delays the renewal past the lease — the leader then
        # demotes itself and re-acquires at epoch+1 every cycle, flapping
        # leadership against a fault that killed no leader.
        self._repl_thread = threading.Thread(
            target=self._replicate_loop, name="tpuft_ha_replicate", daemon=True
        )
        self._repl_thread.start()

    # -- introspection ------------------------------------------------------

    def address(self) -> str:
        return self._addr

    def http_address(self) -> str:
        return self._http

    def native_server(self):
        """The wrapped native :class:`~torchft_tpu._native.LighthouseServer`.

        For surfaces that live on the native object and compose with HA
        per-instance rather than per-group — federation enrollment above
        all (:mod:`torchft_tpu.federation` calls ``set_federation`` on
        every replica of an HA child group; the native push loop only
        fires while the replica holds the lease, so leadership changes
        hand off the digest stream automatically).  Role flips stay owned
        by the election loop: never call ``set_role`` on this directly."""
        return self._server

    def role(self) -> str:
        """"leader" (live lease) or "follower"."""
        return "leader" if self._server.role() == 1 else "follower"

    def leader_epoch(self) -> int:
        return self._server.leader_epoch()

    def is_leader(self) -> bool:
        return self._held is not None

    # -- election -----------------------------------------------------------

    def _election_loop(self) -> None:
        while not self._stop.is_set():
            try:
                if self._held is not None:
                    self._leader_tick()
                    # Renew + replicate at ~lease/3: two missed ticks still
                    # land a renewal before expiry.
                    self._stop.wait(self._lease_ms / 1000.0 / 3.0)
                else:
                    self._follower_tick()
            except Exception:  # noqa: BLE001 — the election must outlive
                # transient I/O errors (lease file on flaky shared storage,
                # a peer mid-restart); the lease guard bounds the damage.
                logger.exception("lighthouse %s: election tick failed", self._owner)
                self._stop.wait(self._backoff.next())

    def _leader_tick(self) -> None:
        held = self._held
        if held is None:
            return  # deposed by the replication thread since the loop check
        renewed = self._lease.renew(held)
        if renewed is None:
            # Stolen or lapsed: demote IMMEDIATELY — the native role flip is
            # what stops this instance answering Quorum authoritatively.
            current = self._lease.read()
            logger.warning(
                "lighthouse %s: lease lost (now held by %s); demoting",
                self._owner,
                current.owner if current else "<nobody>",
            )
            self._demote(current)
            return
        with self._role_lock:
            if self._held is None:
                # Deposed (higher-epoch fencing) while the renew was in
                # flight: the file may still name us, but a peer serves at
                # a higher epoch — stay demoted; the follower tick decides.
                return
            self._held = renewed
            self._server.set_role(
                True, self._addr, self._http, renewed.epoch, renewed.expires_ms
            )

    def _follower_tick(self) -> None:
        rec = self._lease.read()
        now_ms = int(time.time() * 1000)
        if rec is not None and not rec.expired(now_ms):
            # Live leader: follow it (feeds the redirect target) and poll
            # again shortly before the lease could expire.
            self._server.set_role(
                False, rec.rpc_address, rec.http_address, rec.epoch, 0
            )
            self._backoff.reset()
            self._stop.wait(
                min(self._lease_ms / 1000.0 / 4.0, max(0.05, (rec.expires_ms - now_ms) / 1000.0))
            )
            return
        won = self._lease.try_acquire(self._addr, self._http)
        if won is None:
            # Lost the race (or raced a fresh renewal): back off with
            # jitter so rival candidates decorrelate, then re-read.
            self._stop.wait(self._backoff.next())
            return
        with self._role_lock:
            self._held = won
            self._server.set_role(
                True, self._addr, self._http, won.epoch, won.expires_ms
            )
        logger.warning(
            "lighthouse %s: took over leadership (epoch %d)", self._owner, won.epoch
        )
        if won.epoch > 1:
            # Epoch 1 is the group's initial election, not a failover.
            self._metrics.emit("lighthouse_failover", leader_epoch=won.epoch)

    def _demote(self, current: Optional[LeaseRecord]) -> None:
        with self._role_lock:
            self._held = None
            if current is not None:
                self._server.set_role(
                    False, current.rpc_address, current.http_address, current.epoch, 0
                )
            else:
                self._server.set_role(False, "", "", self._server.leader_epoch(), 0)

    # -- replication --------------------------------------------------------

    def _replicate_loop(self) -> None:
        """Leader pushes on their own thread (see __init__): peer I/O —
        dead-standby connect timeouts above all — must never delay a lease
        renewal."""
        backoff = DecorrelatedBackoff(base_s=0.05, cap_s=self._replicate_s * 4)
        while not self._stop.is_set():
            try:
                if self._held is not None:
                    self._replicate()
                self._stop.wait(self._replicate_s)
            except Exception:  # noqa: BLE001 — same discipline as the
                # election loop: replication must outlive transient errors.
                logger.exception("lighthouse %s: replicate tick failed", self._owner)
                self._stop.wait(backoff.next())

    def _replicate(self) -> None:
        """One leader push to every peer.  Failures are per-peer and
        non-fatal (a dead standby rejoins the stream when it restarts); a
        peer answering with a HIGHER epoch means THIS leader was deposed
        without noticing — demote on the spot."""
        if not self._peers:
            return
        snapshot = self._server.snapshot()
        from torchft_tpu import _native as native
        from torchft_tpu.proto import tpuft_pb2 as pb

        for peer in self._peers:
            try:
                client = self._peer_clients.get(peer)
                if client is None:
                    client = native._Client(peer, connect_timeout_ms=1000)
                    self._peer_clients[peer] = client
                raw = client.call(
                    native.LIGHTHOUSE_REPLICATE, snapshot, timeout_ms=2000
                )
                resp = pb.LighthouseReplicateResponse.FromString(raw)
                if not resp.applied and self._held is not None:
                    if resp.leader_epoch > self._held.epoch:
                        logger.warning(
                            "lighthouse %s: peer %s holds epoch %d > own %d — "
                            "deposed; demoting",
                            self._owner, peer, resp.leader_epoch, self._held.epoch,
                        )
                        self._demote(self._lease.read())
                        return
            except Exception:  # noqa: BLE001 — dead standby: drop the
                # cached connection so the next push redials.
                self._peer_clients.pop(peer, None)

    # -- lifecycle ----------------------------------------------------------

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
        if self._repl_thread.is_alive():
            self._repl_thread.join(timeout=5.0)
        if self._held is not None:
            # Clean handoff: push the freshest state, then expire the lease
            # NOW so a standby takes over without waiting it out.
            try:
                self._replicate()
                self._lease.release(self._held)
            except Exception:  # noqa: BLE001
                pass
            self._held = None
        for client in self._peer_clients.values():
            try:
                client.close()
            except Exception:  # noqa: BLE001
                pass
        self._peer_clients.clear()
        self._metrics.close()
        self._server.shutdown()
