"""Highly-available lighthouse: warm standbys behind a lease-based leader.

The lighthouse is the control plane's single point of failure — the
reference abandoned Raft and accepted a centralized service (PAPER.md §1),
and tpu-ft inherited that: one SIGKILL froze every quorum until an
operator intervened.  This package removes the SPOF without reintroducing
consensus:

- :mod:`~torchft_tpu.ha.lease` — leader election as a lease in a shared
  file (atomic-rename writes, settle-and-confirm acquisition, serve-time
  expiry guard in the native server);
- :mod:`~torchft_tpu.ha.replica` — :class:`HALighthouse`, one replica of
  the group: native lighthouse + election loop + continuous leader-to-
  standby state replication (membership, sentinel health, alerts, the
  previous quorum and its id), so a takeover resumes quorum formation on
  the fast path with no observability reset;
- :mod:`~torchft_tpu.ha.backoff` — decorrelated-jitter retry pacing shared
  by every lighthouse reconnect loop, so N replica groups failing over at
  the same instant do not stampede the new leader.

Run replicas with the CLI (``python -m torchft_tpu.lighthouse_cli
--lease-file /shared/lease --peers a:1,b:1 ...``) and point clients at the
whole set: ``TPUFT_LIGHTHOUSE=host1:29510,host2:29510`` — managers fail
over and follow redirects automatically.
"""

from torchft_tpu.ha.backoff import DecorrelatedBackoff
from torchft_tpu.ha.lease import FileLease, LeaseRecord

__all__ = ["DecorrelatedBackoff", "FileLease", "LeaseRecord", "HALighthouse"]


def __getattr__(name: str):
    # HALighthouse imports _native (which may build the C++ core on first
    # import); keep that cost out of `import torchft_tpu.ha` for users who
    # only want the lease/backoff primitives.
    if name == "HALighthouse":
        from torchft_tpu.ha.replica import HALighthouse

        return HALighthouse
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
