"""Standalone Lighthouse server CLI.

Reference parity: the ``torchft_lighthouse`` binary (src/bin/lighthouse.rs:11-23,
pyproject.toml:39-40).  Usage::

    python -m torchft_tpu.lighthouse_cli --bind [::]:29510 --min_replicas 2
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading


def main(argv=None) -> None:
    """CLI entry: standalone Lighthouse server with the HTML dashboard
    (reference: torchft_lighthouse, src/bin/lighthouse.rs:11-23)."""
    parser = argparse.ArgumentParser(description="torchft_tpu lighthouse server")
    parser.add_argument("--bind", default="[::]:29510", help="RPC bind address")
    parser.add_argument("--http_bind", default="[::]:29511", help="dashboard bind address")
    parser.add_argument("--min_replicas", type=int, default=1)
    parser.add_argument("--join_timeout_ms", type=int, default=60000,
                        help="straggler wait before forming a smaller quorum")
    parser.add_argument("--quorum_tick_ms", type=int, default=100)
    parser.add_argument("--heartbeat_timeout_ms", type=int, default=5000)
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(levelname)s %(name)s %(message)s"
    )
    from torchft_tpu._native import LighthouseServer

    server = LighthouseServer(
        bind=args.bind,
        min_replicas=args.min_replicas,
        join_timeout_ms=args.join_timeout_ms,
        quorum_tick_ms=args.quorum_tick_ms,
        heartbeat_timeout_ms=args.heartbeat_timeout_ms,
        http_bind=args.http_bind,
    )
    logging.info("lighthouse listening on %s (dashboard at %s)",
                 server.address(), server.http_address())

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    stop.wait()
    server.shutdown()


if __name__ == "__main__":
    main()
