"""Standalone Lighthouse server CLI.

Reference parity: the ``torchft_lighthouse`` binary (src/bin/lighthouse.rs:11-23,
pyproject.toml:39-40).  Usage::

    python -m torchft_tpu.lighthouse_cli --bind [::]:29510 --min_replicas 2

Highly-available mode (docs/architecture.md "HA lighthouse"): run N of
these, one per host, sharing a lease file on common storage and naming
each other as peers — a lease-based election keeps exactly one serving
as leader while the rest are warm standbys receiving continuous state
replication; clients set ``TPUFT_LIGHTHOUSE`` to the whole comma-separated
list and fail over automatically::

    python -m torchft_tpu.lighthouse_cli --bind host1:29510 \
        --http_bind host1:29511 --lease-file /shared/tpuft_lease \
        --lease-ms 2000 --peers host2:29510,host3:29510

Federated mode (docs/wire.md "Federation"): pass ``--region`` and
``--root-addrs`` to run this instance as a regional CHILD that owns its
local groups' heartbeats/sentinels/ledger and pushes digests to the root;
the root is just another lighthouse (no extra flag — set its
``--min_replicas`` to the GLOBAL group count).  Combines with HA flags on
either tier::

    python -m torchft_tpu.lighthouse_cli --bind 0.0.0.0:29510 \
        --region us-east --root-addrs root-host:29500
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading


def main(argv=None) -> None:
    """CLI entry: standalone Lighthouse server with the HTML dashboard
    (reference: torchft_lighthouse, src/bin/lighthouse.rs:11-23), or one
    replica of an HA lighthouse group when ``--lease-file`` is given."""
    parser = argparse.ArgumentParser(description="torchft_tpu lighthouse server")
    parser.add_argument("--bind", default="[::]:29510", help="RPC bind address")
    parser.add_argument("--http_bind", default="[::]:29511", help="dashboard bind address")
    parser.add_argument("--min_replicas", type=int, default=1)
    parser.add_argument("--join_timeout_ms", type=int, default=60000,
                        help="straggler wait before forming a smaller quorum")
    parser.add_argument("--quorum_tick_ms", type=int, default=100)
    parser.add_argument("--heartbeat_timeout_ms", type=int, default=5000)
    ha = parser.add_argument_group(
        "high availability",
        "run this process as one replica of an HA lighthouse group "
        "(lease-based leader election + leader->standby state replication)",
    )
    ha.add_argument(
        "--lease-file", default=None,
        help="shared lease file enabling HA mode (same path on every replica)",
    )
    ha.add_argument(
        "--lease-ms", type=int, default=2000,
        help="lease duration: the failover floor — a standby takes over at "
        "most one lease period after the leader dies (default 2000)",
    )
    ha.add_argument(
        "--peers", default="",
        help="comma-separated RPC addresses of the OTHER replicas (the "
        "replication push targets); this replica's own address is ignored",
    )
    fed = parser.add_argument_group(
        "federation",
        "run this instance as a regional child lighthouse of a two-tier "
        "federation (the root needs no flags — any lighthouse receiving "
        "digests serves as root)",
    )
    fed.add_argument(
        "--region", default="",
        help="region name enabling child mode; managers in this region keep "
        "their unchanged flat config pointed at this instance",
    )
    fed.add_argument(
        "--root-addrs", default="",
        help="comma-separated RPC addresses of the root lighthouse "
        "(leader + standbys when the root is HA)",
    )
    fed.add_argument(
        "--region-push-interval-ms", type=int, default=500,
        help="digest push cadence; keep well under the root's "
        "heartbeat_timeout_ms (the region-staleness horizon)",
    )
    args = parser.parse_args(argv)

    if bool(args.region) != bool(args.root_addrs):
        parser.error("--region and --root-addrs must be given together")

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(levelname)s %(name)s %(message)s"
    )

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())

    if args.lease_file:
        from torchft_tpu.ha.replica import HALighthouse

        server = HALighthouse(
            lease_path=args.lease_file,
            peers=[p for p in args.peers.split(",") if p.strip()],
            lease_ms=args.lease_ms,
            bind=args.bind,
            http_bind=args.http_bind,
            min_replicas=args.min_replicas,
            join_timeout_ms=args.join_timeout_ms,
            quorum_tick_ms=args.quorum_tick_ms,
            heartbeat_timeout_ms=args.heartbeat_timeout_ms,
        )
        if args.region:
            # Every HA replica enrolls; the native push loop only fires on
            # the current lease holder, so failover hands off the digest
            # stream without re-enrollment.
            server.native_server().set_federation(
                args.region, args.root_addrs, args.region_push_interval_ms
            )
        logging.info(
            "HA lighthouse replica on %s (dashboard at %s, lease %s, %d peer(s))",
            server.address(), server.http_address(), args.lease_file,
            len([p for p in args.peers.split(",") if p.strip()]),
        )
        stop.wait()
        server.shutdown()
        return

    from torchft_tpu._native import LighthouseServer

    server = LighthouseServer(
        bind=args.bind,
        min_replicas=args.min_replicas,
        join_timeout_ms=args.join_timeout_ms,
        quorum_tick_ms=args.quorum_tick_ms,
        heartbeat_timeout_ms=args.heartbeat_timeout_ms,
        http_bind=args.http_bind,
    )
    if args.region:
        server.set_federation(
            args.region, args.root_addrs, args.region_push_interval_ms
        )
    logging.info("lighthouse listening on %s (dashboard at %s)",
                 server.address(), server.http_address())
    stop.wait()
    server.shutdown()


if __name__ == "__main__":
    main()
