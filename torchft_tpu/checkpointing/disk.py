"""Durable disk checkpoints: async atomic save, cold-start resume.

The peer transports (http_transport.py, collective_transport.py) heal a
*restarted* replica from a *live* one; they cannot help when every replica
group is gone (host maintenance, full-job preemption — routine on TPU
pods).  This module closes that gap: each group persists its state to disk
on a cadence and a cold-started job resumes from the newest complete
checkpoint instead of step 0.

Reference parity note: the torchft reference delegates durable checkpoints
to the application (torchtitan's checkpoint manager; its own transports are
peer-to-peer only — torchft/checkpointing/transport.py:14-69 has no disk
path).  A standalone framework needs this first-party.

TPU-first design choices:
  - the on-disk format IS the transport wire format (serialization.py):
    one flatten/restore path for network heal and disk resume, and
    NamedShardings round-trip, so a resumed HSDP replica gets its arrays
    placed back on its own mesh without re-deciding placement;
  - ``save`` flattens on the caller's thread (the device->host fetch is the
    checkpoint barrier — it blocks until the step's arrays are real) and
    writes on a background thread so training overlaps the disk write;
  - atomicity via write-to-tempfile + fsync + ``os.replace``: a crash
    mid-write leaves a ``.tmp`` that restore ignores and the next save
    overwrites.  No partial checkpoint is ever visible under its final
    name;
  - retention keeps the newest ``keep`` checkpoints; deletion happens only
    after the newer save is durable, so there is always at least one
    complete checkpoint on disk once the first save lands.
"""

from __future__ import annotations

import logging
import os
import re
import threading
from typing import Any, List, Optional, Tuple

import numpy as np

from torchft_tpu.checkpointing.serialization import (
    StateDictMeta,
    flatten_state_dict,
    read_state_dict,
    sharding_restorer,
    unflatten_state_dict,
    write_state_dict,
)

logger = logging.getLogger("tpuft")

_CKPT_RE = re.compile(r"^step_(\d{12})\.tpuft$")


def _path(directory: str, step: int) -> str:
    return os.path.join(directory, f"step_{step:012d}.tpuft")


class DiskCheckpointer:
    """Persists one replica group's state dict to a local directory.

    Typical wiring (see examples/train_ddp.py)::

        ckpt = DiskCheckpointer(dir, keep=3)
        step, sd = ckpt.restore_latest(template_fn=save)   # cold start
        if sd is not None: load(sd); manager.load_state_dict({...})
        ...
        if committed and step % every == 0:
            ckpt.save(step, save())                        # async

    Thread model: ``save`` may be called from the training loop; writes run
    on a single daemon worker.  A second ``save`` while one is writing
    blocks until the worker drains (backpressure — checkpoints are ordered
    and never dropped).  A write failure is raised from the *next* ``save``
    or ``wait`` call, never swallowed.
    """

    def __init__(self, directory: str, keep: int = 3) -> None:
        assert keep >= 1, "must retain at least one checkpoint"
        self._dir = directory
        self._keep = keep
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Condition()
        self._pending: Optional[Tuple[int, StateDictMeta, List[np.ndarray]]] = None
        self._error: Optional[BaseException] = None
        self._shutdown = False
        self._worker = threading.Thread(
            target=self._run, name="tpuft_disk_ckpt", daemon=True
        )
        self._worker.start()

    # -- save ---------------------------------------------------------------

    def save(self, step: int, state_dict: Any) -> None:
        """Snapshots ``state_dict`` (device->host fetch happens here, so the
        caller controls what step the checkpoint captures) and enqueues the
        disk write.  Returns once the write is *enqueued*, not durable; call
        ``wait()`` for durability."""
        meta, buffers = flatten_state_dict(state_dict, step=step)
        with self._lock:
            self._raise_pending_error()
            while self._pending is not None and not self._shutdown:
                self._lock.wait(timeout=0.1)
            if self._shutdown:
                raise RuntimeError("DiskCheckpointer is shut down")
            # A write failure observed WHILE blocked in the backpressure wait
            # must surface from this save, not the next one ("raised by the
            # next save" contract counts from the call, not from entry).
            self._raise_pending_error()
            self._pending = (step, meta, buffers)
            self._lock.notify_all()

    def wait(self, timeout: Optional[float] = None) -> None:
        """Blocks until every enqueued save is durable (or raises its
        failure)."""
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while self._pending is not None:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError("checkpoint write still in flight")
                # None blocks until the worker's notify_all — no polling.
                self._lock.wait(timeout=remaining)
            self._raise_pending_error()

    # -- restore ------------------------------------------------------------

    def steps(self) -> List[int]:
        """Completed checkpoint steps on disk, ascending."""
        out = []
        try:
            for name in os.listdir(self._dir):
                m = _CKPT_RE.match(name)
                if m:
                    out.append(int(m.group(1)))
        except FileNotFoundError:
            pass
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def restore(
        self, step: int, template_fn: Optional[Any] = None
    ) -> Any:
        """Loads the checkpoint at ``step``.  ``template_fn`` (a zero-arg
        callable returning the live state dict, i.e. the same callable the
        Manager gets as ``state_dict``) lets restored jax leaves adopt the
        placement of the arrays they replace — required for sharded (HSDP)
        resume, optional for single-device trees."""
        restore_fn = sharding_restorer(template_fn) if template_fn else None
        with open(_path(self._dir, step), "rb") as f:
            meta, buffers = read_state_dict(f)
        return unflatten_state_dict(meta, buffers, restore_sharding=restore_fn)

    def restore_latest(
        self, template_fn: Optional[Any] = None
    ) -> Tuple[Optional[int], Any]:
        """(step, state_dict) of the newest complete checkpoint, or
        (None, None) on a truly cold start.  A checkpoint that fails to
        parse (e.g. torn by a crash of a pre-atomic writer) is skipped with
        a warning and the next-newest is tried."""
        for step in reversed(self.steps()):
            try:
                return step, self.restore(step, template_fn=template_fn)
            except Exception as e:  # noqa: BLE001
                logger.warning(
                    "skipping unreadable checkpoint step %d: %s", step, e
                )
        return None, None

    # -- lifecycle ----------------------------------------------------------

    def shutdown(self) -> None:
        """Drains in-flight writes, then stops the worker."""
        try:
            self.wait()
        finally:
            with self._lock:
                self._shutdown = True
                self._lock.notify_all()
            self._worker.join(timeout=5.0)

    # -- worker -------------------------------------------------------------

    def _raise_pending_error(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(f"previous checkpoint write failed: {err!r}") from err

    def _run(self) -> None:
        while True:
            with self._lock:
                while self._pending is None and not self._shutdown:
                    self._lock.wait()
                if self._shutdown and self._pending is None:
                    return
                step, meta, buffers = self._pending  # type: ignore[misc]
            try:
                self._write(step, meta, buffers)
                self._retain()
            except BaseException as e:  # noqa: BLE001
                logger.error("checkpoint write for step %d failed: %s", step, e)
                with self._lock:
                    self._error = e
            finally:
                with self._lock:
                    self._pending = None
                    self._lock.notify_all()

    def _write(self, step: int, meta: StateDictMeta, buffers: List[np.ndarray]) -> None:
        final = _path(self._dir, step)
        tmp = final + ".tmp"
        with open(tmp, "wb") as f:
            write_state_dict(meta, buffers, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
        # Make the rename itself durable (POSIX: fsync the directory).
        try:
            dfd = os.open(self._dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass
        logger.info("wrote checkpoint step %d (%s)", step, final)

    def _retain(self) -> None:
        steps = self.steps()
        for step in steps[: -self._keep]:
            try:
                os.remove(_path(self._dir, step))
            except OSError:
                pass


class ManagedDiskCheckpoint:
    """The standard train-loop wiring of a DiskCheckpointer to a Manager.

    The disk state dict wraps the peer-heal one (the ``save_fn`` the Manager
    already has) plus the Manager's own ``{step, batches_committed}`` —
    the latter advances by num_participants per committed step, so it
    cannot be derived from the step number.  Usage::

        mdc = ManagedDiskCheckpoint(manager, save, load, ckpt_dir, every=10)
        resumed = mdc.restore()          # before the first quorum join
        ...
        committed = opt.step(grads)
        mdc.maybe_save(committed)        # in the loop
        ...
        mdc.shutdown()                   # never raises; manager.shutdown()
                                         # after it always runs
    """

    def __init__(
        self,
        manager,
        save_fn,
        load_fn,
        directory: str,
        *,
        every: int = 10,
        keep: int = 3,
    ) -> None:
        assert every >= 1, "checkpoint cadence must be >= 1 step"
        self._manager = manager
        self._save_fn = save_fn
        self._load_fn = load_fn
        self._every = every
        self._ckpt = DiskCheckpointer(directory, keep=keep)

    def _disk_state(self):
        return {"user": self._save_fn(), "manager": self._manager.state_dict()}

    def restore(self) -> Optional[int]:
        """Cold-start restore of the newest complete checkpoint; returns its
        step, or None on a truly cold start.  Must run before the first
        quorum join so the group advertises its resumed step."""
        step, sd = self._ckpt.restore_latest(template_fn=self._disk_state)
        if sd is None:
            return None
        self._load_fn(sd["user"])
        self._manager.load_state_dict(sd["manager"])
        logger.info("resumed from disk checkpoint step=%d", step)
        return step

    def maybe_save(self, committed: bool) -> None:
        """Enqueues an async checkpoint on the cadence (committed steps
        only — an uncommitted step's state may be rolled back)."""
        step = self._manager.current_step()
        if committed and step % self._every == 0:
            self._ckpt.save(step, self._disk_state())

    def shutdown(self) -> None:
        """Drains in-flight writes.  Never raises: a deferred write failure
        at exit must not mask the loop's own outcome or skip the caller's
        remaining teardown (manager.shutdown())."""
        try:
            self._ckpt.shutdown()
        except Exception as e:  # noqa: BLE001
            logger.error("disk checkpoint shutdown failed: %s", e)
