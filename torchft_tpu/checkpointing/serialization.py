"""Pytree state-dict (de)serialization shared by the checkpoint transports.

Reference parity: the pytree flatten + _TensorMeta scheme of
torchft/checkpointing/pg_transport.py:27-141 and the streaming serialization
of torchft/checkpointing/_serialization.py, re-designed for JAX: leaves are
jax.Arrays or numpy arrays; jax leaves record their sharding spec by name so
the receiver can restore device placement (the DTensor analogue); all array
payloads travel as raw contiguous bytes after a small pickled header.
"""

from __future__ import annotations

import io
import pickle
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

import numpy as np

__all__ = [
    "TensorMeta",
    "StateDictMeta",
    "as_u8",
    "flatten_state_dict",
    "unflatten_state_dict",
    "state_dict_frames",
    "write_state_dict",
    "read_state_dict",
    "read_exact",
    "read_exact_into",
    "sharding_restorer",
]


def as_u8(arr: np.ndarray) -> np.ndarray:
    """Reinterprets any contiguous array (including ml_dtypes such as
    bfloat16, which memoryview cannot cast) as a flat uint8 view."""
    arr = _contiguous(arr)
    if arr.ndim == 0:
        # .view(uint8) rejects 0-d arrays; reshape is a view, not a copy.
        # Scalar leaves (e.g. optax.adam's `count`) keep their recorded ()
        # shape in TensorMeta — only the byte view is 1-d.
        arr = arr.reshape(1)
    return arr.view(np.uint8).reshape(-1)


def _contiguous(arr: np.ndarray) -> np.ndarray:
    """C-contiguous view of ``arr`` without the copy ``ascontiguousarray``
    would make for an already-contiguous input in edge cases (0-d arrays get
    silently promoted to shape (1,), which would corrupt the recorded leaf
    shape); the serialization hot path must never pay a full host copy for
    a leaf that is already laid out correctly."""
    if arr.flags.c_contiguous:
        return arr
    return np.ascontiguousarray(arr)


@dataclass
class TensorMeta:
    """Array leaf metadata (reference: _TensorMeta,
    torchft/checkpointing/pg_transport.py:39-55)."""

    shape: Tuple[int, ...]
    # The actual np.dtype object: custom dtypes like bfloat16 do not survive
    # a round trip through their .str representation.
    dtype: Any
    nbytes: int
    # "jax" leaves are restored onto device, "np" stay host-side.
    kind: str = "np"
    # Opaque sharding description: (mesh axis names tuple, partition spec)
    # captured from a jax.NamedSharding; None for unsharded/host arrays.
    sharding_spec: Optional[Any] = None


@dataclass
class StateDictMeta:
    """Header for one serialized state dict (reference: _StateDictMeta,
    torchft/checkpointing/pg_transport.py:58-77)."""

    step: int
    treespec_bytes: bytes
    # For each flattened leaf: either ("tensor", index-into-buffers) or
    # ("obj", the pickled-inline python value).
    leaves: List[Tuple[str, Any]] = field(default_factory=list)
    tensor_metas: List[TensorMeta] = field(default_factory=list)
    # Per-buffer integrity checksums (torchft_tpu/checkpointing/integrity):
    # filled by the HTTP transport's background snapshotter, verified by
    # every receiver that sees them — a torn/corrupted stream fails the
    # fetch instead of installing garbage.  None from pre-integrity
    # producers (also what pickles from before these fields existed resolve
    # to, via the dataclass class-level defaults), which skips the check.
    crc_algo: Optional[str] = None
    crcs: Optional[Tuple[int, ...]] = None


def _spec_of(arr: Any) -> Optional[Any]:
    try:
        import jax

        sharding = arr.sharding
        if isinstance(sharding, jax.sharding.NamedSharding):
            return (tuple(sharding.mesh.axis_names), tuple(sharding.spec))
    except Exception:  # noqa: BLE001
        pass
    return None


def flatten_state_dict(state_dict: Any, step: int = 0) -> Tuple[StateDictMeta, List[np.ndarray]]:
    """Flattens a pytree into (header, host buffers).

    jax.Arrays are fetched to host (this blocks on async dispatch, which is
    the TPU analogue of the reference's CPU-copy-on-a-side-stream,
    torchft/checkpointing/http_transport.py:219-241)."""
    import jax

    leaves, treespec = jax.tree_util.tree_flatten(state_dict)
    meta = StateDictMeta(step=step, treespec_bytes=pickle.dumps(treespec))
    buffers: List[np.ndarray] = []
    for leaf in leaves:
        if isinstance(leaf, jax.Array):
            # np.asarray already materializes a fresh host copy; only pay a
            # SECOND copy when that copy came back non-contiguous.
            host = _contiguous(np.asarray(leaf))
            meta.leaves.append(("tensor", len(buffers)))
            meta.tensor_metas.append(
                TensorMeta(
                    shape=tuple(host.shape),
                    dtype=host.dtype,
                    nbytes=host.nbytes,
                    kind="jax",
                    sharding_spec=_spec_of(leaf),
                )
            )
            buffers.append(host)
        elif isinstance(leaf, np.ndarray):
            host = _contiguous(leaf)
            meta.leaves.append(("tensor", len(buffers)))
            meta.tensor_metas.append(
                TensorMeta(
                    shape=tuple(host.shape), dtype=host.dtype, nbytes=host.nbytes
                )
            )
            buffers.append(host)
        else:
            meta.leaves.append(("obj", leaf))
    return meta, buffers


def unflatten_state_dict(
    meta: StateDictMeta,
    buffers: List[np.ndarray],
    restore_sharding: Optional[Any] = None,
) -> Any:
    """Rebuilds the pytree.  `restore_sharding(spec)` may map a recorded
    sharding spec to a live jax Sharding for in-place device placement."""
    import jax

    treespec = pickle.loads(meta.treespec_bytes)
    leaves: List[Any] = []
    for kind, value in meta.leaves:
        if kind == "obj":
            leaves.append(value)
            continue
        tm = meta.tensor_metas[value]
        arr = as_u8(buffers[value]).view(tm.dtype).reshape(tm.shape)
        if tm.kind == "jax":
            sharding = None
            if restore_sharding is not None and tm.sharding_spec is not None:
                sharding = restore_sharding(tm.sharding_spec)
            if sharding is not None:
                arr = jax.device_put(arr, sharding)
            else:
                arr = jax.numpy.asarray(arr)
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(treespec, leaves)


def sharding_restorer(state_dict_fn: Any) -> Any:
    """Builds a spec -> live ``jax.sharding.NamedSharding`` resolver from the
    *current* state dict: fetched leaves adopt the placement of the local
    arrays they replace, so an in-place receive restores a sharded tree onto
    this replica's own mesh without re-deciding placement (the
    DTensor-restore analogue, torchft/checkpointing/pg_transport.py:230-301).

    Keys are the transferable form recorded by ``_spec_of``: (mesh axis
    names, partition spec) — identical across replica groups whose meshes
    share axis names, which is exactly the HSDP setup.
    """

    specs: dict = {}
    rebuilt = [False]

    def rebuild() -> None:
        import jax

        specs.clear()
        for leaf in jax.tree_util.tree_leaves(state_dict_fn()):
            if isinstance(leaf, jax.Array) and isinstance(
                leaf.sharding, jax.sharding.NamedSharding
            ):
                key = (
                    tuple(leaf.sharding.mesh.axis_names),
                    tuple(leaf.sharding.spec),
                )
                specs[key] = leaf.sharding

    def restore(spec: Any):
        key = tuple(spec) if isinstance(spec, list) else spec
        try:
            if key not in specs and not rebuilt[0]:
                # Rebuild lazily, at most once per restorer: the mesh is
                # static so known keys stay valid, and a key still missing
                # after one rebuild (sender has placements this replica's
                # live tree lacks) would otherwise re-flatten the whole tree
                # on every miss of the recovery hot path.
                rebuilt[0] = True
                rebuild()
            return specs.get(key)
        except Exception:  # noqa: BLE001
            return None

    return restore


def state_dict_frames(
    meta: StateDictMeta, buffers: List[np.ndarray]
) -> Tuple[bytes, int]:
    """Encodes the wire prefix (length header + pickled meta) ONCE and
    returns it with the total frame length.  Callers that need a
    Content-Length (http_transport) share this with the writer so the
    framing can never drift from what write_state_dict emits."""
    header = pickle.dumps(meta)
    prefix = len(header).to_bytes(8, "little") + header
    return prefix, len(prefix) + sum(b.nbytes for b in buffers)


def write_state_dict(
    meta: StateDictMeta,
    buffers: List[np.ndarray],
    stream: io.RawIOBase,
    prefix: Optional[bytes] = None,
) -> None:
    """Streams header + raw buffers (reference: streaming ser/de,
    torchft/checkpointing/_serialization.py:28-33).  A caller that already
    encoded the prefix via state_dict_frames (to send a Content-Length)
    passes it back in so the body framing comes from one place."""
    if prefix is None:
        prefix, _ = state_dict_frames(meta, buffers)
    stream.write(prefix)
    for buf in buffers:
        stream.write(memoryview(as_u8(buf)))


def read_state_dict(stream: io.RawIOBase) -> Tuple[StateDictMeta, List[np.ndarray]]:
    """Reads one write_state_dict frame: (header, raw host buffers).

    When the header carries per-buffer checksums (``meta.crcs``), every
    buffer is verified as it lands; a mismatch raises IOError so the caller
    fails the fetch — never installs a torn stream."""
    header_len = int.from_bytes(read_exact(stream, 8), "little")
    meta: StateDictMeta = pickle.loads(read_exact(stream, header_len))
    crcs = getattr(meta, "crcs", None)
    algo = getattr(meta, "crc_algo", None)
    buffers: List[np.ndarray] = []
    for i, tm in enumerate(meta.tensor_metas):
        raw = read_exact(stream, tm.nbytes)
        if crcs is not None:
            from torchft_tpu.checkpointing.integrity import verify

            verify(memoryview(raw), crcs[i], algo, f"checkpoint buffer {i}")
        buffers.append(np.frombuffer(raw, dtype=np.uint8).view(tm.dtype).reshape(tm.shape))
    return meta, buffers


def read_exact_into(stream: io.RawIOBase, view: memoryview) -> None:
    """Fills ``view`` completely from ``stream`` (readinto when the stream
    supports it — bytes land directly in the caller's preallocated buffer,
    no intermediate ``bytes`` materialization).  This is what lets the
    chunked/striped HTTP receive path stream tensor payloads straight into
    their final per-tensor buffers instead of double-copying."""
    n = len(view)
    got = 0
    readinto = getattr(stream, "readinto", None)
    while got < n:
        if readinto is not None:
            r = readinto(view[got:])
            if not r:
                raise EOFError(f"stream ended after {got}/{n} bytes")
            got += r
        else:
            chunk = stream.read(n - got)
            if not chunk:
                raise EOFError(f"stream ended after {got}/{n} bytes")
            view[got : got + len(chunk)] = chunk
            got += len(chunk)


def read_exact(stream: io.RawIOBase, n: int) -> bytearray:
    """Reads exactly n bytes into a preallocated buffer, returned without a
    final bytes() copy (np.frombuffer/pickle accept bytearray)."""
    out = bytearray(n)
    read_exact_into(stream, memoryview(out))
    return out

