"""Timed readers-writer lock.

Reference parity: torchft/checkpointing/_rwlock.py:42-132 (a vendored
two-mutex RW lock).  Re-implemented on a condition variable with
writer-preference and timeouts: the training loop holds the write lock while
weights mutate; checkpoint-serving HTTP threads take timed read locks.
"""

from __future__ import annotations

import threading
from typing import Optional


class RWLock:
    """A writer-preferring readers-writer lock with timeout support."""

    def __init__(self, timeout: Optional[float] = None) -> None:
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0
        self._default_timeout = timeout

    # -- read side ----------------------------------------------------------

    def r_acquire(self, timeout: Optional[float] = None) -> bool:
        timeout = timeout if timeout is not None else self._default_timeout
        with self._cond:
            ok = self._cond.wait_for(
                lambda: not self._writer and self._writers_waiting == 0, timeout=timeout
            )
            if not ok:
                return False
            self._readers += 1
            return True

    def r_release(self) -> None:
        with self._cond:
            assert self._readers > 0, "r_release without matching r_acquire"
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    # -- write side ---------------------------------------------------------

    def w_acquire(self, timeout: Optional[float] = None) -> bool:
        timeout = timeout if timeout is not None else self._default_timeout
        with self._cond:
            self._writers_waiting += 1
            try:
                ok = self._cond.wait_for(
                    lambda: not self._writer and self._readers == 0, timeout=timeout
                )
                if not ok:
                    return False
                self._writer = True
                return True
            finally:
                self._writers_waiting -= 1
                # Readers block on writers_waiting == 0; a timed-out writer
                # must wake them or they stall until their own timeout.
                self._cond.notify_all()

    def w_release(self) -> None:
        with self._cond:
            assert self._writer, "w_release without matching w_acquire"
            self._writer = False
            self._cond.notify_all()

    def w_locked(self) -> bool:
        with self._cond:
            return self._writer

    class _ReadGuard:
        def __init__(self, lock: "RWLock", timeout: Optional[float]) -> None:
            self._lock = lock
            self._timeout = timeout

        def __enter__(self) -> None:
            if not self._lock.r_acquire(self._timeout):
                raise TimeoutError("timed out acquiring read lock")

        def __exit__(self, *args: object) -> None:
            self._lock.r_release()

    def r_lock(self, timeout: Optional[float] = None) -> "RWLock._ReadGuard":
        return RWLock._ReadGuard(self, timeout)
