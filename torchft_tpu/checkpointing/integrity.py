"""Per-chunk integrity checksums for the checkpoint/shard wire paths.

A heal installs fetched bytes straight into live weights, so a torn or
corrupted HTTP stream (donor killed mid-write, proxy truncation, bit flips
on a flaky link) must fail the fetch — latching the step error and
retrying — instead of installing garbage (the chaos-cell failure mode
ROADMAP item 6 names).  Every serialized buffer and every erasure shard
therefore carries a CRC32C computed at snapshot/encode time and verified
at receive time.

CRC32C (Castagnoli) via ``google_crc32c`` when available (C extension,
multi-GB/s — the same polynomial GCS, Snappy and iSCSI use); otherwise
``zlib.crc32`` (also C speed).  The algorithm TAG travels with every
checksum so the verifier always applies the algorithm the producer used —
mixed fleets stay correct, they never silently skip the check.
"""

from __future__ import annotations

import zlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["CRC_ALGO", "checksum", "checksum_buffers", "verify"]

try:  # pragma: no cover - exercised via whichever backend the host has
    import google_crc32c as _crc32c_mod

    def _crc32c(data) -> int:
        # The C extension insists on READ-ONLY bytes; memoryviews and
        # bytearrays (the zero-copy receive paths) need one transient copy.
        if not isinstance(data, bytes):
            data = bytes(data)
        return int(_crc32c_mod.value(data))

    CRC_ALGO = "crc32c"
except ImportError:  # pragma: no cover
    _crc32c_mod = None

    def _crc32c(data) -> int:
        return zlib.crc32(data) & 0xFFFFFFFF

    CRC_ALGO = "crc32"

_ALGOS = {
    "crc32c": _crc32c,
    "crc32": lambda data: zlib.crc32(data) & 0xFFFFFFFF,
}


def checksum(data, algo: str = CRC_ALGO) -> int:
    """Checksum of a bytes-like / uint8-viewable payload under ``algo``."""
    if isinstance(data, np.ndarray):
        from torchft_tpu.checkpointing.serialization import as_u8

        data = memoryview(as_u8(data))
    return _ALGOS[algo](data)


def checksum_buffers(buffers: Sequence[np.ndarray]) -> Tuple[str, List[int]]:
    """(algo, per-buffer checksums) for a flattened state dict — computed
    once per snapshot on the background snapshotter, carried in the
    StateDictMeta header, verified buffer-by-buffer by every receiver."""
    return CRC_ALGO, [checksum(b) for b in buffers]


def verify(data, expect: int, algo: Optional[str], what: str) -> None:
    """Raises IOError naming ``what`` when the payload does not hash to
    ``expect``.  Unknown algorithms fail loudly too: a checksum that cannot
    be verified is indistinguishable from a corrupt stream, and installing
    unverified bytes is exactly what this module exists to prevent."""
    algo = algo or CRC_ALGO
    fn = _ALGOS.get(algo)
    if fn is None:
        raise IOError(f"{what}: unknown checksum algorithm {algo!r}")
    if isinstance(data, np.ndarray):
        from torchft_tpu.checkpointing.serialization import as_u8

        data = memoryview(as_u8(data))
    got = fn(data)
    if got != expect:
        raise IOError(
            f"{what}: checksum mismatch ({algo} {got:#010x} != expected "
            f"{expect:#010x}) — stream torn or corrupted"
        )
