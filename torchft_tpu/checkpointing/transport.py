"""Checkpoint transport abstraction for live peer-to-peer weight recovery.

Reference parity: CheckpointTransport ABC, torchft/checkpointing/transport.py:14-69.
A transport moves a full state dict (a pytree of jax/numpy arrays plus
metadata) from a healthy replica group to a recovering one *while training
continues* on the healthy groups.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Generic, List, TypeVar

T = TypeVar("T")


class CheckpointTransport(ABC, Generic[T]):
    # True for pull-based transports whose serving is passive (an opened
    # window costs nothing if unused): the manager then serves EVERY
    # recovering group, enabling striped multi-donor fetches.  Push/
    # point-to-point transports (collective send/recv) keep the default —
    # their sends block until matched, so they only serve primary
    # assignments.
    serves_all_donors: bool = False

    @abstractmethod
    def metadata(self) -> str:
        """Returns transport metadata (e.g. "http://host:port") relayed to
        recovering peers through the manager quorum."""

    @abstractmethod
    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: T, timeout: float
    ) -> None:
        """Makes `state_dict` for `step` available to the destination replica
        ranks (push- or pull-based depending on the transport)."""

    def disallow_checkpoint(self) -> None:
        """Called when the weights are about to be mutated (optimizer step);
        pull-based transports must stop serving the stale checkpoint."""

    @abstractmethod
    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: float
    ) -> T:
        """Fetches the state dict for `step` from the source replica rank
        using its advertised `metadata`.

        The manager may pass an ordered donor-metadata LIST instead of one
        string when the quorum assigned several healthy donors; transports
        that cannot stripe across sources should use the first entry."""

    def shutdown(self, wait: bool = True) -> None:
        """Releases transport resources."""
