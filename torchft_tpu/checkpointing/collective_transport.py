"""Checkpoint transport over the reconfigurable Collective's send/recv.

Reference parity: torchft/checkpointing/pg_transport.py.  Shares the
manager's data-plane collective (already rendezvoused across replica groups
each quorum): a pickled header travels first (tag 1/2), then each array
buffer raw, tag-by-tag (tag 3+i).  The receiver may pass an existing state
dict to receive *in place*: fetched buffers are placed with the live arrays'
shardings so device layout is preserved (the DTensor-restore analogue,
torchft/checkpointing/pg_transport.py:230-301).
"""

from __future__ import annotations

import logging
import pickle
import time
from contextlib import contextmanager
from typing import Any, Callable, Generator, List, Optional

import numpy as np

from torchft_tpu.checkpointing.serialization import (
    as_u8,
    flatten_state_dict,
    sharding_restorer,
    unflatten_state_dict,
)
from torchft_tpu.checkpointing.transport import CheckpointTransport
from torchft_tpu.collectives import Collective

logger = logging.getLogger("torchft_tpu.checkpointing.collective")


@contextmanager
def _timeit(name: str) -> Generator[None, None, None]:
    """Wall-clock log context (reference: _timeit,
    torchft/checkpointing/pg_transport.py:80-85)."""
    start = time.perf_counter()
    yield
    logger.info("%s took %.3fs", name, time.perf_counter() - start)


class CollectiveTransport(CheckpointTransport):
    """Streams state dicts between replica ranks over collective send/recv.

    Args:
        collective: the shared, manager-configured collective whose ranks are
            replica-group ranks.
        timeout: per-transfer deadline.
        state_dict_fn: when set, recv_checkpoint receives *in place*: the
            current state dict's jax leaves provide the shardings to restore
            fetched weights onto device without re-deciding placement.
    """

    def __init__(
        self,
        collective: Collective,
        timeout: float = 60.0,
        state_dict_fn: Optional[Callable[[], Any]] = None,
    ) -> None:
        self._collective = collective
        self._timeout = timeout
        self._state_dict_fn = state_dict_fn

    def metadata(self) -> str:
        return "<collective>"

    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: Any, timeout: float
    ) -> None:
        with _timeit("flatten_state_dict"):
            meta, buffers = flatten_state_dict(state_dict, step=step)
        header = pickle.dumps(meta)
        header_arr = np.frombuffer(header, dtype=np.uint8)

        with _timeit(f"send_checkpoint to {dst_ranks}"):
            works = []
            for dst in dst_ranks:
                works.append(self._collective.send(header_arr, dst, tag=1))
            for work in works:
                work.wait(timeout=timeout)
            works = []
            for i, buf in enumerate(buffers):
                flat = as_u8(buf)
                for dst in dst_ranks:
                    works.append(self._collective.send(flat, dst, tag=3 + i))
            for work in works:
                work.wait(timeout=timeout)

    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: float
    ) -> Any:
        if not isinstance(metadata, str):
            # Multi-donor metadata list from the manager: collective recv is
            # inherently single-source (one send/recv ring peer), use the
            # primary.
            metadata = metadata[0]
        with _timeit(f"recv_checkpoint from {src_rank}"):
            header = self._collective.recv((0,), np.uint8, src_rank, tag=1).wait(
                timeout=timeout
            )
            meta = pickle.loads(bytes(header))
            if meta.step != step:
                raise RuntimeError(
                    f"checkpoint step mismatch: wanted {step}, got {meta.step}"
                )
            buffers: List[np.ndarray] = []
            for i, tm in enumerate(meta.tensor_metas):
                raw = self._collective.recv((tm.nbytes,), np.uint8, src_rank, tag=3 + i).wait(
                    timeout=timeout
                )
                # recv returns a contiguous uint8 ndarray; reinterpret in
                # place (a bytes() roundtrip here would copy every buffer).
                buffers.append(
                    np.ascontiguousarray(raw).view(tm.dtype).reshape(tm.shape)
                )
        restore = (
            sharding_restorer(self._state_dict_fn)
            if self._state_dict_fn is not None
            else None
        )
        return unflatten_state_dict(meta, buffers, restore)

    def shutdown(self, wait: bool = True) -> None:
        # The collective is owned by the manager; nothing to release here.
        pass
