from torchft_tpu.checkpointing.transport import CheckpointTransport

__all__ = ["CheckpointTransport"]
