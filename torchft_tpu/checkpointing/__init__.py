from torchft_tpu.checkpointing.disk import DiskCheckpointer, ManagedDiskCheckpoint
from torchft_tpu.checkpointing.transport import CheckpointTransport

__all__ = ["CheckpointTransport", "DiskCheckpointer", "ManagedDiskCheckpoint"]
