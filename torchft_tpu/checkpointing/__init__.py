from torchft_tpu.checkpointing.disk import DiskCheckpointer
from torchft_tpu.checkpointing.transport import CheckpointTransport

__all__ = ["CheckpointTransport", "DiskCheckpointer"]
