"""HTTP checkpoint transport: pull-based live weight recovery.

Reference parity: torchft/checkpointing/http_transport.py.  A threading HTTP
server on every replica streams the current-step state dict to recovering
peers; an RWLock gates serving so the train loop can mutate weights safely
(write-held while training, released while a checkpoint is being served);
the URL scheme is /checkpoint/<step>/{full|header|metadata|<chunk_i>}.

Two performance structures on top of the reference design:

- **Async snapshot pipeline** (donor side): ``send_checkpoint`` only
  enqueues the pytree and opens the serving window — a background worker
  does the device→host flatten into the inactive buffer slot and atomically
  flips the served ``(meta, buffers, step)``, so the donor's train loop
  never blocks on host copies (jax leaves are immutable, making the
  by-reference snapshot safe).  A request for the pending step blocks
  (bounded) until the flip instead of 404ing.

- **Striped multi-donor fetch** (receiver side): ``recv_checkpoint``
  accepts a list of donor URLs, partitions the buffer index space into
  round-robin stripes (the ``chunk_<i>?n=<total>`` framing — receiver
  parameterized, not server config), assigns stripes to donors balanced by
  bytes, pulls them in parallel streaming each tensor straight into its
  preallocated buffer, and fails a stripe over to the next donor on
  error/timeout — so heal bandwidth scales with the donor count and a donor
  dying mid-heal degrades instead of aborting.

Two integrity/redundancy structures on top (this PR):

- **Per-buffer CRC32C**: the background snapshotter checksums every flat
  buffer once per snapshot (meta.crcs); receivers verify each buffer as it
  lands — on the /full path, the striped path, and the shard endpoints — so
  a torn or corrupted stream mid-heal FAILS the fetch (stripe failover,
  then latched error + retry) instead of installing garbage.

- **Erasure-shard endpoints** (torchft_tpu/ec): the same server also hosts
  the group's :class:`~torchft_tpu.ec.store.ShardStore` at
  ``GET/POST /ec/shard/<step>/<idx>`` + ``GET /ec/have/<step>`` — static
  self-verifying bytes served WITHOUT the checkpoint RWLock or a serving
  window, which is what makes reconstruction donor-free.  The snapshotter
  additionally accepts non-serving snapshot enqueues (``enqueue_snapshot``
  with serve=False): the flatten runs and the EC hook fires, but the
  served ``(meta, buffers, step)`` slot is NOT flipped, so per-commit
  encode generations can never 404 a healer mid-fetch.
"""

from __future__ import annotations

import io
import logging
import os
import pickle
import socket
import threading
import time
import urllib.parse
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from torchft_tpu.checkpointing._rwlock import RWLock
from torchft_tpu.checkpointing.serialization import (
    StateDictMeta,
    as_u8,
    flatten_state_dict,
    read_exact,
    read_exact_into,
    read_state_dict,
    state_dict_frames,
    unflatten_state_dict,
    write_state_dict,
)
from torchft_tpu.checkpointing.transport import CheckpointTransport
from torchft_tpu.http import ThreadingHTTPServerV6

logger = logging.getLogger("torchft_tpu.checkpointing.http")


class HTTPTransport(CheckpointTransport):
    """Serves pickled+raw state-dict streams over HTTP.

    Args:
        timeout: per-request deadline.
        num_chunks: if > 0, single-donor receivers that ask the legacy
            ``/metadata`` endpoint are told to split the fetch into this many
            round-robin chunks (reference:
            torchft/checkpointing/http_transport.py:287-298).  Striped
            multi-donor receivers choose their own stripe count instead.
        restore_sharding: optional spec -> jax.Sharding resolver used when
            rebuilding fetched arrays on device.
    """

    # Pull-based: opening the serving window for every recovering group is
    # free, which is what lets striped receivers fetch from all donors.
    serves_all_donors = True

    def __init__(
        self,
        timeout: float = 60.0,
        num_chunks: int = 0,
        restore_sharding: Optional[Callable[[Any], Any]] = None,
    ) -> None:
        self._timeout = timeout
        self._num_chunks = num_chunks
        self._restore_sharding = restore_sharding
        # Held while training mutates weights; released (allow_checkpoint)
        # while a consistent snapshot is being served.
        self._checkpoint_lock = RWLock(timeout=timeout)
        self._checkpoint_lock.w_acquire()
        # Served snapshot + async-snapshotter state, all guarded by
        # _snap_cond: _state/_step are the ACTIVE (served) buffer slot,
        # _snap_pending the newest enqueued-but-not-flattened snapshot
        # (double buffering: the active slot keeps serving while the worker
        # fills the inactive one; the flip is atomic under the condvar).
        self._snap_cond = threading.Condition()
        self._state: Optional[Tuple[StateDictMeta, List[np.ndarray]]] = None
        self._step = -1
        # Pending snapshots keyed by serve flag: a per-commit EC enqueue
        # (serve=False) must never overwrite a pending SERVING enqueue in
        # the single drop-stale slot, and vice versa.  Serving entries are
        # flattened first (a healer is waiting on that flip).
        self._snap_pending: Dict[bool, Tuple[int, Any]] = {}
        self._pending_step = -1
        self._snap_busy = False
        # Flatten errors latched PER KIND: a successful EC (serve=False)
        # flatten must not clear a failed SERVING snapshot's error out of
        # wait_snapshot (and an EC failure must not mark a servable donor
        # failed) — the two pipelines share a worker, not an outcome.
        self._snap_error: Dict[bool, Optional[Exception]] = {}
        self._shutdown = False
        self._spans = None  # optional obs SpanTracker (set_span_tracker)
        # Erasure-shard plane (torchft_tpu/ec): a ShardStore served at
        # /ec/shard/<step>/<idx>, and a hook the background snapshotter
        # calls with every flattened snapshot (the EC encode entry point).
        self._shard_store = None
        self._snapshot_hook: Optional[Callable[[int, StateDictMeta, List[np.ndarray]], None]] = None
        # Per-buffer CRCs on served snapshots (TPUFT_HTTP_CRC=0 disables
        # computing them; receivers verify whenever the header carries them).
        self._crc_enabled = os.environ.get("TPUFT_HTTP_CRC", "1") != "0"
        # Optional serving-side bandwidth cap shared by ALL connections of
        # this transport (TPUFT_HTTP_SHAPED_MBPS, read at construction):
        # emulates a donor-NIC link for benchmarking the link-bound regime
        # where striped multi-donor healing scales (the checkpoint-path
        # sibling of the collective layer's TPUFT_SHAPED_LINK).
        self._pacer = _ServerPacer.from_env()

        transport = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt: str, *args: object) -> None:
                logger.debug(fmt % args)

            def do_GET(self) -> None:
                path, _, query = self.path.partition("?")
                parts = path.strip("/").split("/")
                # /ec/shard/<step>/<idx> and /ec/have/<step>: the erasure
                # shard plane — static self-verifying bytes served straight
                # from the ShardStore, WITHOUT the checkpoint RWLock or a
                # serving window (the donor-free property).
                if parts and parts[0] == "ec":
                    transport._handle_ec_get(self, parts, query)
                    return
                # /checkpoint/<step>/<what>[?n=<stripes>]
                if len(parts) != 3 or parts[0] != "checkpoint":
                    self.send_error(404, "unknown path")
                    return
                try:
                    step = int(parts[1])
                except ValueError:
                    self.send_error(400, "bad step")
                    return
                what = parts[2]
                n_req: Optional[int] = None
                if query:
                    try:
                        raw_n = urllib.parse.parse_qs(query).get("n", [None])[0]
                        if raw_n is not None:
                            n_req = int(raw_n)
                    except ValueError:
                        self.send_error(400, "bad stripe count")
                        return
                    if n_req is not None and n_req <= 0:
                        self.send_error(400, "bad stripe count")
                        return
                try:
                    # A snapshot for this step may still be flattening on the
                    # worker thread: block (bounded) for the flip instead of
                    # 404ing a healer that raced the async pipeline.
                    transport._await_flip(step)
                    with transport._checkpoint_lock.r_lock(transport._timeout):
                        # Re-check after acquiring the read lock: a request
                        # that arrived before the serving window opened sees
                        # the enqueue only now (r_lock blocked on it), so the
                        # first _await_flip ran before there was anything
                        # pending to wait for.
                        transport._await_flip(step)
                        with transport._snap_cond:
                            if transport._state is None or transport._step != step:
                                self.send_error(
                                    404,
                                    f"checkpoint for step {step} not available "
                                    f"(serving {transport._step})",
                                )
                                return
                            # Buffer references are immutable after the flip:
                            # serving can proceed outside the condvar even if
                            # a newer snapshot flips mid-stream.
                            meta, buffers = transport._state
                        if what == "full":
                            # Stream header + raw buffers straight to the
                            # socket: materializing a multi-GB BytesIO first
                            # is an extra full copy on the default healing
                            # path.  state_dict_frames is the writer's own
                            # framing, so Content-Length cannot drift from
                            # what read_state_dict expects.
                            prefix, total = state_dict_frames(meta, buffers)
                            self.send_response(200)
                            self.send_header(
                                "Content-Type", "application/octet-stream"
                            )
                            self.send_header("Content-Length", str(total))
                            self.end_headers()
                            write_state_dict(
                                meta,
                                buffers,
                                _paced(self.wfile, transport._pacer),
                                prefix=prefix,
                            )
                            return
                        if what.startswith("chunk_"):
                            # Chunks stream too: building a ~GB chunk in a
                            # BytesIO first costs two full copies made while
                            # holding the GIL, which convoys the parallel
                            # chunk readers (measured 3x worse than
                            # sequential on a 1-core host).
                            framed = transport._chunk_frame(meta, buffers, what, n_req)
                            if framed is None:
                                self.send_error(404, f"unknown object {what}")
                                return
                            sub_prefix, sel, total = framed
                            self.send_response(200)
                            self.send_header(
                                "Content-Type", "application/octet-stream"
                            )
                            self.send_header("Content-Length", str(total))
                            self.end_headers()
                            out = _paced(self.wfile, transport._pacer)
                            out.write(sub_prefix)
                            for i in sel:
                                out.write(memoryview(as_u8(buffers[i])))
                            return
                        payload = transport._render(meta, buffers, what)
                        if payload is None:
                            self.send_error(404, f"unknown object {what}")
                            return
                        self.send_response(200)
                        self.send_header("Content-Type", "application/octet-stream")
                        self.send_header("Content-Length", str(len(payload)))
                        self.end_headers()
                        self.wfile.write(payload)
                except TimeoutError:
                    self.send_error(503, "checkpoint lock busy")

            def do_POST(self) -> None:
                parts = self.path.partition("?")[0].strip("/").split("/")
                if parts and parts[0] == "ec":
                    transport._handle_ec_post(self, parts)
                    return
                self.send_error(404, "unknown path")

        self._server = ThreadingHTTPServerV6(("", 0), Handler)
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="tpuft_http_transport", daemon=True
        )
        self._thread.start()
        self._snap_thread = threading.Thread(
            target=self._snapshot_loop, name="tpuft_http_snapshot", daemon=True
        )
        self._snap_thread.start()

    # -- async snapshot pipeline --------------------------------------------

    def set_span_tracker(self, spans) -> None:
        """Wires an :class:`~torchft_tpu.obs.spans.SpanTracker` so the
        background flatten emits ``snapshot`` spans — the evidence in
        ``obs.report`` that snapshotting overlaps the donor's train step
        instead of sitting on its critical path."""
        self._spans = spans

    def attach_shard_store(self, store) -> None:
        """Attaches a :class:`~torchft_tpu.ec.store.ShardStore` so this
        server also serves/accepts erasure shards on ``/ec/...`` (see
        docs/wire.md "Erasure shard endpoints")."""
        self._shard_store = store

    def set_snapshot_hook(
        self, hook: Callable[[int, StateDictMeta, List[np.ndarray]], None]
    ) -> None:
        """Registers a callable run on the BACKGROUND snapshotter after
        every successful flatten — the EC plane's encode entry point
        (:meth:`~torchft_tpu.ec.store.ECPlane.on_snapshot`).  The hook runs
        off the train loop by construction and must not raise."""
        self._snapshot_hook = hook

    def _snapshot_loop(self) -> None:
        """Worker: flatten the newest enqueued pytree into the inactive
        buffer slot, then atomically flip the served snapshot (serving
        enqueues) and fire the snapshot hook (all enqueues)."""
        while True:
            with self._snap_cond:
                while not self._snap_pending and not self._shutdown:
                    self._snap_cond.wait()
                if self._shutdown:
                    return
                # Serving enqueues first: a healer is blocked on that flip,
                # while an EC encode generation only has to land eventually.
                serve = True in self._snap_pending
                step, state_dict = self._snap_pending.pop(serve)
                self._snap_busy = True
            try:
                # Device->host copies happen HERE, off the train loop.  The
                # old snapshot keeps serving from the active slot until the
                # flip below (double buffering).
                if self._spans is not None:
                    with self._spans.span("snapshot", step=step):
                        meta, buffers = self._flatten_with_crcs(state_dict, step)
                else:
                    meta, buffers = self._flatten_with_crcs(state_dict, step)
            except Exception as e:  # noqa: BLE001 — a failed snapshot must
                # not kill the worker; healers see 404 and retry next round.
                logger.exception("async snapshot for step %s failed: %s", step, e)
                with self._snap_cond:
                    self._snap_error[serve] = e
                    self._snap_busy = False
                    if serve and self._pending_step == step:
                        self._pending_step = -1
                    self._snap_cond.notify_all()
                continue
            with self._snap_cond:
                if serve and step >= self._step:
                    self._state = (meta, buffers)
                    self._step = step
                self._snap_error[serve] = None
                if serve and self._pending_step == step:
                    self._pending_step = -1
                # The flip is visible NOW (_await_flip wakes here); the
                # busy flag stays up through the hook so wait_snapshot
                # covers the full pipeline including the EC encode.
                self._snap_cond.notify_all()
            hook = self._snapshot_hook
            if hook is not None:
                try:
                    hook(step, meta, buffers)
                except Exception as e:  # noqa: BLE001 — EC encode is
                    # best-effort; a failure degrades to donor-only healing.
                    logger.exception("snapshot hook for step %s failed: %s", step, e)
            with self._snap_cond:
                self._snap_busy = False
                self._snap_cond.notify_all()

    def _flatten_with_crcs(self, state_dict: Any, step: int):
        """flatten_state_dict + per-buffer CRCs stamped into the header —
        computed ONCE here on the background thread, verified by every
        receiver (full, striped, shard endpoints)."""
        meta, buffers = flatten_state_dict(state_dict, step=step)
        if self._crc_enabled:
            from torchft_tpu.checkpointing.integrity import checksum_buffers

            meta.crc_algo, crcs = checksum_buffers(buffers)
            meta.crcs = tuple(crcs)
        return meta, buffers

    def _await_flip(self, step: int) -> None:
        """Blocks while a snapshot for ``step`` is enqueued/flattening, until
        it becomes servable (or fails / times out)."""
        deadline = time.monotonic() + self._timeout
        with self._snap_cond:
            while (
                self._step < step
                and self._pending_step >= step
                and not self._shutdown
            ):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("snapshot still pending")
                self._snap_cond.wait(remaining)

    def wait_snapshot(self, timeout: Optional[float] = None) -> bool:
        """Blocks until no snapshot is pending (benches/tests: separates
        snapshot cost from fetch cost).  Returns False on timeout or when
        the last snapshot FAILED to flatten — a silent True here would let
        a bench/test treat an unservable donor as ready."""
        deadline = time.monotonic() + (timeout if timeout is not None else self._timeout)
        with self._snap_cond:
            while (
                self._snap_pending or self._snap_busy or self._pending_step >= 0
            ) and not self._shutdown:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._snap_cond.wait(remaining)
            # Servability is the SERVING pipeline's outcome only: an EC
            # (serve=False) flatten failure degrades the shard plane, not
            # the donor's checkpoint window.
            return self._snap_error.get(True) is None

    # -- serving ------------------------------------------------------------

    def _chunk_frame(
        self,
        meta: StateDictMeta,
        buffers: List[np.ndarray],
        what: str,
        n_req: Optional[int] = None,
    ) -> Optional[Tuple[bytes, List[int], int]]:
        """(sub_meta prefix, selected buffer indices, total body length) for
        one chunk_<i> request, or None for a bad index.  The receiver may
        parameterize the round-robin split via ``?n=<total>`` (striped
        multi-donor fetch); without it the server's own chunk config applies
        (torchft/checkpointing/http_transport.py:287-298)."""
        try:
            idx = int(what[len("chunk_"):])
        except ValueError:
            return None  # malformed chunk index -> 404, not a 500 traceback
        n = n_req if n_req is not None else self._chunk_count(buffers)
        if idx < 0 or idx >= n:
            return None
        sel = [i for i in range(len(buffers)) if i % n == idx]
        sub_meta = pickle.dumps((idx, sel))
        prefix = len(sub_meta).to_bytes(8, "little") + sub_meta
        total = len(prefix) + sum(buffers[i].nbytes for i in sel)
        return prefix, sel, total

    def _render(self, meta: StateDictMeta, buffers: List[np.ndarray], what: str) -> Optional[bytes]:
        out = io.BytesIO()
        if what == "header":
            # Just the length-prefixed pickled StateDictMeta — what a chunked
            # receiver needs to size its buffers, without making the server
            # materialize the full multi-GB stream.  Same framing source as
            # the /full path so the prefix format cannot drift.
            out.write(state_dict_frames(meta, [])[0])
        elif what == "metadata":
            out.write(pickle.dumps(self._chunk_count(buffers)))
        else:
            return None
        return out.getvalue()

    def _chunk_count(self, buffers: List[np.ndarray]) -> int:
        if self._num_chunks <= 0:
            return 1
        return max(1, min(self._num_chunks, len(buffers)))

    # -- erasure shard endpoints (torchft_tpu/ec) ----------------------------

    def _handle_ec_get(self, handler, parts: List[str], query: str = "") -> None:
        """GET /ec/shard/<step>/<idx>[?part=<i>&n=<N>] (one self-verifying
        shard frame, or header + payload byte-range part i of N — the
        striped-receiver idiom of the checkpoint path's ``?n=`` chunks,
        receiver-parameterized so reconstruction chooses its own
        parallelism; see ec.encoder.write_shard_part for the range
        contract) and GET /ec/have/<step> (JSON inventory + geometry).
        Served straight from the ShardStore — no RWLock, no serving
        window."""
        store = self._shard_store
        if store is None:
            handler.send_error(404, "no shard store attached")
            return
        try:
            if len(parts) == 4 and parts[1] == "shard":
                step, idx = int(parts[2]), int(parts[3])
                shard = store.get(step, idx)
                if shard is None:
                    handler.send_error(404, f"shard {idx} for step {step} not held")
                    return
                from torchft_tpu.ec.encoder import write_shard, write_shard_part

                part = n = None
                if query:
                    qs = urllib.parse.parse_qs(query)
                    raw_part = qs.get("part", [None])[0]
                    raw_n = qs.get("n", [None])[0]
                    if raw_part is not None or raw_n is not None:
                        try:
                            part, n = int(raw_part or 0), int(raw_n or 0)
                        except ValueError:
                            handler.send_error(400, "bad shard range")
                            return
                        if n <= 0 or not 0 <= part < n:
                            handler.send_error(400, "bad shard range")
                            return
                body = (
                    write_shard(shard) if n is None
                    else write_shard_part(shard, part, n)
                )
                handler.send_response(200)
                handler.send_header("Content-Type", "application/octet-stream")
                handler.send_header("Content-Length", str(len(body)))
                handler.end_headers()
                # Shares the donor-NIC pacer: shard serving rides the same
                # physical link as checkpoint serving in the shaped regime.
                _paced(handler.wfile, self._pacer).write(body)
                return
            if len(parts) == 3 and parts[1] == "have":
                import json

                body = json.dumps(store.inventory(int(parts[2]))).encode()
                handler.send_response(200)
                handler.send_header("Content-Type", "application/json")
                handler.send_header("Content-Length", str(len(body)))
                handler.end_headers()
                handler.wfile.write(body)
                return
        except ValueError:
            handler.send_error(400, "bad step/shard index")
            return
        handler.send_error(404, "unknown ec path")

    def _handle_ec_post(self, handler, parts: List[str]) -> None:
        """POST /ec/shard/<step>/<idx>: a peer pushing a parity shard.  The
        frame's CRC is verified BEFORE storing — a torn push is refused
        (400), never served onward."""
        store = self._shard_store
        if store is None:
            handler.send_error(404, "no shard store attached")
            return
        if len(parts) != 4 or parts[1] != "shard":
            handler.send_error(404, "unknown ec path")
            return
        try:
            step, idx = int(parts[2]), int(parts[3])
            length = int(handler.headers.get("Content-Length", "0"))
        except ValueError:
            handler.send_error(400, "bad step/shard index")
            return
        if length <= 0:
            handler.send_error(400, "missing body")
            return
        try:
            from torchft_tpu.checkpointing.serialization import read_exact
            from torchft_tpu.ec.encoder import read_shard

            shard = read_shard(bytes(read_exact(handler.rfile, length)))
            if shard.step != step or shard.idx != idx:
                raise IOError(
                    f"shard header ({shard.step},{shard.idx}) != path ({step},{idx})"
                )
        except Exception as e:  # noqa: BLE001 — corrupt push -> 400, not a 500
            # ascii-sanitized: the HTTP status line is latin-1 encoded and
            # error text may carry wider characters.
            msg = f"bad shard frame: {e}".encode("ascii", "replace").decode()
            handler.send_error(400, msg)
            return
        store.put(shard)
        handler.send_response(204)
        handler.send_header("Content-Length", "0")
        handler.end_headers()

    def materialize(self, meta: StateDictMeta, buffers: List[np.ndarray]) -> Any:
        """(meta, buffers) -> the live pytree, through the same sharding
        restorer the donor-fetch path uses — the final leg of an erasure
        reconstruction, shared so the two heal paths cannot diverge."""
        return unflatten_state_dict(meta, buffers, self._restore_sharding)

    def metadata(self) -> str:
        return f"http://{socket.gethostname()}:{self._port}"

    # -- CheckpointTransport ------------------------------------------------

    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: Any, timeout: float
    ) -> None:
        """Pull-based: enqueue the snapshot and open the serving window.

        Returns immediately — the flatten (device->host copy of every leaf)
        runs on the background snapshotter.  The by-reference capture is
        safe because jax.Arrays are immutable and the Manager builds a fresh
        state-dict tree per call; a caller passing mutable numpy leaves must
        not mutate them in place before the snapshot lands (wait_snapshot).
        """
        self.enqueue_snapshot(step, state_dict, serve=True)
        self.allow_checkpoint(step)

    def enqueue_snapshot(self, step: int, state_dict: Any, serve: bool = True) -> None:
        """Enqueues a snapshot for the background flatten pipeline.

        ``serve=True`` is the send_checkpoint path: the result flips the
        served ``(meta, buffers, step)`` slot.  ``serve=False`` runs the
        SAME pipeline — flatten + CRCs + the EC snapshot hook — but never
        touches the served slot, so the Manager can feed every committed
        step to the erasure encoder without racing a healer's in-flight
        fetch off its step (the serving flip stays quorum-paced).
        Drop-stale per kind: only the newest enqueue of each kind matters.
        """
        with self._snap_cond:
            self._snap_pending[serve] = (step, state_dict)
            if serve:
                self._pending_step = max(self._pending_step, step)
            self._snap_cond.notify_all()

    def allow_checkpoint(self, step: int) -> None:
        if self._checkpoint_lock.w_locked():
            self._checkpoint_lock.w_release()

    def disallow_checkpoint(self) -> None:
        if not self._checkpoint_lock.w_locked():
            if not self._checkpoint_lock.w_acquire(self._timeout):
                raise TimeoutError("timed out re-acquiring checkpoint write lock")

    def recv_checkpoint(
        self,
        src_rank: int,
        metadata: Union[str, Sequence[str]],
        step: int,
        timeout: float,
    ) -> Any:
        """Fetches the checkpoint from one or many donors.

        ``metadata`` may be a single donor base URL or an ordered donor
        list; with several donors the fetch is striped across all of them
        (disjoint byte ranges in parallel) and any stripe fails over to the
        next donor, so one donor dying mid-heal degrades bandwidth instead
        of aborting the heal.
        """
        donors = [metadata] if isinstance(metadata, str) else [m for m in metadata if m]
        if not donors:
            raise ValueError("recv_checkpoint: no donor metadata")
        try:
            forced = int(os.environ.get("TPUFT_HTTP_CHUNK_WORKERS") or 0)
        except ValueError:
            # A malformed tuning knob must not abort recovery itself.
            logger.warning("ignoring malformed TPUFT_HTTP_CHUNK_WORKERS")
            forced = 0

        n_stripes = 0
        if len(donors) == 1:
            base = f"{donors[0]}/checkpoint/{step}"
            n_chunks = pickle.loads(self._fetch(f"{base}/metadata", timeout))
            # Parallel chunk pulls only pay when there are cores to run
            # them: on a 1-core host the decode threads convoy on the GIL —
            # the RECEIVER decides, since the server serves /full regardless
            # of its chunking config.  TPUFT_HTTP_CHUNK_WORKERS overrides
            # the cpu-count heuristic (tests force the chunked path on
            # 1-core CI).
            workers = forced or min(n_chunks, os.cpu_count() or 1)
            if n_chunks <= 1 or workers < 2:
                # Deserialize straight off the socket: buffering the whole
                # multi-GB response into bytes first doubles peak memory and
                # adds a full copy.
                with self._urlopen(f"{base}/full", timeout) as resp:
                    meta, buffers = read_state_dict(resp)
                return unflatten_state_dict(meta, buffers, self._restore_sharding)
            n_stripes = n_chunks
        else:
            workers = forced or max(len(donors), min(2 * len(donors), os.cpu_count() or 1))

        meta, buffers = self._recv_striped(donors, step, n_stripes, workers, timeout)
        return unflatten_state_dict(meta, buffers, self._restore_sharding)

    # -- striped multi-donor receive ----------------------------------------

    def _recv_striped(
        self,
        donors: List[str],
        step: int,
        n_stripes: int,
        workers: int,
        timeout: float,
    ) -> Tuple[StateDictMeta, List[np.ndarray]]:
        dead: set = set()
        meta = self._fetch_header(donors, step, timeout, dead)
        n_tensors = len(meta.tensor_metas)
        if n_tensors == 0:
            return meta, []
        if n_stripes <= 0:
            # Over-stripe 2x the donor count: byte-greedy assignment can
            # then balance donors with heterogeneous tensor sizes, and a
            # dead donor's work splits across the survivors.
            n_stripes = min(n_tensors, max(1, 2 * len(donors)))
        n_stripes = min(n_stripes, n_tensors)
        sels, sizes = _stripe_partition(meta, n_stripes)
        assign = _assign_stripes_by_bytes(sizes, len(donors))

        # Preallocate every tensor's final buffer once; stripe bodies stream
        # straight into these (no whole-chunk bytes materialization, no
        # per-tensor slice copies — this halves peak RSS during heal).
        store = [bytearray(tm.nbytes) for tm in meta.tensor_metas]
        views = [memoryview(b) for b in store]

        def fetch_stripe(idx: int) -> None:
            self._fetch_stripe(
                donors, assign[idx], step, n_stripes, idx, sels[idx], meta, views,
                timeout, dead,
            )

        if workers >= 2 and n_stripes > 1:
            with ThreadPoolExecutor(max_workers=min(workers, n_stripes)) as pool:
                list(pool.map(fetch_stripe, range(n_stripes)))
        else:
            for idx in range(n_stripes):
                fetch_stripe(idx)

        buffers = [
            np.frombuffer(store[i], dtype=np.uint8).view(tm.dtype).reshape(tm.shape)
            for i, tm in enumerate(meta.tensor_metas)
        ]
        return meta, buffers

    def _fetch_header(
        self, donors: List[str], step: int, timeout: float, dead: set
    ) -> StateDictMeta:
        last: Optional[Exception] = None
        for d, donor in enumerate(donors):
            try:
                raw = self._fetch(f"{donor}/checkpoint/{step}/header", timeout)
            except Exception as e:  # noqa: BLE001 — failover to next donor
                dead.add(d)
                last = e
                logger.warning("header fetch from %s failed: %s", donor, e)
                continue
            stream = io.BytesIO(raw)
            header_len = int.from_bytes(stream.read(8), "little")
            return pickle.loads(stream.read(header_len))
        raise RuntimeError(f"all {len(donors)} donors failed serving the header: {last}")

    def _fetch_stripe(
        self,
        donors: List[str],
        assigned: int,
        step: int,
        n: int,
        idx: int,
        sel: List[int],
        meta: StateDictMeta,
        views: List[memoryview],
        timeout: float,
        dead: set,
    ) -> None:
        """Pulls stripe ``idx`` of ``n`` into the preallocated views, failing
        over from the assigned donor through the rest of the rotation."""
        order = [(assigned + k) % len(donors) for k in range(len(donors))]
        candidates = [d for d in order if d not in dead] or order
        last: Optional[Exception] = None
        crcs = getattr(meta, "crcs", None)
        crc_algo = getattr(meta, "crc_algo", None)
        # Single-donor chunked fetches omit the ?n= query: n already equals
        # the chunk count the server advertised on /metadata, and a pre-PR
        # donor's handler cannot parse a query string (rolling-upgrade
        # back-compat the wire doc promises).
        query = f"?n={n}" if len(donors) > 1 else ""
        for attempt, d in enumerate(candidates):
            url = f"{donors[d]}/checkpoint/{step}/chunk_{idx}{query}"
            try:
                with self._urlopen(url, timeout) as resp:
                    sub_len = int.from_bytes(read_exact(resp, 8), "little")
                    got_idx, got_sel = pickle.loads(bytes(read_exact(resp, sub_len)))
                    if got_idx != idx or list(got_sel) != list(sel):
                        raise RuntimeError(
                            f"stripe mismatch: asked ({idx},{n}), got {got_idx}"
                        )
                    for i in got_sel:
                        read_exact_into(resp, views[i])
                        if crcs is not None:
                            # Verify the buffer AS IT LANDS: a corrupt/torn
                            # stripe raises here and fails over to the next
                            # donor — the re-fetch simply overwrites the
                            # same preallocated view.
                            from torchft_tpu.checkpointing.integrity import verify

                            verify(
                                views[i], crcs[i], crc_algo,
                                f"stripe {idx}/{n} buffer {i} from {donors[d]}",
                            )
                return
            except Exception as e:  # noqa: BLE001 — stripe failover
                last = e
                dead.add(d)
                if attempt + 1 < len(candidates):
                    logger.warning(
                        "stripe %d/%d from %s failed (%s); failing over to %s",
                        idx, n, donors[d], e, donors[candidates[attempt + 1]],
                    )
        raise RuntimeError(
            f"stripe {idx}/{n} failed on all {len(candidates)} donors: {last}"
        )

    def _fetch(self, url: str, timeout: float) -> bytes:
        with self._urlopen(url, timeout) as resp:
            return resp.read()

    def _urlopen(self, url: str, timeout: float):
        """Single indirection for every receiver-side HTTP open (tests hook
        this to inject donor death deterministically)."""
        return urllib.request.urlopen(url, timeout=timeout)

    def shutdown(self, wait: bool = True) -> None:
        with self._snap_cond:
            self._shutdown = True
            self._snap_cond.notify_all()
        self._server.shutdown()
        self._server.server_close()
        if wait:
            self._thread.join(timeout=5)
            self._snap_thread.join(timeout=5)


def _stripe_partition(
    meta: StateDictMeta, n: int
) -> Tuple[List[List[int]], List[int]]:
    """Round-robin buffer-index stripes and their byte sizes — must mirror
    the server's ``sel`` arithmetic in ``_chunk_frame`` exactly."""
    sels: List[List[int]] = [[] for _ in range(n)]
    sizes = [0] * n
    for i, tm in enumerate(meta.tensor_metas):
        sels[i % n].append(i)
        sizes[i % n] += tm.nbytes
    return sels, sizes


def _assign_stripes_by_bytes(sizes: List[int], n_donors: int) -> List[int]:
    """Greedy byte-balanced stripe->donor assignment (largest stripes first
    onto the least-loaded donor), so heterogeneous tensor sizes don't leave
    one donor's link idle while another's saturates."""
    loads = [0] * n_donors
    assign = [0] * len(sizes)
    for idx in sorted(range(len(sizes)), key=lambda s: -sizes[s]):
        d = min(range(n_donors), key=lambda j: loads[j])
        assign[idx] = d
        loads[d] += sizes[idx]
    return assign


class _ServerPacer:
    """Virtual-time link shared by every connection of one transport: each
    write reserves `bytes / rate` seconds of the link and sleeps until its
    reservation ends, so N parallel stripe readers see ONE donor-NIC's
    bandwidth, not N connections' worth.  Benchmark-only (enabled by
    TPUFT_HTTP_SHAPED_MBPS at transport construction)."""

    def __init__(self, mbps: float) -> None:
        self._rate = mbps * 1e6
        self._lock = threading.Lock()
        self._next_free = 0.0

    @classmethod
    def from_env(cls) -> Optional["_ServerPacer"]:
        try:
            mbps = float(os.environ.get("TPUFT_HTTP_SHAPED_MBPS") or 0.0)
        except ValueError:
            mbps = 0.0
        return cls(mbps) if mbps > 0 else None

    def consume(self, n: int) -> None:
        now = time.monotonic()
        with self._lock:
            start = max(now, self._next_free)
            self._next_free = start + n / self._rate
            until = self._next_free
        if until > now:
            time.sleep(until - now)


class _PacedStream:
    """Write-through wrapper applying a shared _ServerPacer in ~4 MB slices
    (smooth pacing; a donor killed mid-fetch dies mid-stripe)."""

    _SLICE = 4 << 20

    def __init__(self, raw, pacer: _ServerPacer) -> None:
        self._raw = raw
        self._pacer = pacer

    def write(self, data) -> int:
        mv = memoryview(data)
        for off in range(0, len(mv), self._SLICE):
            part = mv[off : off + self._SLICE]
            # Reserve the link BEFORE writing: the actual socket write then
            # overlaps the next reservation instead of adding to it, so the
            # emulated link runs at its nominal rate.
            self._pacer.consume(len(part))
            self._raw.write(part)
        return len(mv)


def _paced(raw, pacer: Optional[_ServerPacer]):
    return raw if pacer is None else _PacedStream(raw, pacer)
