"""HTTP checkpoint transport: pull-based live weight recovery.

Reference parity: torchft/checkpointing/http_transport.py.  A threading HTTP
server on every replica streams the current-step state dict to recovering
peers; an RWLock gates serving so the train loop can mutate weights safely
(write-held while training, released while a checkpoint is being served);
the URL scheme is /checkpoint/<step>/{full|metadata|<chunk_i>} with optional
round-robin chunking fetched in parallel by the receiver.
"""

from __future__ import annotations

import io
import logging
import os
import pickle
import socket
import threading
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from torchft_tpu.checkpointing._rwlock import RWLock
from torchft_tpu.checkpointing.serialization import (
    StateDictMeta,
    as_u8,
    flatten_state_dict,
    read_state_dict,
    state_dict_frames,
    unflatten_state_dict,
    write_state_dict,
)
from torchft_tpu.checkpointing.transport import CheckpointTransport
from torchft_tpu.http import ThreadingHTTPServerV6

logger = logging.getLogger("torchft_tpu.checkpointing.http")


class HTTPTransport(CheckpointTransport):
    """Serves pickled+raw state-dict streams over HTTP.

    Args:
        timeout: per-request deadline.
        num_chunks: if > 0, the buffers are split round-robin into this many
            chunks which the receiver fetches in parallel
            (reference: torchft/checkpointing/http_transport.py:287-298).
        restore_sharding: optional spec -> jax.Sharding resolver used when
            rebuilding fetched arrays on device.
    """

    def __init__(
        self,
        timeout: float = 60.0,
        num_chunks: int = 0,
        restore_sharding: Optional[Callable[[Any], Any]] = None,
    ) -> None:
        self._timeout = timeout
        self._num_chunks = num_chunks
        self._restore_sharding = restore_sharding
        # Held while training mutates weights; released (allow_checkpoint)
        # while a consistent snapshot is being served.
        self._checkpoint_lock = RWLock(timeout=timeout)
        self._checkpoint_lock.w_acquire()
        self._state: Optional[Tuple[StateDictMeta, List[np.ndarray]]] = None
        self._step = -1

        transport = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt: str, *args: object) -> None:
                logger.debug(fmt % args)

            def do_GET(self) -> None:
                parts = self.path.strip("/").split("/")
                # /checkpoint/<step>/<what>
                if len(parts) != 3 or parts[0] != "checkpoint":
                    self.send_error(404, "unknown path")
                    return
                try:
                    step = int(parts[1])
                except ValueError:
                    self.send_error(400, "bad step")
                    return
                what = parts[2]
                try:
                    with transport._checkpoint_lock.r_lock(transport._timeout):
                        if transport._state is None or transport._step != step:
                            self.send_error(
                                404,
                                f"checkpoint for step {step} not available "
                                f"(serving {transport._step})",
                            )
                            return
                        meta, buffers = transport._state
                        if what == "full":
                            # Stream header + raw buffers straight to the
                            # socket: materializing a multi-GB BytesIO first
                            # is an extra full copy on the default healing
                            # path.  state_dict_frames is the writer's own
                            # framing, so Content-Length cannot drift from
                            # what read_state_dict expects.
                            prefix, total = state_dict_frames(meta, buffers)
                            self.send_response(200)
                            self.send_header(
                                "Content-Type", "application/octet-stream"
                            )
                            self.send_header("Content-Length", str(total))
                            self.end_headers()
                            write_state_dict(meta, buffers, self.wfile, prefix=prefix)
                            return
                        if what.startswith("chunk_"):
                            # Chunks stream too: building a ~GB chunk in a
                            # BytesIO first costs two full copies made while
                            # holding the GIL, which convoys the parallel
                            # chunk readers (measured 3x worse than
                            # sequential on a 1-core host).
                            framed = transport._chunk_frame(meta, buffers, what)
                            if framed is None:
                                self.send_error(404, f"unknown object {what}")
                                return
                            sub_prefix, sel, total = framed
                            self.send_response(200)
                            self.send_header(
                                "Content-Type", "application/octet-stream"
                            )
                            self.send_header("Content-Length", str(total))
                            self.end_headers()
                            self.wfile.write(sub_prefix)
                            for i in sel:
                                self.wfile.write(memoryview(as_u8(buffers[i])))
                            return
                        payload = transport._render(meta, buffers, what)
                        if payload is None:
                            self.send_error(404, f"unknown object {what}")
                            return
                        self.send_response(200)
                        self.send_header("Content-Type", "application/octet-stream")
                        self.send_header("Content-Length", str(len(payload)))
                        self.end_headers()
                        self.wfile.write(payload)
                except TimeoutError:
                    self.send_error(503, "checkpoint lock busy")

        self._server = ThreadingHTTPServerV6(("", 0), Handler)
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="tpuft_http_transport", daemon=True
        )
        self._thread.start()

    # -- serving ------------------------------------------------------------

    def _chunk_frame(
        self, meta: StateDictMeta, buffers: List[np.ndarray], what: str
    ) -> Optional[Tuple[bytes, List[int], int]]:
        """(sub_meta prefix, selected buffer indices, total body length) for
        one chunk_<i> request, or None for a bad index.  Round-robin
        assignment keeps chunk sizes balanced without reordering metadata
        (torchft/checkpointing/http_transport.py:287-298)."""
        try:
            idx = int(what[len("chunk_"):])
        except ValueError:
            return None  # malformed chunk index -> 404, not a 500 traceback
        n = self._chunk_count(buffers)
        if idx < 0 or idx >= n:
            return None
        sel = [i for i in range(len(buffers)) if i % n == idx]
        sub_meta = pickle.dumps((idx, sel))
        prefix = len(sub_meta).to_bytes(8, "little") + sub_meta
        total = len(prefix) + sum(buffers[i].nbytes for i in sel)
        return prefix, sel, total

    def _render(self, meta: StateDictMeta, buffers: List[np.ndarray], what: str) -> Optional[bytes]:
        out = io.BytesIO()
        if what == "header":
            # Just the length-prefixed pickled StateDictMeta — what a chunked
            # receiver needs to size its buffers, without making the server
            # materialize the full multi-GB stream.  Same framing source as
            # the /full path so the prefix format cannot drift.
            out.write(state_dict_frames(meta, [])[0])
        elif what == "metadata":
            out.write(pickle.dumps(self._chunk_count(buffers)))
        else:
            return None
        return out.getvalue()

    def _chunk_count(self, buffers: List[np.ndarray]) -> int:
        if self._num_chunks <= 0:
            return 1
        return max(1, min(self._num_chunks, len(buffers)))

    def metadata(self) -> str:
        return f"http://{socket.gethostname()}:{self._port}"

    # -- CheckpointTransport ------------------------------------------------

    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: Any, timeout: float
    ) -> None:
        """Pull-based: snapshot to host and open the serving window."""
        meta, buffers = flatten_state_dict(state_dict, step=step)
        self._state = (meta, buffers)
        self._step = step
        self.allow_checkpoint(step)

    def allow_checkpoint(self, step: int) -> None:
        if self._checkpoint_lock.w_locked():
            self._checkpoint_lock.w_release()

    def disallow_checkpoint(self) -> None:
        if not self._checkpoint_lock.w_locked():
            if not self._checkpoint_lock.w_acquire(self._timeout):
                raise TimeoutError("timed out re-acquiring checkpoint write lock")

    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: float
    ) -> Any:
        base = f"{metadata}/checkpoint/{step}"
        n_chunks = pickle.loads(_fetch(f"{base}/metadata", timeout))
        # Parallel chunk pulls only pay when there are cores to run them:
        # on a 1-core host the decode threads convoy on the GIL (measured
        # 3x slower than sequential, 10x slower than one stream at 3.75 GB)
        # — the RECEIVER decides, since the server serves /full regardless
        # of its chunking config.  TPUFT_HTTP_CHUNK_WORKERS overrides the
        # cpu-count heuristic (tests force the chunked path on 1-core CI).
        try:
            forced = int(os.environ.get("TPUFT_HTTP_CHUNK_WORKERS") or 0)
        except ValueError:
            # A malformed tuning knob must not abort recovery itself.
            logger.warning("ignoring malformed TPUFT_HTTP_CHUNK_WORKERS")
            forced = 0
        workers = forced or min(n_chunks, os.cpu_count() or 1)
        if n_chunks <= 1 or workers < 2:
            # Deserialize straight off the socket: buffering the whole
            # multi-GB response into bytes first doubles peak memory and
            # adds a full copy.
            with urllib.request.urlopen(f"{base}/full", timeout=timeout) as resp:
                meta, buffers = read_state_dict(resp)
        else:
            with ThreadPoolExecutor(max_workers=workers) as pool:
                parts = list(
                    pool.map(
                        lambda i: _fetch(f"{base}/chunk_{i}", timeout), range(n_chunks)
                    )
                )
            meta, buffers = self._assemble_chunks(base, parts, timeout)
        return unflatten_state_dict(meta, buffers, self._restore_sharding)

    def _assemble_chunks(
        self, base: str, parts: List[bytes], timeout: float
    ) -> Tuple[StateDictMeta, List[np.ndarray]]:
        meta_stream = io.BytesIO(_fetch(f"{base}/header", timeout))
        header_len = int.from_bytes(meta_stream.read(8), "little")
        meta: StateDictMeta = pickle.loads(meta_stream.read(header_len))
        buffers: List[Optional[np.ndarray]] = [None] * len(meta.tensor_metas)
        for part in parts:
            sub_len = int.from_bytes(part[:8], "little")
            idx, sel = pickle.loads(part[8 : 8 + sub_len])
            offset = 8 + sub_len
            for i in sel:
                tm = meta.tensor_metas[i]
                raw = part[offset : offset + tm.nbytes]
                offset += tm.nbytes
                buffers[i] = (
                    np.frombuffer(raw, dtype=np.uint8).view(tm.dtype).reshape(tm.shape)
                )
        assert all(b is not None for b in buffers), "missing chunks"
        return meta, buffers  # type: ignore[return-value]

    def shutdown(self, wait: bool = True) -> None:
        self._server.shutdown()
        self._server.server_close()
        if wait:
            self._thread.join(timeout=5)


def _fetch(url: str, timeout: float) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()
