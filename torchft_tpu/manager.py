"""Fault-tolerance Manager: the per-replica-group training-loop state machine.

Reference parity: torchft/manager.py.  The Manager owns everything the train
loop needs for per-step fault tolerance:

  - async quorum: each step starts a quorum computation on a background
    thread that overlaps with the forward/backward pass
    (torchft/manager.py:385-438);
  - reconfiguration: when the quorum id changes, the cross-group collective
    is rebuilt against a fresh store prefix (torchft/manager.py:502-509);
  - healing: behind replicas stream weights from a healthy peer through a
    CheckpointTransport while the healthy groups keep training
    (torchft/manager.py:511-568);
  - error latching: collective failures never raise into the train loop;
    they mark the step failed and are resolved at commit time
    (torchft/manager.py:262-383);
  - commit protocol: an optimizer step lands only when every local rank of
    the group voted success (torchft/manager.py:587-663).

TPU adaptations: the unit of data is a pytree leaf (jax.Array / numpy array)
rather than a torch tensor; cross-group traffic runs on a host-level
Collective over the DCN path (see torchft_tpu/collectives.py) because XLA
programs cannot change their collective world at runtime; the reference's
dedicated CUDA recovery stream maps to performing transfers on the quorum
thread while JAX async dispatch keeps device compute running.
"""

from __future__ import annotations

import json
import logging
import os
import re
import socket
import threading
import time
import uuid
from concurrent.futures import Future, ThreadPoolExecutor
from datetime import timedelta
from enum import Enum
from typing import Any, Callable, Dict, List, Optional, TypeVar, cast

import numpy as np

from torchft_tpu._native import ManagerClient, ManagerServer, StoreClient, StoreServer
from torchft_tpu.checkpointing.transport import CheckpointTransport
from torchft_tpu.collectives import Collective
from torchft_tpu.futures import completed_future, future_timeout

T = TypeVar("T")

MANAGER_ADDR_KEY: str = "manager_addr"
REPLICA_ID_KEY: str = "replica_id"

# Environment knobs (reference: torchft/manager.py:50,166-205).
TPUFT_LIGHTHOUSE_ENV: str = "TPUFT_LIGHTHOUSE"
TPUFT_MANAGER_PORT_ENV: str = "TPUFT_MANAGER_PORT"
# Cap on how many donors one healer stripes a fetch across.  More donors =
# more aggregate bandwidth (each serves a disjoint byte range) but also more
# connections per heal; 4 saturates typical host NICs long before the donor
# pool does.  0 = no cap.
TPUFT_MAX_HEAL_DONORS_ENV: str = "TPUFT_MAX_HEAL_DONORS"
# Heal-retry pacing (docs/api.md): after a FAILED heal fetch the next
# quorum's retry waits a decorrelated-jitter backoff (ha/backoff.py) so a
# flapping donor — or a donor whose serving window is briefly busy — cannot
# turn every quorum round into a zero-delay heal storm.  base/cap seconds;
# reset on the first successful fetch.
TPUFT_HEAL_BACKOFF_BASE_ENV: str = "TPUFT_HEAL_BACKOFF_BASE_S"
TPUFT_HEAL_BACKOFF_CAP_ENV: str = "TPUFT_HEAL_BACKOFF_CAP_S"


class WorldSizeMode(Enum):
    """How the effective batch/world size behaves as replica groups come and
    go (reference: WorldSizeMode, torchft/manager.py:56-71)."""

    DYNAMIC = 0
    FIXED_WITH_SPARES = 1


class ExceededMaxRetriesError(RuntimeError):
    """Raised by should_commit after max_retries consecutive failed commits
    (reference: torchft/manager.py:652-661)."""


class Manager:
    """Fault tolerance manager for one local rank of one replica group."""

    def __init__(
        self,
        collective: Collective,
        load_state_dict: Optional[Callable[[T], None]],
        state_dict: Optional[Callable[[], T]],
        min_replica_size: int,
        use_async_quorum: bool = True,
        timeout: timedelta = timedelta(seconds=60),
        quorum_timeout: timedelta = timedelta(seconds=60),
        connect_timeout: timedelta = timedelta(seconds=10),
        rank: Optional[int] = None,
        world_size: Optional[int] = None,
        world_size_mode: WorldSizeMode = WorldSizeMode.DYNAMIC,
        fixed_world_size: Optional[int] = None,
        store_addr: Optional[str] = None,
        store_port: Optional[int] = None,
        external_store_addr: Optional[str] = None,
        lighthouse_addr: Optional[str] = None,
        replica_id: Optional[str] = None,
        manager_bind: Optional[str] = None,
        heartbeat_interval: timedelta = timedelta(milliseconds=100),
        checkpoint_transport: Optional[CheckpointTransport] = None,
        init_sync: bool = True,
        max_retries: Optional[int] = None,
    ) -> None:
        """
        Args:
            collective: reconfigurable cross-group collective (data plane).
            load_state_dict: applies a user state dict fetched from a peer.
            state_dict: captures the user state dict to serve to peers.
            min_replica_size: minimum replica groups for a committable step.
            use_async_quorum: overlap quorum with forward/backward.
            rank/world_size: local rank / ranks per group (env: RANK,
                WORLD_SIZE).
            store_addr/store_port: host + port for the group's rendezvous
                store, created by local rank 0 (env: MASTER_ADDR/MASTER_PORT).
            external_store_addr: use an existing store (tests / shared infra).
            lighthouse_addr: lighthouse RPC address (env: TPUFT_LIGHTHOUSE).
                A comma-separated list fails over across an HA replica
                set.  Under a federated control plane this names the
                REGION's child lighthouse(s) — byte-for-byte the same
                config as a flat deployment; managers never learn the
                root exists (docs/wire.md "Federation").
            replica_id: stable replica-group id; a ":uuid" suffix is added so
                fast restarts look like new members (torchft/manager.py:230-238).
            init_sync: sync weights from replica 0 at step 0.
            max_retries: consecutive failed commits before giving up.
        """
        self._load_state_dict_fns: Dict[str, Callable] = {}
        self._user_state_dicts: Dict[str, Callable] = {}
        if load_state_dict is not None:
            self._load_state_dict_fns["default"] = load_state_dict
        if state_dict is not None:
            self._user_state_dicts["default"] = state_dict

        self._collective = collective
        self._min_replica_size = min_replica_size
        self._use_async_quorum = use_async_quorum
        self._timeout = timeout
        self._quorum_timeout = quorum_timeout
        self._connect_timeout = connect_timeout
        self._world_size_mode = world_size_mode
        self._init_sync = init_sync
        self._max_retries = max_retries
        self._commit_failures = 0

        self._rank: int = rank if rank is not None else int(os.environ.get("RANK", 0))
        group_world_size = world_size if world_size is not None else int(
            os.environ.get("WORLD_SIZE", 1)
        )
        self._group_world_size: int = group_world_size
        self._fixed_world_size = fixed_world_size

        lighthouse_addr = lighthouse_addr or os.environ.get(TPUFT_LIGHTHOUSE_ENV, "")
        # May be a comma-separated HA replica set ("host1:p,host2:p", see
        # docs/wire.md "HA lighthouse"): the native ManagerServer fails its
        # quorum/heartbeat calls over across the list and follows "not the
        # leader" redirects, and every Python-side dial below goes through
        # the failover-aware LighthouseClient.  Kept for the
        # cooperative-drain notice (begin_drain dials the lighthouse
        # directly with this group's exact incarnation id).
        self._lighthouse_addr = lighthouse_addr

        self._store_server: Optional[StoreServer] = None
        self._manager_server: Optional[ManagerServer] = None

        if external_store_addr is not None:
            store_address = external_store_addr
            self._store = StoreClient(store_address)
        else:
            store_host = store_addr or os.environ.get("MASTER_ADDR", "localhost")
            port = store_port if store_port is not None else int(
                os.environ.get("MASTER_PORT", 0)
            )
            if self._rank == 0:
                self._store_server = StoreServer(bind=f"[::]:{port}")
                actual_port = self._store_server.address().rsplit(":", 1)[1]
                store_address = f"{store_host}:{actual_port}"
            else:
                if port == 0:
                    raise ValueError(
                        "non-zero store_port (or MASTER_PORT) required for rank > 0"
                    )
                store_address = f"{store_host}:{port}"
            self._store = StoreClient(
                store_address, connect_timeout_ms=int(connect_timeout.total_seconds() * 1000)
            )
        self._store_address = store_address

        if self._rank == 0:
            if replica_id is None:
                replica_id = os.environ.get("REPLICA_GROUP_ID", socket.gethostname())
            # Suffix survives fast restarts: a restarted group must look like
            # a brand-new member to the lighthouse (torchft/manager.py:230-238).
            new_uuid = str(uuid.uuid4())
            replica_id = f"{replica_id}:{new_uuid}" if replica_id else new_uuid
            bind = manager_bind or "[::]:" + os.environ.get(TPUFT_MANAGER_PORT_ENV, "0")
            if not lighthouse_addr:
                raise ValueError(
                    f"lighthouse_addr or ${TPUFT_LIGHTHOUSE_ENV} must be set"
                )
            self._manager_server = ManagerServer(
                replica_id=replica_id,
                lighthouse_addr=lighthouse_addr,
                bind=bind,
                store_addr=store_address,
                world_size=group_world_size,
                heartbeat_interval_ms=int(heartbeat_interval.total_seconds() * 1000),
                connect_timeout_ms=int(connect_timeout.total_seconds() * 1000),
            )
            self._store.set(MANAGER_ADDR_KEY, self._manager_server.address().encode())
            self._store.set(REPLICA_ID_KEY, replica_id.encode())

        addr = self._store.get(
            MANAGER_ADDR_KEY, wait=True,
            timeout_ms=int(connect_timeout.total_seconds() * 1000),
        )
        assert addr is not None
        # Captured so the healing path dials peer managers through the same
        # (mockable) factory.
        self._manager_client_factory = ManagerClient
        self._client = self._manager_client_factory(
            addr.decode(), connect_timeout_ms=int(connect_timeout.total_seconds() * 1000)
        )
        rid = self._store.get(REPLICA_ID_KEY, wait=True)
        assert rid is not None
        self._replica_id = rid.decode()

        self._checkpoint_transport = checkpoint_transport

        self._step = 0
        self._quorum_id = -1
        self._batches_committed = 0
        self._healing = False
        self._errored: Optional[Exception] = None
        self._pending_work: List[Future] = []
        self._pending_state_dict: Optional[Dict[str, object]] = None
        self._quorum_future: Optional[Future] = None
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tpuft_quorum"
        )

        self._participating_replica_rank: Optional[int] = None
        self._participating_replica_world_size: int = 0

        # Causal trace id of the step in flight (docs/wire.md "Causal trace
        # ids"): minted once per quorum round and carried on every control
        # RPC — Quorum (via the native ManagerServer to the lighthouse),
        # CheckpointMetadata, ShouldCommit, Drain — so the server-side
        # flight recorders can be joined to this replica's span stream.
        self._trace_id: str = ""

        # Cooperative-drain state (torchft_tpu/drain): set once by
        # begin_drain, observed by the train loop between steps.
        self._drain_notice = None
        self._drain_watcher = None
        self._drain_lock = threading.Lock()

        self._logger = _ManagerLogger(self, self._replica_id, self._rank)
        # JSONL event stream when TPUFT_METRICS_PATH is set (no-op otherwise).
        from torchft_tpu.metrics import MetricsLogger
        from torchft_tpu.obs.spans import SpanTracker, StepTimeStats

        self._metrics = MetricsLogger.from_env(self._replica_id)
        # Step-scoped trace spans over the same stream (obs/spans.py): each
        # phase below runs inside a span, and the span's single monotonic
        # measurement also feeds the legacy *_ms fields.
        self._spans = SpanTracker(self._metrics)
        # Goodput ledger (obs/ledger.py): every committed step's wall time
        # classified into the pinned cause taxonomy at the commit vote —
        # the per-step vector rides step_summary, the cumulative counters
        # ride heartbeat fields 14-16 into the lighthouse's cluster ledger
        # (/goodput.json, tpuft_goodput_ratio, tpuft_lost_seconds_total).
        from torchft_tpu.obs.ledger import StepLedger

        self._ledger = StepLedger()
        # The ledger's own commit clock + failed-attempt phase buffer: a
        # failed vote resets the STEP-TIME clock (_last_commit_mono —
        # a retry-spanning interval would misread as slowness) but the
        # ledger must still charge the retried interval, so it keeps its
        # own last-commit mark and accumulates the failed attempts'
        # phases until the step finally commits (the documented rule in
        # obs/ledger.py: the retries' charges land in the eventual
        # committed interval).
        self._ledger_prev_commit_mono: Optional[float] = None
        self._ledger_pending_phases: Dict[str, float] = {}
        # Straggler-sentinel telemetry: rolling busy-time per committed step
        # (EWMA + p50/p99), pushed onto lighthouse heartbeats via SetStatus
        # so the cluster-level health scoring sees this replica's pace.
        self._step_stats = StepTimeStats()
        self._last_commit_mono: Optional[float] = None
        # Allreduce data-plane throughput: payload bytes and the first-issue
        # timestamp of the step in flight, summarized at commit time as
        # allreduce_gb_per_s (step_summary field + the lighthouse's
        # tpuft_allreduce_gb_per_s heartbeat gauge).  End-to-end rate — from
        # first issue to drain — so overlap wins (bucket pipelining, ring
        # lanes) show up here, not just in microbenchmarks.
        self._ar_lock = threading.Lock()
        self._ar_bytes = 0
        self._ar_t_first: Optional[float] = None
        self._ar_t_last: Optional[float] = None
        self._ar_gbps = 0.0
        # Device<->host transfer bytes for the step in flight, noted by the
        # data-plane layers above (GradientAverager's note_d2h/note_h2d)
        # and flushed into step_summary — with device wire prep the D2H
        # side should read ~wire bytes (half of f32), and the H2D side
        # shows the scatter-back cost the allreduce_h2d span charges.
        self._d2h_bytes = 0
        self._h2d_bytes = 0
        # Lifetime (monotonic) transfer totals for the worker /metrics
        # endpoint — the per-step fields above reset at start_quorum.
        self._d2h_bytes_total = 0
        self._h2d_bytes_total = 0
        # Per-neighbor link health (docs/architecture.md "Data-plane
        # observability"): EWMA goodput + hop RTT derived at each commit
        # from the ring engines' hop-telemetry deltas, pushed on heartbeat
        # fields 11-13 for the lighthouse's slow-link sentinel.  The
        # previous cumulative snapshot closes each step's delta window;
        # reset-aware (lane counters zero per configure()).
        self._link_prev: Optional[Dict[str, float]] = None
        self._link_ewma: Dict[str, float] = {}
        # Extra fields wrappers note onto the step in flight's step_summary
        # (note_summary_fields) — the semisync engine's per-round fragment
        # counts and wire bytes ride here.  Cleared with the other per-step
        # accounting at start_quorum and flushed at the commit vote.
        self._summary_extra: Dict[str, object] = {}

        # Elastic batch engine (docs/architecture.md "Elastic scale"): when
        # TPUFT_ELASTIC_GLOBAL_BATCH is set, every quorum transition rescales
        # this group's batch share so the GLOBAL batch stays constant across
        # membership churn — join/leave changes throughput, never the
        # training trajectory's effective batch.  The plan for the current
        # participant count is exposed via elastic_plan() (train loops read
        # group_batch/accum_steps from it) and stamped into every committed
        # step record.  Membership callbacks fire on the quorum thread after
        # the collective reconfigures, before the step proceeds — data
        # loaders re-shard there.  Lazy import: ddp imports Manager at
        # module level, so the reverse import must happen at runtime.
        self._elastic = None
        self._elastic_plan: Optional[Dict[str, object]] = None
        try:
            from torchft_tpu.ddp import ElasticBatchScaler

            self._elastic = ElasticBatchScaler.from_env()
        except Exception:  # noqa: BLE001 — elastic must not break startup
            self._elastic = None
        self._membership_callbacks: List[Callable[[Dict[str, object]], None]] = []
        self._last_participants: Optional[List[int]] = None

        # Erasure-coded peer state (torchft_tpu/ec, docs/architecture.md
        # "Donor-free healing"): when TPUFT_EC_K > 0 and the checkpoint
        # transport can host a shard store, every committed step's state is
        # additionally encoded into k+m Reed-Solomon shards on the
        # transport's background snapshotter, and a heal whose donors are
        # unreachable reconstructs from any k surviving shard holders.
        self._ec = None
        from torchft_tpu.ec import ECConfig

        ec_cfg = ECConfig.from_env()
        if (
            ec_cfg.enabled
            and self._checkpoint_transport is not None
            and hasattr(self._checkpoint_transport, "attach_shard_store")
        ):
            from torchft_tpu.ec import ECPlane

            self._ec = ECPlane(
                ec_cfg,
                spans=self._spans,
                metrics=self._metrics,
                resolve_peer=self._dial_peer_transport,
                push_timeout=self._timeout.total_seconds(),
            )

        # Heal-retry pacing: decorrelated jitter between consecutive heal
        # attempts after a failure (satellite of the EC work; see the env
        # docs above).  _heal_failures counts consecutive failed fetches.
        from torchft_tpu.ha.backoff import DecorrelatedBackoff

        heal_base_s = _env_float(TPUFT_HEAL_BACKOFF_BASE_ENV, 0.2)
        if heal_base_s <= 0:
            # Same loud-but-safe policy as _env_float: a bad tuning value
            # must never abort recovery (DecorrelatedBackoff rejects <= 0).
            self._logger.warn(
                f"ignoring non-positive {TPUFT_HEAL_BACKOFF_BASE_ENV}="
                f"{heal_base_s}; using default 0.2"
            )
            heal_base_s = 0.2
        self._heal_backoff = DecorrelatedBackoff(
            base_s=heal_base_s,
            cap_s=_env_float(TPUFT_HEAL_BACKOFF_CAP_ENV, 5.0),
        )
        self._heal_failures = 0
        self._ec_enqueued_step = -1

        # Unified worker /metrics endpoint (obs/prom.py): step pace,
        # transfer totals, monotonic lane/hop counters (lane_totals), the
        # link-health EWMAs, plus any subsystem sections (the semisync
        # plane registers its tpuft_semisync_* render here).  Pull-based:
        # the provider snapshot runs at SCRAPE time, so training pays
        # nothing while nobody scrapes.  serve() is a no-op unless
        # TPUFT_WORKER_METRICS_PORT (or the deprecated
        # TPUFT_SEMISYNC_METRICS_PORT alias) is set.
        from torchft_tpu.obs.prom import WorkerMetrics

        self._worker_metrics = WorkerMetrics(
            replica_id=self._replica_id,
            provider=self._worker_metrics_snapshot,
        )
        # Per-hop wire-byte + latency histograms, folded at SCRAPE time
        # from the ring engines' retained hop timeline — no new recording
        # cost on the data path (docs/wire.md "Worker /metrics").  The
        # cumulative buckets live here (scrape-thread-only state) so the
        # exposed histograms stay monotonic over the sliding ring.
        self._hop_hist: Dict[tuple, dict] = {}  # (tier, lane) -> buckets
        self._hop_hist_last_ts = 0.0
        self._hop_hist_lock = threading.Lock()
        self._worker_metrics.add_section(self._render_hop_histograms)
        self._worker_metrics.serve()

        self._wire_transport_spans()

    def _wire_transport_spans(self) -> None:
        """Hands the span tracker to transports that emit their own spans —
        the HTTP transport's background snapshotter emits ``snapshot`` spans
        so obs.report can show the flatten overlapping the train step — and
        wires the EC plane's shard store + encode hook onto the transport."""
        transport = self._checkpoint_transport
        if transport is not None and hasattr(transport, "set_span_tracker"):
            transport.set_span_tracker(self._spans)
        if (
            self._ec is not None
            and transport is not None
            and hasattr(transport, "attach_shard_store")
        ):
            transport.attach_shard_store(self._ec.store)
            transport.set_snapshot_hook(self._ec.on_snapshot)

    def _dial_peer_transport(self, manager_addr: str) -> str:
        """Resolves a peer manager's checkpoint-transport base URL for this
        local rank (the shard endpoints live on the same server).  Used by
        the EC plane — cached there per address."""
        client = self._manager_client_factory(
            manager_addr,
            connect_timeout_ms=int(self._connect_timeout.total_seconds() * 1000),
        )
        try:
            return client._checkpoint_metadata(
                self._rank,
                timeout_ms=int(self._timeout.total_seconds() * 1000),
                trace_id=self._trace_id,
            )
        finally:
            client.close()

    # -- registration -------------------------------------------------------

    def register_state_dict_fn(
        self, key: str, load: Callable[[object], None], save: Callable[[], object]
    ) -> None:
        """Registers an additional named state-dict provider (wrappers like
        LocalSGD/DiLoCo register theirs here)."""
        self._load_state_dict_fns[key] = load
        self._user_state_dicts[key] = save

    def set_checkpoint_transport(self, transport: CheckpointTransport) -> None:
        self._checkpoint_transport = transport
        self._wire_transport_spans()

    # -- quorum -------------------------------------------------------------

    def start_quorum(
        self,
        allow_heal: bool = True,
        shrink_only: bool = False,
        timeout: Optional[timedelta] = None,
    ) -> None:
        """Starts the next-step quorum computation, possibly async.

        Must be called at the top of every step (the optimizer wrapper does
        it from zero_grad).  Reference: torchft/manager.py:385-438.
        """
        # Wait for the previous quorum to finish so state isn't mutated
        # concurrently (torchft/manager.py:411-412).
        if self._quorum_future is not None:
            self._quorum_future.result()

        self._errored = None
        self._healing = False
        self._pending_work = []
        with self._ar_lock:
            # Defensive: a loop that skipped should_commit must not bleed
            # its bytes into the next step's throughput summary.
            self._ar_bytes = 0
            self._ar_t_first = None
            self._ar_t_last = None
            self._d2h_bytes = 0
            self._h2d_bytes = 0
            self._summary_extra = {}

        # Feed the erasure encoder: at the top of a step the user state IS
        # the last committed step's state (a failed vote discarded its
        # speculative update), so enqueue it for the background snapshotter
        # as a NON-serving snapshot — the flatten + k+m encode + parity
        # push all run off the train thread (the enqueue itself is ~µs),
        # and the serving slot stays quorum-paced.  Deduped per step so
        # failed-commit retries don't re-flatten identical state.
        if (
            self._ec is not None
            and self._step != self._ec_enqueued_step
            and self._ec.wants_snapshot(self._step)
            and hasattr(self._checkpoint_transport, "enqueue_snapshot")
        ):
            self._checkpoint_transport.enqueue_snapshot(
                self._step, self._manager_state_dict(), serve=False
            )
            self._ec_enqueued_step = self._step

        self._quorum_future = self._executor.submit(
            self._async_quorum,
            allow_heal=allow_heal,
            shrink_only=shrink_only,
            quorum_timeout=timeout or self._quorum_timeout,
        )
        if not self._use_async_quorum:
            self.wait_quorum()
            if self._healing:
                # Sync mode applies the fetched state dict eagerly and is
                # then fully healed: the step runs with good weights, so the
                # commit path must not try to re-apply
                # (torchft/manager.py:429-438).
                self._apply_pending_state_dict()
                self._healing = False

    def wait_quorum(self) -> None:
        """Blocks until the current quorum completes (torchft/manager.py:440-449)."""
        assert self._quorum_future is not None, "call start_quorum before wait_quorum"
        self._quorum_future.result()

    def _async_quorum(
        self, allow_heal: bool, shrink_only: bool, quorum_timeout: timedelta
    ) -> None:
        try:
            self._quorum_inner(allow_heal, shrink_only, quorum_timeout)
        except Exception as e:  # noqa: BLE001
            if "is draining" in str(e):
                # The LIGHTHOUSE marked this incarnation draining (operator
                # /replica/<id>/drain, or the straggler sentinel's
                # auto-drain) and refuses its joins.  That is a drain
                # notice delivered through the quorum path: begin the
                # cooperative exit so the train loop finishes this step and
                # leaves cleanly instead of flailing through failed commits
                # until something kills it.  "is draining" is the grep
                # contract with both HandleQuorum message sites in
                # native/src/lighthouse.cc (the framed-TCP wire carries
                # status + message only, no structured error payload);
                # pinned by tests/test_straggler.py.
                from torchft_tpu.drain import DrainNotice

                # The rejection may carry the announced grace remainder as
                # a "(deadline_ms=N)" suffix (root-issued drains plumb the
                # operator's deadline down through the digest response);
                # pace the cooperative exit to it instead of a fixed 30 s.
                m = re.search(r"deadline_ms=(\d+)", str(e))
                grace_s = int(m.group(1)) / 1000.0 if m else 30.0
                self._logger.warn(
                    "lighthouse declared this replica draining; beginning "
                    f"cooperative exit (grace {grace_s:.1f}s)"
                )
                self.begin_drain(
                    DrainNotice(source="lighthouse", deadline=time.time() + grace_s)
                )
            else:
                self._logger.exception(f"quorum failed: {e}")
            self.report_error(e)
            # Not participating this step.
            self._participating_replica_rank = None
            self._participating_replica_world_size = 0

    def _quorum_inner(
        self, allow_heal: bool, shrink_only: bool, quorum_timeout: timedelta
    ) -> None:
        metadata = (
            self._checkpoint_transport.metadata() if self._checkpoint_transport else ""
        )
        self._set_status("quorum")
        # Mint this step's causal trace id; the span record carries it so
        # obs/report.py can join the client-observed quorum wait against
        # the lighthouse flight recorder's server-side formation span.
        from torchft_tpu.obs.flight import mint_trace_id

        trace_id = mint_trace_id(
            self._spans.slice_gen, self._replica_id, self._step
        )
        self._trace_id = trace_id
        with self._spans.span(
            "quorum", step=self._step, trace_id=trace_id
        ) as sp_quorum:
            quorum = self._client._quorum(
                group_rank=self._rank,
                step=self._step,
                checkpoint_metadata=metadata,
                shrink_only=shrink_only,
                timeout_ms=int(quorum_timeout.total_seconds() * 1000),
                init_sync=self._init_sync,
                commit_failures=self._commit_failures,
                trace_id=trace_id,
            )

        quorum_id = quorum.quorum_id
        replica_rank = quorum.replica_rank
        replica_world_size = quorum.replica_world_size
        recover_src_replica_rank = quorum.recover_src_replica_rank
        store_address = quorum.store_address
        max_step = quorum.max_step
        heal = quorum.heal

        if self._ec is not None:
            # Refresh the EC plane's placement membership from the full
            # participant list (fields 15-16).  Empty against a pre-EC
            # server — the plane then keeps its previous view, which still
            # probes correctly (placement is a hint; reconstruction sweeps
            # holder inventories regardless).
            p_ranks = list(getattr(quorum, "participant_replica_ranks", []) or [])
            p_addrs = list(
                getattr(quorum, "participant_manager_addresses", []) or []
            )
            if p_ranks and len(p_ranks) == len(p_addrs):
                self._ec.set_peers(p_ranks, p_addrs, replica_rank)

        # Participation bookkeeping (torchft/manager.py:480-500): with async
        # quorum (or healing disabled) only the up-to-date groups participate
        # this step — a healing group's max_replica_rank is None; with sync
        # quorum every group is healthy by the time the step runs.
        if self._use_async_quorum or not allow_heal:
            self._participating_replica_rank = quorum.max_replica_rank
            self._participating_replica_world_size = quorum.max_world_size
        else:
            self._participating_replica_rank = replica_rank
            self._participating_replica_world_size = replica_world_size

        # FIXED_WITH_SPARES pins the divisor; extra live groups are spares
        # contributing zeros.
        if self._world_size_mode == WorldSizeMode.FIXED_WITH_SPARES:
            fixed = self._fixed_world_size or self._min_replica_size
            self._participating_replica_world_size = min(
                self._participating_replica_world_size, fixed
            )
            if (
                self._participating_replica_rank is not None
                and self._participating_replica_rank >= fixed
            ):
                self._participating_replica_rank = None

        self._metrics.emit(
            "quorum",
            step=self._step,
            quorum_id=quorum_id,
            replica_rank=replica_rank,
            replica_world_size=replica_world_size,
            participating=self._participating_replica_world_size,
            heal=heal,
            # Same measurement the span record carries: where a slow step
            # went (quorum wait vs reconfigure vs heal) without a profiler.
            quorum_ms=sp_quorum.duration_ms,
        )

        if quorum_id != self._quorum_id:
            # Unique store prefix per (quorum, local rank): local rank r of
            # every group forms one ring (torchft/manager.py:502-509).
            prefix = f"tpuft/{quorum_id}/{self._rank}"
            self._logger.info(
                f"reconfiguring collective for quorum {quorum_id} "
                f"(rank {replica_rank}/{replica_world_size})"
            )
            with self._spans.span("configure", step=self._step) as sp_cfg:
                self._collective.configure(
                    f"{store_address}/{prefix}", replica_rank, replica_world_size
                )
            self._quorum_id = quorum_id
            # The collective records how the configure went (full rendezvous
            # vs incremental lane reuse).  The wrappers don't proxy unknown
            # attributes, so read defensively.
            lc = getattr(self._collective, "last_configure", None) or {}
            self._metrics.emit(
                "reconfigure",
                step=self._step,
                quorum_id=quorum_id,
                replica_rank=replica_rank,
                replica_world_size=replica_world_size,
                configure_ms=sp_cfg.duration_ms,
                mode=lc.get("mode", "unknown"),
                reused_lanes=lc.get("reused_lanes", 0),
                opened_lanes=lc.get("opened_lanes", 0),
            )
            self._on_membership_change(
                quorum, quorum_id, replica_world_size, sp_cfg.duration_ms, lc
            )

        if allow_heal and self._checkpoint_transport is not None:
            # Recovery source: serve our weights (torchft/manager.py:511-528).
            # Pull-based transports serve the FULL recovering set (striped
            # multi-donor fetch pulls disjoint byte ranges from every donor);
            # point-to-point transports serve only primary assignments —
            # their sends block until the healer's matching recv.
            if self._checkpoint_transport.serves_all_donors:
                serve_dsts = list(
                    getattr(quorum, "recover_dst_replica_ranks_all", None)
                    or quorum.recover_dst_replica_ranks
                )
                # Force-recover symmetry: when we heal WHILE already holding
                # the max_step state (commit_failures re-fetch, step ==
                # max_step), our peers may be in exactly the same position —
                # a cluster-wide failed step (e.g. a replica killed
                # mid-allreduce fails EVERY group's commit) force-recovers
                # everyone, and each group's assigned donor is another
                # force-recovering group.  commit_failures is request-local,
                # so donors cannot be told to serve us; without this, nobody
                # opens a serving window and the mutual heal deadlocks until
                # timeout, every quorum, forever.  Serving is passive for
                # pull transports and our state IS the committed max_step
                # state (a failed vote discards the speculative update), so
                # opening the window is always safe.
                if not serve_dsts and heal and max_step == self._step:
                    # Our own donor rotation names the peers most likely
                    # healing from us (HTTP serving ignores the dst list —
                    # it is passive — this only makes the log truthful).
                    serve_dsts = list(quorum.recover_src_replica_ranks) or [
                        quorum.recover_src_replica_rank
                    ]
            else:
                serve_dsts = list(quorum.recover_dst_replica_ranks)
            if serve_dsts:
                self._logger.info(
                    f"serving checkpoint at step {max_step} to replicas "
                    f"{serve_dsts}"
                )
                self._checkpoint_transport.send_checkpoint(
                    dst_ranks=serve_dsts,
                    step=max_step,
                    state_dict=self._manager_state_dict(),
                    timeout=self._timeout.total_seconds(),
                )
            # Recovery destination: fetch weights from the assigned donors —
            # striped across every healthy max-step group the quorum listed,
            # so heal bandwidth scales with the donor count and one donor
            # dying mid-heal degrades instead of aborting
            # (torchft/manager.py:530-568 is the single-donor ancestor).
            if heal:
                self._healing = True
                src_rank = cast(int, recover_src_replica_rank)
                donor_ranks = list(quorum.recover_src_replica_ranks) or [src_rank]
                donor_addrs = [
                    a
                    for a in (
                        list(quorum.recover_src_manager_addresses)
                        or [quorum.recover_src_manager_address]
                    )
                    if a
                ]
                max_donors = _max_heal_donors()
                if max_donors > 0:
                    donor_ranks = donor_ranks[:max_donors]
                    donor_addrs = donor_addrs[:max_donors]
                if not self._checkpoint_transport.serves_all_donors:
                    # Point-to-point transports: only the PRIMARY donor is
                    # sending to us — failing over to another donor would
                    # recv from a peer with no matching send (hang, then
                    # timeout) instead of failing fast and re-planning on
                    # the next quorum.
                    donor_ranks = donor_ranks[:1]
                    donor_addrs = donor_addrs[:1]
                if self._heal_failures > 0:
                    # Heal-retry backoff: consecutive failed fetches pace
                    # their retries with decorrelated jitter so a flapping
                    # donor cannot make every quorum round a heal storm.
                    delay = self._heal_backoff.next()
                    self._logger.warn(
                        f"heal retry #{self._heal_failures}: backing off "
                        f"{delay:.2f}s before re-fetching"
                    )
                    time.sleep(delay)
                self._set_status("heal")
                prefer_ec = self._ec is not None and self._ec.config.mode == "prefer"
                state: Optional[Dict[str, object]] = None
                fetch_err: Optional[Exception] = None
                if not prefer_ec and donor_addrs:
                    state, fetch_err = self._heal_from_donors(
                        src_rank, max_step, donor_ranks, donor_addrs
                    )
                elif not donor_addrs:
                    fetch_err = RuntimeError(
                        "quorum response names no reachable donor"
                    )
                if state is None and self._ec is not None:
                    # Donor-free fallback (or "prefer" mode's first choice):
                    # reconstruct the max-step state from any k surviving
                    # shard holders — no serving window, no donor rotation.
                    state = self._heal_from_shards(max_step, fetch_err)
                if state is None and prefer_ec and donor_addrs:
                    # prefer mode degrades to the donor path when coverage
                    # is short (fresh cluster, EC disabled on peers).
                    state, fetch_err = self._heal_from_donors(
                        src_rank, max_step, donor_ranks, donor_addrs
                    )
                if state is None:
                    self._heal_failures += 1
                    raise fetch_err if fetch_err is not None else RuntimeError(
                        "heal failed with no donors and no shard coverage"
                    )
                self._heal_failures = 0
                self._heal_backoff.reset()
                self._pending_state_dict = state
                # Fast-forward to the healed step (torchft/manager.py:562-568).
                self._step = max_step
        elif heal:
            self._healing = True

        # Quorum (and any heal fetch) resolved: the group is training until
        # the commit vote — without this the async-quorum overlap leaves the
        # replica labeled "quorum"/"heal" for the whole compute phase.
        self._set_status("step")

    def _heal_from_donors(
        self,
        src_rank: int,
        max_step: int,
        donor_ranks: List[int],
        donor_addrs: List[str],
    ) -> tuple:
        """The striped multi-donor fetch path: (state, None) on success,
        (None, error) on failure — the caller decides whether an erasure
        reconstruction can still save this quorum round."""
        # "healing from replica" is a grep contract with bench.py's
        # log-fallback heal counter (tests/test_bench_contract.py).
        self._logger.info(
            f"healing from replica {src_rank} at step {max_step} via "
            f"{len(donor_addrs)} donor(s) {list(zip(donor_ranks, donor_addrs))}"
        )
        self._metrics.emit(
            "heal_start",
            src_rank=src_rank,
            max_step=max_step,
            n_donors=len(donor_addrs),
        )
        try:
            with self._spans.span(
                "heal", step=max_step, src_rank=src_rank
            ) as sp_heal:
                donor_metas, donor_used = self._resolve_donor_metadatas(
                    donor_ranks, donor_addrs
                )
                state = self._checkpoint_transport.recv_checkpoint(
                    src_rank=donor_used[0],
                    metadata=(
                        donor_metas if len(donor_metas) > 1 else donor_metas[0]
                    ),
                    step=max_step,
                    timeout=self._timeout.total_seconds(),
                )
            self._metrics.emit(
                "heal_fetched",
                src_rank=donor_used[0],
                step=max_step,
                heal_ms=sp_heal.duration_ms,
                n_donors=len(donor_metas),
            )
            return cast(Dict[str, object], state), None
        except Exception as e:  # noqa: BLE001 — the caller may still
            # reconstruct from erasure shards this same round
            self._logger.warn(f"donor heal fetch failed: {e}")
            return None, e

    def _heal_from_shards(
        self, max_step: int, fetch_err: Optional[Exception]
    ) -> Optional[Dict[str, object]]:
        """Donor-free reconstruction: any k surviving shard holders ->
        the max-step state, installed through the exact same
        materialization the donor path uses (bitwise-equal by
        construction).  Returns None when coverage never reached k — the
        caller then latches the donor error and the next quorum retries."""
        assert self._ec is not None
        if max_step <= 0:
            # Step-0 init sync collapses the source set to participant 0's
            # (random-init) weights; no shard generation exists for it by
            # design (pre-sync states diverge) — donor path only.
            return None
        if fetch_err is not None:
            self._logger.warn(
                f"donor path exhausted ({fetch_err}); reconstructing step "
                f"{max_step} from erasure shards"
            )
        try:
            with self._spans.span("ec_reconstruct", step=max_step) as sp:
                meta, buffers, stats = self._ec.reconstruct_state(
                    max_step, timeout=self._timeout.total_seconds()
                )
                transport = self._checkpoint_transport
                if hasattr(transport, "materialize"):
                    state = transport.materialize(meta, buffers)
                else:
                    from torchft_tpu.checkpointing.serialization import (
                        unflatten_state_dict,
                    )

                    state = unflatten_state_dict(meta, buffers)
            self._metrics.emit(
                "ec_reconstruct",
                step=max_step,
                reconstruct_ms=sp.duration_ms,
                **{
                    k: v
                    for k, v in stats.items()
                    if k in ("holders", "probes", "corrupt", "fetch_errors",
                             "shards_used", "parity_used")
                },
            )
            self._logger.info(
                f"reconstructed step {max_step} from erasure shards "
                f"{stats.get('shards_used')} ({stats['holders']} holders, "
                f"{stats.get('parity_used', 0)} parity)"
            )
            return cast(Dict[str, object], state)
        except Exception as e:  # noqa: BLE001 — reconstruction is the
            # fallback; its failure must surface as a latched step error,
            # not a dead worker.
            self._logger.warn(f"erasure reconstruction failed: {e}")
            return None

    def _resolve_donor_metadatas(
        self, donor_ranks: List[int], donor_addrs: List[str]
    ) -> tuple:
        """Dials each donor's manager for its per-rank transport metadata,
        dropping donors that do not answer (a donor can die between the
        quorum and the heal; the stripe fetch then simply never includes
        it).  The dials run in parallel so one hung donor costs a single
        timeout, not a sum of timeouts, on the heal critical path.  Raises
        only when NO donor is reachable."""

        def dial(pair) -> str:
            return self._dial_peer_transport(pair[1])

        pairs = list(zip(donor_ranks, donor_addrs))
        metas: List[str] = []
        used: List[int] = []
        last_err: Optional[Exception] = None
        if len(pairs) == 1:
            outcomes = [self._try_call(dial, pairs[0])]
        else:
            with ThreadPoolExecutor(
                max_workers=len(pairs), thread_name_prefix="tpuft_donor_dial"
            ) as pool:
                outcomes = list(pool.map(lambda p: self._try_call(dial, p), pairs))
        for (rank_i, addr_i), (meta, err) in zip(pairs, outcomes):
            if err is None:
                metas.append(meta)
                used.append(rank_i)
            else:
                last_err = err
                self._logger.warn(f"donor {rank_i} ({addr_i}) unreachable: {err}")
        if not metas:
            raise RuntimeError(
                f"no heal donor reachable (tried {len(donor_addrs)}): {last_err}"
            )
        return metas, used

    @staticmethod
    def _try_call(fn, arg) -> tuple:
        """(result, None) or (None, exception) — lets a parallel map report
        per-item failures without aborting the batch."""
        try:
            return fn(arg), None
        except Exception as e:  # noqa: BLE001
            return None, e

    def _manager_state_dict(self) -> Dict[str, object]:
        """Full transferable state: user trees + manager bookkeeping
        (torchft/manager.py:677-694)."""
        return {
            "user": {k: fn() for k, fn in self._user_state_dicts.items()},
            "tpuft": self.state_dict(),
        }

    def _apply_pending_state_dict(self) -> None:
        """Applies a healed state dict to the user model (torchft/manager.py:570-585)."""
        assert self._healing, "apply_pending_state_dict called without healing"
        if self._pending_state_dict is None:
            # Quorum thread may still be fetching.
            self.wait_quorum()
        if self._pending_state_dict is None:
            # The heal FETCH failed (donors died or their serving windows
            # were busy; the quorum thread latched the error).  Degrade,
            # never crash: skip the apply, make sure an error is latched so
            # this step's commit vote fails, and let the NEXT quorum retry
            # the heal against the then-healthy donor set.  The assert that
            # used to live here turned a transient donor 503 into the death
            # of a worker the cluster had already paid to respawn — at
            # O(100) groups a single busy donor window killed healers
            # fleet-wide (found by the scale sweep's preemption-wave cell).
            if self._errored is None:
                self.report_error(RuntimeError("healing checkpoint was not fetched"))
            self._logger.warn(
                "healed state dict was never fetched; failing this step's "
                "commit and retrying the heal at the next quorum"
            )
            return
        self._logger.info("applying healed state dict")
        user = cast(Dict[str, object], self._pending_state_dict["user"])
        for key, value in user.items():
            if key in self._load_state_dict_fns:
                self._load_state_dict_fns[key](value)
        self.load_state_dict(cast(Dict[str, int], self._pending_state_dict["tpuft"]))
        self._pending_state_dict = None

    # -- allreduce ----------------------------------------------------------

    def allreduce(
        self,
        tensor,
        should_average: bool = True,
        allow_wire_compression: bool = True,
        wire_codec: Optional[str] = None,
        donate: bool = False,
    ) -> Future:
        """Fault-tolerant gradient allreduce across replica groups.

        Accepts a jax.Array or numpy array; returns a Future resolving to the
        averaged array of the same type/sharding.  Never raises — failures
        resolve to the unmodified input and latch the step error
        (reference: torchft/manager.py:262-323).

        allow_wire_compression=False exempts this call from lossy wire
        encodings (TCPCollective wire_dtype="bf16") — required when the
        payload is parameters rather than gradients (LocalSGD sync).

        wire_codec selects an explicit per-call wire encoding
        (collectives.WIRE_CODECS; "int8" = per-chunk-scale symmetric int8,
        ~0.25x the f32 wire) — the semisync pseudogradient plane's knob.
        The kwarg is only forwarded when set, so swapped-in collectives
        (tests, wrappers) keep the bare allreduce signature they mock.

        donate=True hands the host buffer's ownership to the collective:
        it may reduce in place and return the same storage, skipping the
        defensive copy.  Only safe when the caller does not reuse the
        input after the call (wire/fragment staging buffers).  On failure
        the future still resolves to the UNMODIFIED input semantics the
        caller observes today — the collective's contract is that a failed
        op never publishes a half-reduced buffer as the result.  Forwarded
        to the collective only when True, same mock-compat rule as
        wire_codec.
        """
        if self.errored() is not None:
            return completed_future(tensor)

        self.wait_quorum()

        # Alone in the ring and participating: averaging is the identity —
        # skip the device->host->device roundtrip entirely (TPU HBM traffic
        # is the budget; the reference still pays a no-op pg.allreduce here).
        if self._collective.size() == 1 and self.is_participating():
            return completed_future(tensor)

        is_jax = _is_jax_array(tensor)
        try:
            # Deadline-guarded: a wedged device computation surfaces as a
            # latched TimeoutError, not a hung train loop (the reference's
            # stream_timeout edge, torchft/futures.py:129-148).
            from torchft_tpu.futures import device_get

            host = device_get(tensor, self._timeout.total_seconds())
        except TimeoutError as e:
            self._logger.exception(f"allreduce input materialization: {e}")
            self.report_error(e)
            return completed_future(tensor)
        if not self.is_participating():
            # Healing replicas / spares contribute zeros (torchft/manager.py:287-288).
            host = np.zeros_like(host)

        # The DCN-throughput gauge counts bytes AS THE WIRE CARRIES THEM:
        # a bf16-wiring collective encodes float payloads to 2 bytes/elt
        # per hop regardless of whether the cast ran on device (bf16
        # buffer handed in) or inside the ring encode (f32 handed in).
        # Counting the handoff width instead would make the same wire
        # traffic read 2x apart between those two modes, inverting the
        # device-prep A/B that bench_allreduce draws from this gauge.  The
        # collective's own wire_nbytes is the single source of truth;
        # collectives without the probe count the handoff width.
        wire_nbytes = getattr(self._collective, "wire_nbytes", None)
        try:
            if callable(wire_nbytes):
                ar_nbytes = (
                    int(wire_nbytes(host, allow_wire_compression, wire_codec))
                    if wire_codec is not None
                    else int(wire_nbytes(host, allow_wire_compression))
                )
            else:
                ar_nbytes = int(host.nbytes)
        except Exception:  # noqa: BLE001 — telemetry only, never fail a step
            ar_nbytes = int(host.nbytes)
        with self._ar_lock:
            if self._ar_t_first is None:
                self._ar_t_first = time.monotonic()
            self._ar_bytes += ar_nbytes

        try:
            kwargs: Dict[str, Any] = {"allow_wire_compression": allow_wire_compression}
            if wire_codec is not None:
                kwargs["wire_codec"] = wire_codec
            if donate:
                kwargs["donate"] = True
            work = self._collective.allreduce([host], op="sum", **kwargs)

            def normalize(results: List[np.ndarray]):
                out = results[0]
                if should_average:
                    num = max(1, self.num_participants())
                    out = (out / num).astype(host.dtype, copy=False)
                if is_jax:
                    import jax

                    return jax.device_put(out, tensor.sharding)
                return out

            from torchft_tpu.futures import then

            fut = then(work.future(), normalize)
            return self.wrap_future(fut, default=tensor)
        except Exception as e:  # noqa: BLE001
            self._logger.exception(f"allreduce failed: {e}")
            self.report_error(e)
            return completed_future(tensor)

    def wrap_future(self, fut: Future, default, timeout: Optional[timedelta] = None) -> Future:
        """Arms a deadline and converts failure into (default, latched error)
        (reference: torchft/manager.py:346-383)."""
        timed = future_timeout(fut, (timeout or self._timeout).total_seconds())
        out: Future = Future()

        def settle(f: Future) -> None:
            # Drain edge for the allreduce GB/s window: the LAST settle of
            # the step, not should_commit() time, ends the wire window — a
            # loop that runs its optimizer between the averager's drain and
            # the vote must not see that compute charged to the DCN path.
            with self._ar_lock:
                self._ar_t_last = time.monotonic()
            exc = f.exception()
            if exc is not None:
                self._logger.exception(f"async work failed: {exc}")
                self.report_error(exc)
                out.set_result(default)
            else:
                out.set_result(f.result())

        timed.add_done_callback(settle)
        self._pending_work.append(out)
        return out

    def note_d2h(self, nbytes: int) -> None:
        """Adds device->host fetch bytes to the step in flight's transfer
        accounting (flushed into step_summary as ``d2h_bytes``).  Called by
        the data-plane wrappers (GradientAverager) that stage gradients
        through host buffers — with device wire prep this reads wire bytes,
        the ~2x reduction the bench pins."""
        with self._ar_lock:
            self._d2h_bytes += int(nbytes)
            self._d2h_bytes_total += int(nbytes)

    def note_h2d(self, nbytes: int) -> None:
        """Adds host->device scatter-back bytes to the step in flight's
        transfer accounting (``h2d_bytes`` in step_summary) — the return
        half of the round-trip the ``allreduce_h2d`` span charges."""
        with self._ar_lock:
            self._h2d_bytes += int(nbytes)
            self._h2d_bytes_total += int(nbytes)

    def note_summary_fields(self, **fields: object) -> None:
        """Merges extra fields into the step in flight's ``step_summary``
        record (flushed at the commit vote, cleared at start_quorum).
        Wrappers with their own data plane (the semisync engine) use this
        to land per-round accounting — fragment counts, codec, wire
        bytes — in the same record the phase breakdown rides."""
        with self._ar_lock:
            self._summary_extra.update(fields)

    def register_membership_callback(
        self, cb: Callable[[Dict[str, object]], None]
    ) -> None:
        """Registers ``cb`` to run on every quorum transition that changes
        the participant set.  The callback receives the same payload the
        ``membership_change`` event carries — old/new participant replica
        ranks, joined/left deltas, transition wall time, configure mode,
        and the refreshed elastic plan (None when the elastic batch engine
        is off).  It runs on the quorum thread after the collective is
        reconfigured and before the step proceeds, so a data loader can
        re-shard before the next batch is drawn.  Exceptions are swallowed
        and logged: a resize hook must never fail the step."""
        self._membership_callbacks.append(cb)

    def elastic_plan(self) -> Optional[Dict[str, object]]:
        """The elastic batch plan for the current participant count, or
        None when the elastic batch engine is off (TPUFT_ELASTIC_GLOBAL_BATCH
        unset) or no quorum has formed yet.  Keys: participants,
        global_batch, group_batch (this group's share), microbatch,
        accum_steps, lr_scale.  Stable between quorum transitions."""
        return self._elastic_plan

    def _on_membership_change(
        self,
        quorum: object,
        quorum_id: int,
        replica_world_size: int,
        configure_ms: float,
        last_configure: Dict[str, object],
    ) -> None:
        """Post-reconfigure membership bookkeeping: refresh the elastic
        batch plan, proactively re-shard the EC plane, emit the
        ``membership_change`` event, and fire registered callbacks.  Runs
        on the quorum thread for every quorum-id change; the event and
        callbacks fire only when the participant SET actually changed
        (a quorum id can change without membership churn, e.g. a
        lighthouse failover re-issuing the same membership)."""
        new_participants = sorted(
            list(getattr(quorum, "participant_replica_ranks", []) or [])
            or range(replica_world_size)
        )
        old_participants = self._last_participants
        self._last_participants = new_participants

        # Refresh the elastic plan from the PARTICIPATING world (healing
        # groups contribute zeros and take no batch share) so the global
        # batch stays constant across the transition.
        if self._elastic is not None:
            participants = self._participating_replica_world_size or len(
                new_participants
            )
            try:
                self._elastic_plan = self._elastic.plan(
                    participants, rank=self._participating_replica_rank
                )
            except Exception as e:  # noqa: BLE001 — resize must not fail a step
                self._logger.warn(f"elastic plan failed: {e}")

        if old_participants == new_participants:
            return

        # Proactive EC re-shard: re-place the latest shard generation under
        # the new membership so coverage is restored BEFORE the next fault,
        # not after (the tpuft_ec_shard_coverage alert fires on the gap).
        if self._ec is not None and hasattr(self._ec, "reshard"):
            try:
                self._ec.reshard()
            except Exception as e:  # noqa: BLE001
                self._logger.warn(f"ec reshard failed: {e}")

        old_set = set(old_participants or [])
        new_set = set(new_participants)
        payload: Dict[str, object] = {
            "quorum_id": quorum_id,
            "old_participants": old_participants,
            "new_participants": new_participants,
            "joined": sorted(new_set - old_set),
            "left": sorted(old_set - new_set),
            "transition_s": configure_ms / 1e3,
            "mode": last_configure.get("mode", "unknown"),
            "elastic_plan": self._elastic_plan,
        }
        self._metrics.emit("membership_change", step=self._step, **payload)
        # Also land the transition on this step's step_summary record so a
        # slow step reads its cause inline (resize vs fault) without joining
        # against the membership_change stream.
        self.note_summary_fields(
            membership_change={
                "joined": payload["joined"],
                "left": payload["left"],
                "transition_s": payload["transition_s"],
                "mode": payload["mode"],
            }
        )
        for cb in self._membership_callbacks:
            try:
                cb(dict(payload))
            except Exception as e:  # noqa: BLE001
                self._logger.warn(f"membership callback failed: {e}")

    @property
    def metrics(self):
        """The Manager's :class:`~torchft_tpu.metrics.MetricsLogger`.
        Public so wrappers that run their own data plane (the semisync
        engine) can emit registered events into the SAME stream the
        Manager's spans and lifecycle events ride — one timeline per
        replica, not a side channel."""
        return self._metrics

    @property
    def spans(self):
        """The Manager's :class:`~torchft_tpu.obs.spans.SpanTracker`.
        Public so wrappers that BLOCK the train thread on FT work outside
        the Manager's own phases (GradientAverager's bucket drain, custom
        sync loops) can record that wait as a span — anything not spanned
        here is charged as busy/productive time by both obs.report and the
        straggler sentinel's step-time telemetry."""
        return self._spans

    @property
    def timeout(self) -> timedelta:
        """Default per-operation deadline.  Public so wrappers can bound their
        own device->host materializations and RPC waits without reaching into
        private state (reference exposes the same knob as a ctor arg,
        torchft/manager.py:95-97)."""
        return self._timeout

    # -- link health (docs/architecture.md "Data-plane observability") ------

    _LINK_ALPHA = 0.5

    def _observe_link(self, lanes: dict) -> Dict[str, float]:
        """One per-step link-health observation from the lane_stats
        snapshot's hop aggregates: deltas against the previous snapshot
        give this step's send-blocked / recv-wait seconds and wire bytes,
        from which the per-neighbor goodput estimates follow —

        * ``link_send_gbps`` = sent bytes per second of send-BLOCKED time,
          the localizing signal (only the degraded edge's sender blocks;
          downstream recv-waits equalize around the lockstep ring);
        * ``link_recv_gbps`` = received bytes per second of recv-wait;
        * ``link_hop_rtt_ms`` = mean recv-wait per hop.

        EWMA'd (alpha 0.5, like the step-time stats) and returned as the
        step_summary / heartbeat fields; {} when the step moved no ring
        traffic or a reconfigure reset the counters mid-window."""
        hops = lanes.get("hops") or {}
        sent = float(sum(lanes.get("sent") or []))
        recv = float(sum(lanes.get("recv") or []))
        for t in (lanes.get("tiers") or {}).values():
            sent += sum(t.get("sent") or [])
            recv += sum(t.get("recv") or [])
        cur = {
            "sent": sent,
            "recv": recv,
            "send_block": float(
                sum(h.get("send_block_s", 0.0) for h in hops.values())
            ),
            "recv_wait": float(
                sum(h.get("recv_wait_s", 0.0) for h in hops.values())
            ),
            "hops": float(sum(h.get("hops", 0) for h in hops.values())),
        }
        prev, self._link_prev = self._link_prev, cur
        if prev is None or cur["hops"] < prev["hops"]:
            # First window, or the counters reset under us (reconfigure).
            return {}
        d = {k: cur[k] - prev[k] for k in cur}
        if d["hops"] <= 0 or (d["sent"] <= 0 and d["recv"] <= 0):
            return {}
        # A healthy link's send-blocked time is near zero (sends complete
        # into kernel buffers) — dividing by it would yield an estimate
        # that is pure scheduler noise, and noise RATIOS between healthy
        # peers are unbounded (the false-alert mode the bench's control
        # cell pins at zero).  Below a 5 ms-per-window floor the estimate
        # SATURATES: lockstep peers move identical bytes per step, so all
        # healthy readings collapse to the same floored value (ratio 1.0
        # by construction) while a genuinely blocked sender's seconds of
        # send-block dominate the floor and read as the true goodput.
        floor_s = 5e-3
        cap = 1e4
        send_gbps = min(d["sent"] / 1e9 / max(d["send_block"], floor_s), cap)
        recv_gbps = min(d["recv"] / 1e9 / max(d["recv_wait"], floor_s), cap)
        rtt_ms = d["recv_wait"] / d["hops"] * 1e3
        ew = self._link_ewma
        a = self._LINK_ALPHA
        for key, obs in (
            ("recv_gbps", recv_gbps),
            ("send_gbps", send_gbps),
            ("rtt_ms", rtt_ms),
        ):
            ew[key] = obs if key not in ew else a * obs + (1 - a) * ew[key]
        return {
            "link_recv_gbps": round(ew["recv_gbps"], 4),
            "link_send_gbps": round(ew["send_gbps"], 4),
            "link_hop_rtt_ms": round(ew["rtt_ms"], 3),
        }

    @property
    def worker_metrics(self):
        """The unified worker ``/metrics`` endpoint
        (:class:`~torchft_tpu.obs.prom.WorkerMetrics`).  Public so
        subsystems with their own exposition (the semisync engine)
        register a section here instead of opening a second port."""
        return self._worker_metrics

    def _worker_metrics_snapshot(self):
        """Series provider for the worker /metrics endpoint — called at
        SCRAPE time, never on the training path."""
        series = []

        def g(name, help_, value, kind="gauge", labels=()):
            series.append((name, kind, help_, labels, value))

        g("tpuft_worker_step", "current training step", self._step)
        snap = self._step_stats.snapshot()
        g(
            "tpuft_worker_step_time_ms_ewma",
            "rolling per-step busy-time EWMA, ms",
            snap["ewma"],
        )
        with self._ar_lock:
            d2h, h2d = self._d2h_bytes_total, self._h2d_bytes_total
        g(
            "tpuft_worker_d2h_bytes_total",
            "device->host fetch bytes (lifetime)", d2h, kind="counter",
        )
        g(
            "tpuft_worker_h2d_bytes_total",
            "host->device scatter-back bytes (lifetime)", h2d, kind="counter",
        )
        lane_totals = getattr(self._collective, "lane_totals", None)
        if callable(lane_totals):
            try:
                lt = lane_totals()
            except Exception:  # noqa: BLE001
                lt = None
            if lt:
                g(
                    "tpuft_worker_reconfigures_total",
                    "collective reconfigurations banked", lt["reconfigures"],
                    kind="counter",
                )
                # Metric-major so each series family renders contiguous
                # (Prometheus text-format convention).
                tiers = sorted((lt.get("tiers") or {}).items())
                for tname, t in tiers:
                    g(
                        "tpuft_worker_lane_sent_bytes_total",
                        "ring wire bytes sent per tier (monotonic across "
                        "reconfigures — banked at the source)",
                        t["sent_bytes"], kind="counter",
                        labels=(("tier", tname),),
                    )
                for tname, t in tiers:
                    g(
                        "tpuft_worker_lane_recv_bytes_total",
                        "ring wire bytes received per tier (monotonic)",
                        t["recv_bytes"], kind="counter",
                        labels=(("tier", tname),),
                    )
                hop_tiers = sorted((lt.get("hops") or {}).items())
                for tname, h in hop_tiers:
                    g(
                        "tpuft_worker_hops_total",
                        "ring hops per tier (monotonic)", h["hops"],
                        kind="counter", labels=(("tier", tname),),
                    )
                for key, metric in (
                    ("send_block_s", "tpuft_worker_hop_send_block_seconds_total"),
                    ("recv_wait_s", "tpuft_worker_hop_recv_wait_seconds_total"),
                    ("combine_s", "tpuft_worker_hop_combine_seconds_total"),
                    ("shape_s", "tpuft_worker_hop_shaping_seconds_total"),
                ):
                    for tname, h in hop_tiers:
                        g(
                            metric,
                            "per-hop stall seconds per tier (monotonic)",
                            round(float(h.get(key, 0.0)), 6), kind="counter",
                            labels=(("tier", tname),),
                        )
        ew = self._link_ewma
        if ew:
            g("tpuft_link_recv_gbps",
              "inbound ring-edge goodput EWMA (worker-side view)",
              round(ew.get("recv_gbps", 0.0), 4))
            g("tpuft_link_send_gbps",
              "outbound ring-edge goodput EWMA (worker-side view)",
              round(ew.get("send_gbps", 0.0), 4))
            g("tpuft_link_hop_rtt_ms", "mean per-hop recv-wait, ms",
              round(ew.get("rtt_ms", 0.0), 3))
        # Goodput ledger (worker-side view; the lighthouse aggregates the
        # same counters cluster-wide from heartbeat fields 14-16).
        led = self._ledger.snapshot()
        if led["steps"]:
            g("tpuft_worker_goodput_ratio",
              "cumulative productive fraction of accounted step wall",
              led["goodput_ratio"] if led["goodput_ratio"] is not None else -1.0)
            g("tpuft_worker_compute_seconds_total",
              "productive seconds accounted by the goodput ledger",
              led["compute_s"], kind="counter")
            for cause, v in sorted(led["lost_s"].items()):
                g("tpuft_worker_lost_seconds_total",
                  "lost seconds per ledger cause (pinned taxonomy, "
                  "obs/ledger.py CAUSES)",
                  v, kind="counter", labels=(("cause", cause),))
        return series

    def _render_hop_histograms(self) -> str:
        """Worker /metrics section: per-hop latency + wire-byte histograms
        per ring tier, fed from the collective's retained hop timeline
        (``hop_records``) — the sampled ring the data-plane flight
        recorder already keeps, so scraping adds no recording cost.

        MONOTONIC across scrapes: the timeline is a bounded SLIDING ring,
        so rebucketizing the whole ring each scrape would re-count old
        records and DROP counts when they age out — Prometheus reads any
        decrease in a histogram series as a counter reset.  Instead each
        scrape folds only records NEWER than the previous scrape's
        high-water timestamp into cumulative per-tier buckets (records
        that fall off the ring between scrapes are missed — an undercount
        under sparse scraping, never a reset)."""
        hop_records = getattr(self._collective, "hop_records", None)
        if not callable(hop_records):
            return ""
        try:
            recs = hop_records()
        except Exception:  # noqa: BLE001 — telemetry only
            return ""
        from torchft_tpu.obs.prom import (
            HOP_BYTES_BOUNDS,
            HOP_LATENCY_BOUNDS,
            bucketize,
            render_histogram_counts,
        )

        with self._hop_hist_lock:
            last_ts = self._hop_hist_last_ts
            for r in recs:
                ts = float(r.get("ts", 0.0))
                if ts <= last_ts:
                    continue
                # Slots key on (tier, lane): the lane split is what tells a
                # striped ring's per-lane byte skew apart from a uniform
                # slowdown.  Records from engines predating the lane field
                # fold into lane 0.
                tier = int(r.get("tier", 0))
                lane = int(r.get("lane", 0))
                slot = self._hop_hist.setdefault(
                    (tier, lane),
                    {
                        "lat": [0] * (len(HOP_LATENCY_BOUNDS) + 1),
                        "lat_sum": 0.0,
                        "bytes": [0] * (len(HOP_BYTES_BOUNDS) + 1),
                        "bytes_sum": 0.0,
                    },
                )
                lat = (
                    float(r.get("send_s", 0.0))
                    + float(r.get("recv_s", 0.0))
                    + float(r.get("comb_s", 0.0))
                )
                _, dsum = bucketize(HOP_LATENCY_BOUNDS, (lat,), slot["lat"])
                slot["lat_sum"] += dsum
                _, dsum = bucketize(
                    HOP_BYTES_BOUNDS, (float(r.get("nbytes", 0)),),
                    slot["bytes"],
                )
                slot["bytes_sum"] += dsum
                self._hop_hist_last_ts = max(self._hop_hist_last_ts, ts)
            if not self._hop_hist:
                return ""
            # Per-tier families sum their lanes (sums of monotonic buckets
            # stay monotonic); the lane-split family emits one series per
            # slot.
            lat_series = []
            byte_series = []
            lane_byte_series = []
            for tier in sorted({t for t, _ in self._hop_hist}):
                labels = (
                    ("replica", self._replica_id),
                    ("tier", str(tier)),
                )
                lat = [0] * (len(HOP_LATENCY_BOUNDS) + 1)
                lat_sum = 0.0
                byts = [0] * (len(HOP_BYTES_BOUNDS) + 1)
                bytes_sum = 0.0
                for (t, _lane), slot in self._hop_hist.items():
                    if t != tier:
                        continue
                    lat = [a + b for a, b in zip(lat, slot["lat"])]
                    lat_sum += slot["lat_sum"]
                    byts = [a + b for a, b in zip(byts, slot["bytes"])]
                    bytes_sum += slot["bytes_sum"]
                lat_series.append((labels, lat, lat_sum))
                byte_series.append((labels, byts, bytes_sum))
            for tier, lane in sorted(self._hop_hist):
                slot = self._hop_hist[(tier, lane)]
                lane_byte_series.append(
                    (
                        (
                            ("replica", self._replica_id),
                            ("tier", str(tier)),
                            ("lane", str(lane)),
                        ),
                        list(slot["bytes"]),
                        slot["bytes_sum"],
                    )
                )
        out = render_histogram_counts(
            "tpuft_worker_hop_latency_seconds",
            "per-hop wall time (send-block + recv-wait + combine) from the "
            "retained hop timeline, per ring tier (sampled per "
            "TPUFT_HOP_SAMPLE; monotonic across scrapes)",
            HOP_LATENCY_BOUNDS, lat_series,
        )
        out += render_histogram_counts(
            "tpuft_worker_hop_wire_bytes",
            "per-hop wire payload bytes from the retained hop timeline, "
            "per ring tier (monotonic across scrapes)",
            HOP_BYTES_BOUNDS, byte_series,
        )
        out += render_histogram_counts(
            "tpuft_hop_bytes",
            "per-hop wire payload bytes split per ring tier AND lane, from "
            "the retained hop timeline (monotonic across scrapes) — the "
            "lane split exposes striped-ring byte skew the per-tier "
            "histogram averages away",
            HOP_BYTES_BOUNDS, lane_byte_series,
        )
        return out

    # -- goodput ledger (docs/architecture.md "Goodput ledger") -------------

    def _quorum_server_ms(self) -> Optional[float]:
        """Server-side share of this step's quorum wait, from the group's
        own native ManagerServer flight ring: the ``ManagerQuorum`` RPC
        span for the current trace id covers the local-rank aggregation +
        the lighthouse round (formation wait included) — everything that
        is NOT this client's transport.  The ledger splits the quorum
        cause with it (quorum_server vs quorum_transport).  None when no
        server runs here (rank != 0, fake-wire tests) or the ring holds no
        matching span — the ledger then charges the whole wait as
        quorum_server rather than fabricating a split."""
        srv = self._manager_server
        if srv is None or not self._trace_id:
            return None
        flight = getattr(srv, "flight", None)
        if not callable(flight):
            return None
        try:
            dump = flight(limit=32)
        except Exception:  # noqa: BLE001 — telemetry only
            return None
        total, seen = 0.0, False
        for ev in dump.get("events", []):
            if (
                isinstance(ev, dict)
                and ev.get("kind") == "rpc"
                and ev.get("method") == "ManagerQuorum"
                and ev.get("trace_id") == self._trace_id
            ):
                total += max(0.0, float(ev.get("dur_us", 0)) / 1e3)
                seen = True
        return total if seen else None

    def _push_ledger(self) -> None:
        """Pushes the ledger's cumulative counters onto heartbeat fields
        14-16 (best-effort; rank != 0 has no server, and status must never
        fail a step)."""
        srv = self._manager_server
        if srv is None or not hasattr(srv, "set_ledger"):
            return
        try:
            ratio, compute_s, lost = self._ledger.heartbeat_vector()
            srv.set_ledger(ratio, compute_s, lost)
        except Exception:  # noqa: BLE001
            pass

    @property
    def ledger(self):
        """The Manager's :class:`~torchft_tpu.obs.ledger.StepLedger` —
        public so benches and tests can read the cumulative cause totals
        without re-parsing the stream."""
        return self._ledger

    # -- status -------------------------------------------------------------

    def _set_status(self, state: str) -> None:
        """Pushes (step, state) plus the rolling step-time telemetry and the
        last committed step's allreduce GB/s into this group's native
        ManagerServer so its lighthouse heartbeats carry live per-replica
        progress AND pace — the feed for the lighthouse's ``GET /metrics``
        exposition (including ``tpuft_allreduce_gb_per_s``), the dashboard's
        step-lag column, and the straggler sentinel's health scoring.
        Rank != 0 has no server; best-effort by design (status must never
        fail a step)."""
        srv = self._manager_server
        if srv is None:
            return
        try:
            ec_held, ec_step, ec_k = -1, -1, -1
            if self._ec is not None:
                step, count = self._ec.coverage()
                # (-1, 0) while empty -> an authoritative zero report so a
                # pruned/fresh store never shows stale coverage.
                ec_held, ec_step = count, max(0, step)
                # k rides along so the lighthouse coverage sentinel can
                # page at coverage < k + 1 without its own EC config.
                ec_k = self._ec.config.k
            lk = self._link_ewma
            srv.set_status(
                self._step,
                state,
                self._step_stats.ewma_ms,
                self._step_stats.last_ms,
                self._ar_gbps,
                ec_held,
                ec_step,
                ec_k,
                lk.get("recv_gbps", -1.0),
                lk.get("send_gbps", -1.0),
                lk.get("rtt_ms", -1.0),
            )
        except Exception:  # noqa: BLE001
            pass

    # -- error handling -----------------------------------------------------

    def report_error(self, e: Exception) -> None:
        """Latches an error for this step; cleared at the next start_quorum
        (reference: torchft/manager.py:325-337)."""
        self._errored = e
        self._metrics.emit("error", step=self._step, error=repr(e))

    def errored(self) -> Optional[Exception]:
        return self._errored

    # -- commit protocol ----------------------------------------------------

    def should_commit(self, timeout: Optional[timedelta] = None) -> bool:
        """Two-phase commit vote across all local ranks of the group
        (reference: torchft/manager.py:587-663)."""
        # Settle the quorum before voting: the vote concerns state the
        # quorum thread may still be mutating (heal fast-forward of _step,
        # _healing, participation bookkeeping).  A loop that allreduced
        # already waited; this closes the race for loops that vote without
        # gradient traffic (num_participants() read 0 mid-flight there).
        if self._quorum_future is not None:
            self.wait_quorum()
        # Drain pending allreduces; their errors are already latched.  The
        # span is the merge wait: how long commit time blocked on gradient
        # traffic the step's compute did not already hide.
        with self._spans.span("allreduce_merge", step=self._step):
            for work in self._pending_work:
                try:
                    work.result()
                except Exception:  # noqa: BLE001
                    pass
            self._pending_work = []

        # Allreduce data-plane throughput for this step: payload bytes over
        # the first-issue -> drained window.  Computed after the drain so
        # pipelining/lane overlap is reflected; pushed to the lighthouse on
        # the post-vote status heartbeat and into step_summary below.
        with self._ar_lock:
            ar_bytes, ar_t_first = self._ar_bytes, self._ar_t_first
            ar_t_last = self._ar_t_last
            d2h_bytes, h2d_bytes = self._d2h_bytes, self._h2d_bytes
            summary_extra = self._summary_extra
            self._ar_bytes, self._ar_t_first = 0, None
            self._ar_t_last = None
            self._d2h_bytes = 0
            self._h2d_bytes = 0
            self._summary_extra = {}
        ar_fields: Dict[str, object] = dict(summary_extra)
        # Elastic invariant audit trail: every committed step record carries
        # the plan it trained under, so the bench (and any postmortem) can
        # assert the global batch never moved across membership churn.
        if self._elastic_plan is not None:
            ar_fields.setdefault(
                "elastic_global_batch", self._elastic_plan["global_batch"]
            )
            ar_fields.setdefault(
                "elastic_group_batch", self._elastic_plan["group_batch"]
            )
            ar_fields.setdefault(
                "elastic_accum_steps", self._elastic_plan["accum_steps"]
            )
            ar_fields.setdefault(
                "elastic_participants", self._elastic_plan["participants"]
            )
        if d2h_bytes or h2d_bytes:
            ar_fields["d2h_bytes"] = d2h_bytes
            ar_fields["h2d_bytes"] = h2d_bytes
        ar_gbps: Optional[float] = None
        lanes_snap: Optional[dict] = None
        if ar_bytes and ar_t_first is not None:
            if ar_t_last is None or ar_t_last <= ar_t_first:
                ar_t_last = time.monotonic()
            ar_dur = max(1e-9, ar_t_last - ar_t_first)
            ar_gbps = ar_bytes / 1e9 / ar_dur
            ar_fields.update(
                {
                    "allreduce_bytes": ar_bytes,
                    "allreduce_s": round(ar_dur, 4),
                    "allreduce_gb_per_s": round(ar_gbps, 4),
                }
            )
            lane_stats = getattr(self._collective, "lane_stats", None)
            if callable(lane_stats):
                try:
                    lanes_snap = lane_stats()
                    ar_fields["allreduce_lanes"] = lanes_snap
                    # Per-neighbor link health from this step's hop-stall
                    # deltas (rides step_summary AND heartbeat fields
                    # 11-13 — the slow-link sentinel's feed).
                    ar_fields.update(self._observe_link(lanes_snap))
                except Exception:  # noqa: BLE001 — telemetry only
                    pass

        if self._collective.errored() is not None:
            self.report_error(cast(Exception, self._collective.errored()))

        if self._healing:
            self._apply_pending_state_dict()

        enough_replicas = self.num_participants() >= self._min_replica_size
        local_should_commit = enough_replicas and self._errored is None
        vote_step = self._step
        with self._spans.span("commit_vote", step=vote_step) as sp_vote:
            should_commit = self._client.should_commit(
                self._rank,
                vote_step,
                local_should_commit,
                timeout_ms=int((timeout or self._timeout).total_seconds() * 1000),
                trace_id=self._trace_id,
            )
        self._logger.info(
            f"should_commit={should_commit} (local={local_should_commit}, "
            f"enough_replicas={enough_replicas}, error={self._errored})"
        )
        self._metrics.emit(
            "commit",
            step=vote_step,
            committed=should_commit,
            local=local_should_commit,
            participants=self.num_participants(),
            error=repr(self._errored) if self._errored else None,
            vote_ms=sp_vote.duration_ms,
        )
        # Straggler-sentinel observation: this step's BUSY time = the
        # commit-to-commit wall interval minus the FT wait phases the span
        # accumulator holds for the step in flight (read BEFORE step_summary
        # flushes it).  In lockstep training the raw interval equalizes
        # across the quorum — everyone waits for the slowest — so only
        # wall-minus-waits identifies the host that actually computed the
        # whole time.  Failed commits produce no observation (their eventual
        # commit interval spans the retries and would misread as slowness).
        step_time_fields: Dict[str, object] = {}
        # Ledger classification reads the span accumulation BEFORE
        # step_summary flushes it (obs/ledger.py).
        phases_now = self._spans.phases_ms()
        if should_commit:
            now_mono = time.monotonic()
            if self._last_commit_mono is not None:
                wall_ms = (now_mono - self._last_commit_mono) * 1e3
                busy_ms = max(0.0, wall_ms - self._spans.ft_accounted_ms())
                self._step_stats.observe(busy_ms)
                snap = self._step_stats.snapshot()
                step_time_fields = {
                    "step_wall_ms": round(wall_ms, 3),
                    "step_time_ms": round(busy_ms, 3),
                    "step_time_ms_ewma": snap["ewma"],
                    "step_time_ms_p50": snap["p50"],
                    "step_time_ms_p99": snap["p99"],
                }
            # Ledger interval: from the ledger's own last-commit mark, so
            # a retried step's wall (failed votes included) is charged in
            # this one committed observation, with the failed attempts'
            # buffered phases merged in.
            if self._ledger_prev_commit_mono is not None:
                ledger_wall_s = now_mono - self._ledger_prev_commit_mono
                ledger_phases = dict(self._ledger_pending_phases)
                for k, v in phases_now.items():
                    ledger_phases[k] = ledger_phases.get(k, 0.0) + float(v)
                # The server/transport split costs a flight-ring read
                # (small JSON parse); only pay it when the quorum wait is
                # big enough for the split to mean anything — steady-state
                # sub-50 ms waits charge the lump to quorum_server, and
                # the ledger's commit-path cost stays sub-0.1 ms.
                q_server_ms = (
                    self._quorum_server_ms()
                    if ledger_phases.get("quorum", 0.0) > 50.0
                    else None
                )
                causes = self._ledger.observe_step(
                    vote_step,
                    ledger_wall_s,
                    ledger_phases,
                    lanes=lanes_snap,
                    committed=True,
                    draining=self.drain_requested(),
                    quorum_server_ms=q_server_ms,
                )
                if causes is not None:
                    step_time_fields["ledger"] = {
                        "causes": {k: round(v, 4) for k, v in causes.items()},
                        "goodput_ratio": self._ledger.goodput_ratio(),
                    }
                self._push_ledger()
            self._ledger_pending_phases = {}
            self._ledger_prev_commit_mono = now_mono
            self._last_commit_mono = now_mono
        else:
            # Failed votes produce no ledger observation, but their
            # phases buffer into the eventual committed interval's charge
            # and the hop-delta window still advances so the retried
            # step's stalls are not double-charged.
            for k, v in phases_now.items():
                self._ledger_pending_phases[k] = (
                    self._ledger_pending_phases.get(k, 0.0) + float(v)
                )
            self._ledger.observe_step(
                vote_step, 0.0, phases_now, lanes=lanes_snap, committed=False
            )
            self._last_commit_mono = None
        self._spans.step_summary(
            vote_step, committed=should_commit, **step_time_fields, **ar_fields
        )

        if self._checkpoint_transport is not None:
            # Weights are about to be mutated: stop serving the stale
            # checkpoint (torchft/manager.py:645).
            self._checkpoint_transport.disallow_checkpoint()

        if should_commit:
            self._step += 1
            self._batches_committed += self.num_participants()
            self._commit_failures = 0
            # The gauge is "the last COMMITTED step's" throughput (proto
            # field 6): a failed vote's timeout-stretched window must not
            # overwrite it, and a committed step with no allreduce traffic
            # (healing, spare) clears it — a stale healthy number would
            # mask exactly the DCN degradation the gauge exists to expose.
            self._ar_gbps = ar_gbps if ar_gbps is not None else 0.0
            self._set_status("step")
        else:
            self._commit_failures += 1
            if self._max_retries is not None and self._commit_failures > self._max_retries:
                raise ExceededMaxRetriesError(
                    f"exceeded max_retries={self._max_retries} consecutive failed commits"
                )
        return should_commit

    # -- cooperative drain --------------------------------------------------

    def attach_drain_watcher(self, watcher=None) -> "object":
        """Wires a :class:`~torchft_tpu.drain.DrainWatcher` to this manager
        and starts it.  With no argument, builds one from the environment
        contract (SIGTERM + ``TPUFT_DRAIN_DIR`` notice file + optional GCE
        metadata poll).  The watcher is stopped by :meth:`shutdown`."""
        if watcher is None:
            from torchft_tpu.drain import DrainWatcher

            watcher = DrainWatcher(on_notice=self.begin_drain)
        else:
            watcher._on_notice = self.begin_drain
        self._drain_watcher = watcher
        watcher.start()
        return watcher

    def begin_drain(self, notice=None) -> None:
        """Handles a drain notice: records it for the train loop and tells
        the lighthouse IMMEDIATELY (wire method 5) so the next quorum
        excludes this group with zero join/heartbeat-timeout wait, while
        the in-flight step finishes undisturbed.  Idempotent; callable from
        any thread (the DrainWatcher invokes it from a signal handler or a
        poller thread)."""
        from torchft_tpu.drain import DrainNotice

        if notice is None:
            notice = DrainNotice(source="manual", deadline=time.time() + 30.0)
        with self._drain_lock:
            if self._drain_notice is not None:
                return
            self._drain_notice = notice
        self._logger.warn(
            f"drain notice ({notice.source}): finishing in-flight step, "
            f"deadline in {notice.remaining_s():.1f}s"
        )
        self._metrics.emit(
            "drain_notice",
            step=self._step,
            source=notice.source,
            deadline_ms=notice.deadline_ms_from_now(),
        )
        self._set_status("draining")
        # Rank 0 owns the group's lighthouse relationship; other local
        # ranks observe the same notice via their own watcher/launcher
        # channel and simply stop stepping.  The RPC runs on its own
        # thread: begin_drain may be called from a SIGTERM handler on the
        # main thread, and the final step must not stall behind a dial.
        if self._rank == 0 and self._lighthouse_addr:
            def _notify() -> None:
                # Reconnect loop with DECORRELATED jitter: the notice may
                # land exactly during a lighthouse failover (the two
                # events correlate — a host being preempted can take the
                # lighthouse with it), and every draining group in a
                # preemption wave retries this same call.  Jittered sleeps
                # keep those retries from stampeding the new leader in
                # sync; the loop gives up at the drain deadline (less a
                # grace margin) because a notice that cannot be delivered
                # degrades to the crash path (heartbeat timeout) — it must
                # never outlive the process's own exit budget.
                from torchft_tpu._native import LighthouseClient
                from torchft_tpu.ha.backoff import DecorrelatedBackoff

                deadline = time.monotonic() + min(
                    10.0, max(2.0, notice.remaining_s() - 2.0)
                )
                backoff = DecorrelatedBackoff(base_s=0.1, cap_s=1.5)
                last_err: Optional[Exception] = None
                while time.monotonic() < deadline:
                    try:
                        client = LighthouseClient(
                            self._lighthouse_addr, connect_timeout_ms=2000
                        )
                        try:
                            client.drain(
                                self._replica_id,
                                deadline_ms=notice.deadline_ms_from_now(),
                                timeout_ms=2000,
                                trace_id=self._trace_id,
                            )
                        finally:
                            client.close()
                        return
                    except Exception as e:  # noqa: BLE001
                        last_err = e
                        sleep_s = backoff.next()
                        if time.monotonic() + sleep_s >= deadline:
                            break
                        time.sleep(sleep_s)
                # A failed notice degrades to the crash path (heartbeat
                # timeout), never kills the final step.
                self._logger.warn(f"lighthouse drain notice failed: {last_err}")

            threading.Thread(
                target=_notify, name="tpuft_drain_notify", daemon=True
            ).start()

    def drain_requested(self) -> bool:
        """True once a drain notice arrived: the train loop must finish the
        current step, then exit via :meth:`complete_drain`."""
        return self._drain_notice is not None

    def drain_notice(self):
        return self._drain_notice

    def complete_drain(self) -> None:
        """Marks the cooperative departure finished (call after the final
        committed step, before :meth:`shutdown`).  The checkpoint transport
        keeps serving until shutdown so an already-assigned heal against
        this donor can still complete."""
        notice = self._drain_notice
        self._metrics.emit(
            "drain_complete",
            step=self._step,
            batches_committed=self._batches_committed,
            source=notice.source if notice is not None else None,
        )
        self._logger.info(
            f"drain complete at step {self._step}; exiting cleanly"
        )

    # -- state --------------------------------------------------------------

    def load_state_dict(self, state_dict: Dict[str, int]) -> None:
        """Restores manager bookkeeping from a durable checkpoint
        (reference: torchft/manager.py:665-677)."""
        self._step = state_dict["step"]
        self._batches_committed = state_dict["batches_committed"]

    def state_dict(self) -> Dict[str, int]:
        """Manager bookkeeping to persist with the model
        (reference: torchft/manager.py:679-694)."""
        return {"step": self._step, "batches_committed": self._batches_committed}

    def current_step(self) -> int:
        """Current step, incremented on every committed step
        (reference: torchft/manager.py:742-750)."""
        return self._step

    def batches_committed(self) -> int:
        """Total batches committed across all groups and steps
        (reference: torchft/manager.py:752-762)."""
        return self._batches_committed

    def num_participants(self) -> int:
        """Replica groups participating in the current step
        (reference: torchft/manager.py:728-736)."""
        return self._participating_replica_world_size

    def participating_rank(self) -> Optional[int]:
        """This group's rank among participating groups, or None while
        healing / sparing (reference: torchft/manager.py:712-726)."""
        assert self._quorum_future is not None, "quorum not started"
        self.wait_quorum()
        return self._participating_replica_rank

    def is_participating(self) -> bool:
        """False while healing or sparing (reference: torchft/manager.py:696-710)."""
        return self._participating_replica_rank is not None

    def replica_id(self) -> str:
        return self._replica_id

    def store_address(self) -> str:
        return self._store_address

    def collective(self) -> Collective:
        return self._collective

    def _dump_hops(self) -> None:
        """Writes the collective's retained hop timeline to
        ``$TPUFT_HOP_DUMP_DIR/hops_<replica_id>.json`` (best-effort; the
        dump must never fail shutdown).  Records carry wall-clock ``ts``,
        so the trace export time-aligns them with the span stream."""
        dump_dir = os.environ.get("TPUFT_HOP_DUMP_DIR", "")
        if not dump_dir:
            return
        hop_records = getattr(self._collective, "hop_records", None)
        if not callable(hop_records):
            return
        try:
            records = hop_records()
            path = os.path.join(
                dump_dir,
                f"hops_{self._replica_id.replace('/', '_').replace(':', '_')}.json",
            )
            with open(path, "w") as f:
                json.dump(
                    {"replica_id": self._replica_id, "records": records}, f
                )
        except Exception:  # noqa: BLE001
            pass

    def shutdown(self) -> None:
        if self._drain_watcher is not None:
            try:
                self._drain_watcher.stop()
            except Exception:  # noqa: BLE001
                pass
            self._drain_watcher = None
        # Data-plane black box: like $TPUFT_FLIGHT_DIR's control-plane
        # dumps, a departing worker leaves its retained hop timeline as
        # hops_<replica_id>.json when TPUFT_HOP_DUMP_DIR is set —
        # tools/trace_export.py collects these into the per-lane
        # data-plane Perfetto track.
        self._dump_hops()
        self._worker_metrics.close()
        self._metrics.close()
        self._executor.shutdown(wait=True)
        if self._checkpoint_transport is not None:
            self._checkpoint_transport.shutdown(wait=False)
        self._client.close()
        self._collective.shutdown()
        if self._manager_server is not None:
            self._manager_server.shutdown()
        if self._store_server is not None:
            self._store_server.shutdown()


def _env_float(name: str, default: float) -> float:
    """Float env knob with a loud-but-safe fallback: a malformed tuning
    value must never abort recovery itself."""
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        logging.getLogger("torchft_tpu.manager").warning(
            "ignoring malformed %s", name
        )
        return default


def _max_heal_donors() -> int:
    """Donor-count cap for one striped heal (``TPUFT_MAX_HEAL_DONORS``,
    default 4, 0 = uncapped); malformed values fall back to the default —
    a bad tuning knob must not abort recovery."""
    try:
        return int(os.environ.get(TPUFT_MAX_HEAL_DONORS_ENV, "4"))
    except ValueError:
        return 4


def _is_jax_array(x) -> bool:
    try:
        import jax

        return isinstance(x, jax.Array)
    except ImportError:
        return False


class _ManagerLogger:
    """Log prefix "[replica/rank - step N]" (reference: torchft/manager.py:773-792)."""

    def __init__(self, manager: Manager, replica_id: str, rank: int) -> None:
        self._logger = logging.getLogger("torchft_tpu.manager")
        self._replica_id = replica_id
        self._rank = rank
        self._manager = manager

    def prefix(self) -> str:
        return f"[{self._replica_id}/{self._rank} - step {self._manager.current_step()}]"

    def info(self, msg: str) -> None:
        self._logger.info(f"{self.prefix()} {msg}")

    def warn(self, msg: str) -> None:
        self._logger.warning(f"{self.prefix()} {msg}")

    def exception(self, msg: str) -> None:
        self._logger.exception(f"{self.prefix()} {msg}")
