"""Future timeout plumbing.

Reference parity: torchft/futures.py — a singleton timeout manager running a
single background thread arms deadlines for futures and context blocks so
that a stuck collective or RPC surfaces as a ``TimeoutError`` on the wrapped
future instead of hanging the train loop.  The reference drives torch Futures
and CUDA events from an asyncio loop thread (torchft/futures.py:88-210); here
the unit of work is a ``concurrent.futures.Future`` and device-side waits are
handled by JAX's async dispatch, so a heap-of-deadlines timer thread suffices.
"""

from __future__ import annotations

import heapq
import itertools
import threading
from concurrent.futures import Future
from contextlib import contextmanager
from typing import Any, Callable, Generator, Optional, TypeVar

T = TypeVar("T")


class _TimeoutManager:
    """Singleton deadline scheduler (reference: _TimeoutManager,
    torchft/futures.py:88-207)."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._heap: list[tuple[float, int, Callable[[], None]]] = []
        self._counter = itertools.count()
        self._thread: Optional[threading.Thread] = None
        self._cancelled: set[int] = set()

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="tpuft_timeout_manager", daemon=True
            )
            self._thread.start()

    def register(self, delay: float, callback: Callable[[], None]) -> int:
        """Schedules callback to fire after `delay` seconds; returns a handle
        usable with cancel()."""
        import time

        with self._cond:
            handle = next(self._counter)
            heapq.heappush(self._heap, (time.monotonic() + delay, handle, callback))
            self._ensure_thread()
            self._cond.notify()
        return handle

    def cancel(self, handle: int) -> None:
        with self._cond:
            self._cancelled.add(handle)
            self._cond.notify()

    def _run(self) -> None:
        import time

        while True:
            with self._cond:
                while not self._heap:
                    self._cond.wait()
                deadline, handle, callback = self._heap[0]
                now = time.monotonic()
                if handle in self._cancelled:
                    heapq.heappop(self._heap)
                    self._cancelled.discard(handle)
                    continue
                if deadline > now:
                    self._cond.wait(timeout=deadline - now)
                    continue
                heapq.heappop(self._heap)
            try:
                callback()
            except Exception:
                pass


_TIMEOUT_MANAGER = _TimeoutManager()


def future_timeout(fut: Future, timeout: float) -> Future:
    """Returns a future that mirrors `fut` but fails with TimeoutError if it
    does not complete within `timeout` seconds (reference:
    future_timeout, torchft/futures.py:210-222)."""
    out: Future = Future()

    def on_timeout() -> None:
        if not out.done():
            out.set_exception(
                TimeoutError(f"future did not complete within {timeout}s")
            )

    handle = _TIMEOUT_MANAGER.register(timeout, on_timeout)

    def on_done(f: Future) -> None:
        _TIMEOUT_MANAGER.cancel(handle)
        if out.done():
            return
        exc = f.exception()
        if exc is not None:
            out.set_exception(exc)
        else:
            out.set_result(f.result())

    fut.add_done_callback(on_done)
    return out


def future_wait(fut: Future, timeout: float) -> Any:
    """Blocking wait with a deadline (reference: future_wait,
    torchft/futures.py:225-252).  The deadline surfaces as the BUILTIN
    TimeoutError: on Python < 3.11 ``Future.result`` raises the distinct
    ``concurrent.futures.TimeoutError``, which ``except TimeoutError``
    handlers across the codebase would silently miss."""
    import concurrent.futures

    try:
        return fut.result(timeout=timeout)
    except concurrent.futures.TimeoutError as e:
        if isinstance(e, TimeoutError):  # 3.11+: already the builtin
            raise
        raise TimeoutError(f"future did not complete within {timeout}s") from None


@contextmanager
def context_timeout(callback: Callable[[], None], timeout: float) -> Generator[None, None, None]:
    """Runs `callback` (typically an abort) if the with-block does not finish
    within `timeout` seconds (reference: context_timeout,
    torchft/futures.py:270-282)."""
    handle = _TIMEOUT_MANAGER.register(timeout, callback)
    try:
        yield
    finally:
        _TIMEOUT_MANAGER.cancel(handle)


class _Materializer:
    """Deadline-guarded device->host materialization (the ``stream_timeout``
    analogue, torchft/futures.py:129-148,255).

    ``np.asarray(jax_array)`` blocks indefinitely if the device computation
    feeding it wedges; the reference arms a CUDA-event timer for the same
    edge.  Here the transfer runs on a dedicated **daemon** thread with a
    deadline: on timeout the caller gets ``TimeoutError`` (to latch into the
    step error) and the wedged thread is abandoned — a fresh one serves later
    calls, so one stuck transfer cannot poison the next step's path, and a
    genuinely wedged worker cannot block interpreter shutdown the way a
    ThreadPoolExecutor worker (joined at exit since Python 3.9) would."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._queue = None  # type: Optional[object]
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _worker(q) -> None:
        while True:
            item = q.get()
            if item is None:
                return
            fn, fut = item
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

    def _get_queue(self):
        import queue as _queue

        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._queue = _queue.SimpleQueue()
                self._thread = threading.Thread(
                    target=self._worker,
                    args=(self._queue,),
                    name="tpuft_materialize",
                    daemon=True,
                )
                self._thread.start()
            return self._queue

    def _abandon(self, q) -> None:
        with self._lock:
            if self._queue is not q:
                # Another timed-out caller already abandoned this generation
                # (and drained it); a fresh queue is serving new work.
                return
            old, self._queue = self._queue, None
            self._thread = None
        # Concurrent callers may have queued work behind the wedged item;
        # fail it now rather than letting those callers burn their full
        # deadline on futures nothing will ever run.
        while True:
            try:
                item = old.get_nowait()
            except Exception:  # queue.Empty
                break
            if item is None:
                continue
            _, fut = item
            if not fut.done():
                fut.set_exception(
                    TimeoutError(
                        "materializer abandoned after a concurrent timeout; "
                        "transfer not attempted"
                    )
                )
        old.put(None)  # exit signal, honored if the worker ever unwedges

    def get(self, fn: Callable[[], T], timeout: float) -> T:
        import concurrent.futures

        fut: Future = Future()
        q = self._get_queue()
        q.put((fn, fut))
        try:
            return fut.result(timeout=timeout)
        except concurrent.futures.TimeoutError:
            # concurrent.futures.TimeoutError, NOT the builtin: on Python
            # < 3.11 they are distinct classes, and catching the builtin
            # here silently skipped the abandon (the wedged worker kept the
            # queue, poisoning every later transfer) while callers' `except
            # TimeoutError` error-latching missed the escape entirely.
            self._abandon(q)
            raise TimeoutError(
                f"device->host materialization did not complete within {timeout}s "
                "(stuck device computation?)"
            ) from None


_MATERIALIZER = _Materializer()


def device_get(x: Any, timeout: float) -> Any:
    """Materializes a (possibly device-backed) array to host numpy with a
    deadline; raises TimeoutError instead of hanging on wedged device work."""
    import numpy as np

    return _MATERIALIZER.get(lambda: np.asarray(x), timeout)


def device_get_tree(leaves: list, timeout: float) -> list:
    """Materializes a list of arrays with one shared deadline."""
    import numpy as np

    return _MATERIALIZER.get(lambda: [np.asarray(l) for l in leaves], timeout)


def _copy_into(dst, src_host, cast: bool) -> None:
    """One dtype-checked copy of a materialized source into its destination
    view.  Same-dtype is the fast path; a mismatch raises a ValueError that
    names both dtypes (``np.copyto(casting="no")`` raises a bare TypeError
    the moment a device buffer's dtype diverges from its planned host
    buffer — e.g. a bf16 wire-prepped bucket landing in an f32 buffer —
    which reads like a numpy bug, not a planning bug) unless the caller
    explicitly opted into value conversion with ``cast=True``."""
    import numpy as np

    src_host = src_host.reshape(dst.shape)
    if src_host.dtype == dst.dtype:
        try:
            np.copyto(dst, src_host, casting="no")
        except TypeError:
            # Some numpy/ml_dtypes combinations reject casting="no" even for
            # identical custom dtypes (bfloat16, float8 variants).  Equal
            # dtypes make a raw byte copy exactly equivalent.
            np.copyto(
                dst.view(np.uint8),
                np.ascontiguousarray(src_host).view(np.uint8),
                casting="no",
            )
        return
    if not cast:
        raise ValueError(
            f"device_get_into: source dtype {src_host.dtype} does not match "
            f"destination buffer dtype {dst.dtype}; plan the host buffer in "
            "the dtype the device hands back (device wire prep fetches the "
            "wire dtype), or pass cast=True to convert values explicitly"
        )
    np.copyto(dst, src_host, casting="unsafe")


def device_get_into(pairs: list, timeout: float, cast: bool = False) -> None:
    """Materializes ``(src, dst)`` pairs host-side under one shared deadline,
    landing each source directly in its destination view — the bucket-
    pipelined D2H path: every gradient leaf is copied straight into its slot
    of a persistent flat buffer, with no per-step concatenate or fresh
    allocation.  ``dst`` must be a writable numpy view shaped like ``src``.

    Dtypes are checked explicitly: matching dtypes take a fast path (with a
    byte-copy fallback for ml_dtypes destinations numpy's ``casting="no"``
    rejects), and a mismatch raises a clear ValueError unless ``cast=True``
    opts into value conversion — the device wire-prep path fetches bf16
    bytes into bf16 buffers, and a silent f32<->bf16 convert here would
    hide a mis-planned buffer at half or double the intended D2H bytes.
    """
    import numpy as np

    def run() -> None:
        for src, dst in pairs:
            _copy_into(dst, np.asarray(src), cast)

    _MATERIALIZER.get(run, timeout)


def completed_future(value: T = None) -> Future:
    """A future already resolved with `value`."""
    fut: Future = Future()
    fut.set_result(value)
    return fut


def failed_future(exc: Exception) -> Future:
    """A Future already resolved to the given exception."""
    fut: Future = Future()
    fut.set_exception(exc)
    return fut


def then(fut: Future, fn: Callable[[Any], T]) -> Future:
    """Chains a continuation onto `fut`, producing a new future with fn's
    result (the torch Future.then analogue used for grad normalization,
    torchft/manager.py:297-311)."""
    out: Future = Future()

    def on_done(f: Future) -> None:
        exc = f.exception()
        if exc is not None:
            out.set_exception(exc)
            return
        try:
            out.set_result(fn(f.result()))
        except Exception as e:  # noqa: BLE001
            out.set_exception(e)

    fut.add_done_callback(on_done)
    return out
