"""Standalone Store server CLI.

The rendezvous endpoint a multi-host replica group's rank-0 pod serves
(wire methods 20-23, docs/wire.md): `multihost.initialize_slice` publishes
and reads the JAX coordinator address through it, and any other
coordination key can ride the same store.  The generated JobSet manifest
(`torchft_tpu/spec.py`) starts this in the background on each group's
host-rank-0 pod; locally it is also handy as a long-lived store for
manual multi-process drives::

    python -m torchft_tpu.store_cli --bind "[::]:29500"
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import List, Optional

__all__ = ["main"]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m torchft_tpu.store_cli",
        description="Serve a standalone tpu-ft Store (framed-TCP protobuf, "
        "docs/wire.md) until interrupted.",
    )
    parser.add_argument("--bind", default="[::]:29500", help="host:port to bind")
    args = parser.parse_args(argv)

    from torchft_tpu.coordination import StoreServer

    store = StoreServer(bind=args.bind)
    print(f"[tpuft_store] listening on {store.address()}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        return 130
    finally:
        store.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
