"""Logical-axis sharding rules.

Model code names array axes logically ("batch", "seq", "embed", "heads",
"mlp", "vocab", "expert", "layers"); a ``ShardingRules`` table maps logical
names to mesh axes ("data", "fsdp", "tensor", "sequence", "expert").  This
is the TPU-idiomatic replacement for the reference's DTensor placements —
sharding is annotation, XLA inserts the collectives (scaling-book recipe).

The default rules give Megatron-style TP (heads/mlp over "tensor"),
FSDP-style parameter sharding (embed over "fsdp"), batch over "data", and
sequence over "sequence" for the ring-attention path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class ShardingRules:
    """logical axis -> mesh axis (or None = replicated)."""

    rules: Tuple[Tuple[str, Optional[str]], ...] = (
        ("batch", "data"),
        ("seq", "sequence"),
        ("embed", "fsdp"),
        ("heads", "tensor"),
        ("kv_heads", "tensor"),
        ("mlp", "tensor"),
        ("vocab", "tensor"),
        ("expert", "expert"),
        # Stacked-layer leading axis: sharded over pipeline stages when the
        # mesh has them (parallel/pipeline.py), replicated otherwise.
        ("layers", "pipeline"),
    )

    def mesh_axis(self, logical: Optional[str], mesh: Mesh) -> Optional[str]:
        if logical is None:
            return None
        for name, axis in self.rules:
            if name == logical:
                # Drop axes the mesh doesn't have (e.g. no "sequence" axis
                # in a pure-DP mesh) — the dimension is then replicated.
                return axis if axis in mesh.axis_names else None
        return None

    def spec(self, logical_axes: Tuple[Optional[str], ...], mesh: Mesh) -> P:
        seen = set()
        out = []
        for ax in logical_axes:
            m = self.mesh_axis(ax, mesh)
            # A mesh axis may appear at most once in a PartitionSpec.
            if m is not None and m in seen:
                m = None
            if m is not None:
                seen.add(m)
            out.append(m)
        return P(*out)

    def sharding(
        self, logical_axes: Tuple[Optional[str], ...], mesh: Mesh
    ) -> NamedSharding:
        return NamedSharding(mesh, self.spec(logical_axes, mesh))


def logical_sharding(
    tree_axes: Any, mesh: Mesh, rules: Optional[ShardingRules] = None
) -> Any:
    """Maps a pytree of logical-axis tuples to a pytree of NamedShardings."""
    rules = rules or ShardingRules()
    return jax.tree.map(
        lambda axes: rules.sharding(axes, mesh),
        tree_axes,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(a is None or isinstance(a, str) for a in x),
    )


def constrain(x: jax.Array, axes: Tuple[Optional[str], ...], mesh: Optional[Mesh],
              rules: Optional[ShardingRules] = None) -> jax.Array:
    """with_sharding_constraint by logical axes; no-op without a mesh."""
    if mesh is None or mesh.empty:
        return x
    rules = rules or ShardingRules()
    return jax.lax.with_sharding_constraint(x, rules.sharding(axes, mesh))
