"""Pipeline parallelism: GPipe schedule over the "pipeline" mesh axis.

Layers are stacked on a leading axis (the transformer already stores them
that way for the scan-over-layers) and sharded across pipeline stages;
activations hop stage-to-stage with ``lax.ppermute`` — one neighbor link
per tick, the ICI-friendly pattern.  The whole schedule is a single
``lax.scan`` inside ``shard_map``: every stage runs the same compiled tick
body (SPMD), with warmup/drain bubbles realized as masked compute rather
than control flow, so XLA sees static shapes throughout.

Reference parity note: the torchft reference has NO pipeline parallelism
(SURVEY.md §2.3 — PP named only as a dimension users may bring); this is a
capability the TPU build adds, composing with the fault-tolerant replica
dimension the same way tp/fsdp/sp do (inside the replica group, invisible
to the Manager).

Autodiff gives the reverse schedule for free: ``ppermute`` transposes to
the inverse permutation and the scan reverses, so ``jax.grad`` of the
pipelined loss is itself a (reverse) pipeline.  Memory follows GPipe:
per-tick activations are scan residuals; wrap ``body_fn`` in
``jax.checkpoint`` (cfg.remat) to trade recompute for residency.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

__all__ = ["pipeline_apply", "pipeline_apply_sharded", "pipeline_loss_fn"]


def pipeline_apply(
    layers: Any,
    x: jax.Array,
    body_fn: Callable[[Any, jax.Array], jax.Array],
    *,
    axis_name: str,
    axis_size: int,
    num_microbatches: int,
) -> jax.Array:
    """Local GPipe body — call inside shard_map.

    Args:
        layers: stage-LOCAL stacked layer params, leading axis = layers
            owned by this stage (in global order).
        x: this data-shard's activations [B, S, E]; B must divide into
            ``num_microbatches``.
        body_fn: one layer: (layer_params, [mb, S, E]) -> [mb, S, E].
        axis_name/axis_size: the pipeline mesh axis.
        num_microbatches: M >= axis_size fills the pipe; the bubble
            fraction is (P-1)/(M+P-1).
    """
    P = axis_size
    M = num_microbatches
    B, S, E = x.shape
    assert B % M == 0, f"batch {B} not divisible into {M} microbatches"
    mb = B // M
    x_mb = x.reshape(M, mb, S, E)
    stage = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % P) for i in range(P)]

    def apply_stage(act: jax.Array) -> jax.Array:
        out, _ = jax.lax.scan(lambda a, w: (body_fn(w, a), None), act, layers)
        return out

    def tick(carry, t):
        act, out_buf = carry
        # Stage 0 ingests microbatch t (clipped: past-the-end ticks re-read
        # the last microbatch into stages whose output is never emitted).
        fresh = jax.lax.dynamic_index_in_dim(
            x_mb, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
        )
        act = jnp.where(stage == 0, fresh, act)
        act = apply_stage(act)
        # The last stage emits microbatch t-(P-1) once the pipe is full.
        m_out = t - (P - 1)
        emit = jnp.logical_and(stage == P - 1, m_out >= 0)
        out_buf = jnp.where(
            emit,
            jax.lax.dynamic_update_index_in_dim(
                out_buf, act, jnp.clip(m_out, 0, M - 1), axis=0
            ),
            out_buf,
        )
        # One neighbor hop: stage s's activation moves to s+1 (the wrap to
        # stage 0 is dead — overwritten by the next tick's ingestion).
        act = jax.lax.ppermute(act, axis_name, perm)
        return (act, out_buf), None

    init = (
        jnp.zeros((mb, S, E), x.dtype),
        jnp.zeros((M, mb, S, E), x.dtype),
    )
    (_, out_buf), _ = jax.lax.scan(tick, init, jnp.arange(M + P - 1))
    # Replicate the last stage's buffer everywhere (masked psum rides ICI
    # once; every stage leaves with the full output, which is what the
    # unsharded head/loss downstream expects).
    out = jax.lax.psum(
        jnp.where(stage == P - 1, out_buf, jnp.zeros_like(out_buf)), axis_name
    )
    return out.reshape(B, S, E)


def pipeline_apply_sharded(
    mesh,
    layers: Any,
    x: jax.Array,
    body_fn: Callable[[Any, jax.Array], jax.Array],
    *,
    num_microbatches: int,
    pipe_axis: str = "pipeline",
    batch_axis: Optional[str] = "data",
) -> jax.Array:
    """shard_map wrapper: layers sharded over ``pipe_axis`` (leading axis),
    activations over ``batch_axis`` — PP x DP composition."""
    from jax.sharding import PartitionSpec as P

    from torchft_tpu.ops._shard_map import shard_map

    if batch_axis is not None and (
        batch_axis not in mesh.axis_names or mesh.shape[batch_axis] == 1
    ):
        batch_axis = None
    axis_size = mesh.shape[pipe_axis]
    n_layers = jax.tree.leaves(layers)[0].shape[0]
    assert n_layers % axis_size == 0, (
        f"{n_layers} layers not divisible over {axis_size} pipeline stages"
    )

    layer_specs = jax.tree.map(lambda _: P(pipe_axis), layers)
    act_spec = P(batch_axis, None, None)
    fn = shard_map(
        functools.partial(
            pipeline_apply,
            body_fn=body_fn,
            axis_name=pipe_axis,
            axis_size=axis_size,
            num_microbatches=num_microbatches,
        ),
        mesh,
        in_specs=(layer_specs, act_spec),
        out_specs=act_spec,
        # The output is replicated over the pipeline axis by an explicit
        # masked psum, which the static replication checker cannot see.
        check=False,
    )
    return fn(layers, x)


def pipeline_loss_fn(
    params: Any,
    batch: Any,
    cfg,
    mesh,
    *,
    num_microbatches: int,
    pipe_axis: str = "pipeline",
    batch_axis: Optional[str] = "data",
) -> jax.Array:
    """Next-token CE of the flagship transformer with its layer stack
    pipelined over ``pipe_axis``.

    Embedding and the lm head run outside the pipeline (replicated over the
    pipeline axis; sharded over whatever the params' own shardings say), the
    decoder stack runs as a GPipe schedule.  Dense configs only — the MoE
    aux loss needs the all-stage reduction the dense path doesn't have.
    """
    from torchft_tpu.models.transformer import _layer, lm_head_loss

    assert cfg.moe_experts == 0, "pipeline_loss_fn supports dense configs only"
    tokens = batch["tokens"]
    B, S = tokens.shape

    x = params["embed"].astype(cfg.dtype)[tokens]

    def body(w, a):
        positions = jnp.broadcast_to(
            jnp.arange(S, dtype=jnp.int32), (a.shape[0], S)
        )
        out, _ = _layer(cfg, None, None, a, w, positions)
        return out

    if cfg.remat:
        body = jax.checkpoint(body)

    x = pipeline_apply_sharded(
        mesh,
        params["layers"],
        x,
        body,
        num_microbatches=num_microbatches,
        pipe_axis=pipe_axis,
        batch_axis=batch_axis,
    )

    # Shared lm-head + CE helper (fused on single-chip TPU, plain XLA under
    # the pipeline mesh) so the pipelined loss can never diverge from the
    # dense loss_fn.
    return lm_head_loss(params, x, cfg, batch["targets"], mesh)
