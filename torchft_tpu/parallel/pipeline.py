"""Pipeline parallelism over the "pipeline" mesh axis: GPipe + 1F1B.

Layers are stacked on a leading axis (the transformer already stores them
that way for the scan-over-layers) and sharded across pipeline stages;
activations hop stage-to-stage with ``lax.ppermute`` — one neighbor link
per tick, the ICI-friendly pattern.  Each schedule is a single
``lax.scan`` inside ``shard_map``: every stage runs the same compiled tick
body (SPMD), with warmup/drain bubbles realized as masked compute rather
than control flow, so XLA sees static shapes throughout.

Two schedules:

  - **GPipe** (``pipeline_loss_fn``): forward-only pipeline; autodiff
    gives the reverse schedule for free (``ppermute`` transposes to the
    inverse permutation, the scan reverses).  Per-tick activations are
    scan residuals, so residency grows with the microbatch count M; wrap
    the body in ``jax.checkpoint`` (cfg.remat) to trade recompute for
    residency.
  - **1F1B** (``pipeline_1f1b_value_and_grad``): the loss lives INSIDE
    the pipeline — the last stage computes head+CE and starts the
    backward of a microbatch on the same tick its forward finishes, so
    each tick runs one forward phase and one backward phase
    (one-forward-one-backward steady state).  Each stage keeps only the
    per-layer INPUT activations of its in-flight microbatches (a ring of
    depth min(M, 2P-1)) and recomputes one layer at a time inside the
    backward — the same per-layer recompute GPipe-with-remat pays, so
    FLOPs match while peak residency is bounded by the pipeline depth P,
    not by M (the property GPipe lacks).  Measured on the 8-way virtual
    mesh (8L d512 model, 2 stages): M=16 -> 98 vs 172 MB XLA temp and
    ~21% faster than GPipe+autodiff; M=4 -> 239 vs 284 MB, also ~21%
    faster.

Reference parity note: the torchft reference has NO pipeline parallelism
(SURVEY.md §2.3 — PP named only as a dimension users may bring); this is a
capability the TPU build adds, composing with the fault-tolerant replica
dimension the same way tp/fsdp/sp do (inside the replica group, invisible
to the Manager).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "pipeline_apply",
    "pipeline_apply_sharded",
    "pipeline_loss_fn",
    "pipeline_1f1b_value_and_grad",
]


def pipeline_apply(
    layers: Any,
    x: jax.Array,
    body_fn: Callable[[Any, jax.Array], jax.Array],
    *,
    axis_name: str,
    axis_size: int,
    num_microbatches: int,
) -> jax.Array:
    """Local GPipe body — call inside shard_map.

    Args:
        layers: stage-LOCAL stacked layer params, leading axis = layers
            owned by this stage (in global order).
        x: this data-shard's activations [B, S, E]; B must divide into
            ``num_microbatches``.
        body_fn: one layer: (layer_params, [mb, S, E]) -> [mb, S, E].
        axis_name/axis_size: the pipeline mesh axis.
        num_microbatches: M >= axis_size fills the pipe; the bubble
            fraction is (P-1)/(M+P-1).
    """
    P = axis_size
    M = num_microbatches
    B, S, E = x.shape
    assert B % M == 0, f"batch {B} not divisible into {M} microbatches"
    mb = B // M
    x_mb = x.reshape(M, mb, S, E)
    stage = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % P) for i in range(P)]

    def apply_stage(act: jax.Array) -> jax.Array:
        out, _ = jax.lax.scan(lambda a, w: (body_fn(w, a), None), act, layers)
        return out

    def tick(carry, t):
        act, out_buf = carry
        # Stage 0 ingests microbatch t (clipped: past-the-end ticks re-read
        # the last microbatch into stages whose output is never emitted).
        fresh = jax.lax.dynamic_index_in_dim(
            x_mb, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
        )
        act = jnp.where(stage == 0, fresh, act)
        act = apply_stage(act)
        # The last stage emits microbatch t-(P-1) once the pipe is full.
        m_out = t - (P - 1)
        emit = jnp.logical_and(stage == P - 1, m_out >= 0)
        out_buf = jnp.where(
            emit,
            jax.lax.dynamic_update_index_in_dim(
                out_buf, act, jnp.clip(m_out, 0, M - 1), axis=0
            ),
            out_buf,
        )
        # One neighbor hop: stage s's activation moves to s+1 (the wrap to
        # stage 0 is dead — overwritten by the next tick's ingestion).
        act = jax.lax.ppermute(act, axis_name, perm)
        return (act, out_buf), None

    init = (
        jnp.zeros((mb, S, E), x.dtype),
        jnp.zeros((M, mb, S, E), x.dtype),
    )
    (_, out_buf), _ = jax.lax.scan(tick, init, jnp.arange(M + P - 1))
    # Replicate the last stage's buffer everywhere (masked psum rides ICI
    # once; every stage leaves with the full output, which is what the
    # unsharded head/loss downstream expects).
    out = jax.lax.psum(
        jnp.where(stage == P - 1, out_buf, jnp.zeros_like(out_buf)), axis_name
    )
    return out.reshape(B, S, E)


def pipeline_apply_sharded(
    mesh,
    layers: Any,
    x: jax.Array,
    body_fn: Callable[[Any, jax.Array], jax.Array],
    *,
    num_microbatches: int,
    pipe_axis: str = "pipeline",
    batch_axis: Optional[str] = "data",
) -> jax.Array:
    """shard_map wrapper: layers sharded over ``pipe_axis`` (leading axis),
    activations over ``batch_axis`` — PP x DP composition."""
    from jax.sharding import PartitionSpec as P

    from torchft_tpu.ops._shard_map import shard_map

    if batch_axis is not None and (
        batch_axis not in mesh.axis_names or mesh.shape[batch_axis] == 1
    ):
        batch_axis = None
    axis_size = mesh.shape[pipe_axis]
    n_layers = jax.tree.leaves(layers)[0].shape[0]
    assert n_layers % axis_size == 0, (
        f"{n_layers} layers not divisible over {axis_size} pipeline stages"
    )

    layer_specs = jax.tree.map(lambda _: P(pipe_axis), layers)
    act_spec = P(batch_axis, None, None)
    fn = shard_map(
        functools.partial(
            pipeline_apply,
            body_fn=body_fn,
            axis_name=pipe_axis,
            axis_size=axis_size,
            num_microbatches=num_microbatches,
        ),
        mesh,
        in_specs=(layer_specs, act_spec),
        out_specs=act_spec,
        # The output is replicated over the pipeline axis by an explicit
        # masked psum, which the static replication checker cannot see.
        check=False,
    )
    return fn(layers, x)


def _layer_body(cfg, w, a):
    """One decoder layer on a [mb, S, E] activation — the single layer
    invocation both pipeline schedules share, so their numerics cannot
    diverge at the layer-contract level."""
    from torchft_tpu.models.transformer import _layer

    S = a.shape[1]
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (a.shape[0], S))
    out, _ = _layer(cfg, None, None, a, w, positions)
    return out


def pipeline_loss_fn(
    params: Any,
    batch: Any,
    cfg,
    mesh,
    *,
    num_microbatches: int,
    pipe_axis: str = "pipeline",
    batch_axis: Optional[str] = "data",
) -> jax.Array:
    """Next-token CE of the flagship transformer with its layer stack
    pipelined over ``pipe_axis``.

    Embedding and the lm head run outside the pipeline (replicated over the
    pipeline axis; sharded over whatever the params' own shardings say), the
    decoder stack runs as a GPipe schedule.  Dense configs only — the MoE
    aux loss needs the all-stage reduction the dense path doesn't have.
    """
    from torchft_tpu.models.transformer import lm_head_loss

    assert cfg.moe_experts == 0, "pipeline_loss_fn supports dense configs only"
    tokens = batch["tokens"]
    B, S = tokens.shape

    x = params["embed"].astype(cfg.dtype)[tokens]
    body = functools.partial(_layer_body, cfg)

    if cfg.remat:
        body = jax.checkpoint(body)

    x = pipeline_apply_sharded(
        mesh,
        params["layers"],
        x,
        body,
        num_microbatches=num_microbatches,
        pipe_axis=pipe_axis,
        batch_axis=batch_axis,
    )

    # Shared lm-head + CE helper (fused on single-chip TPU, plain XLA under
    # the pipeline mesh) so the pipelined loss can never diverge from the
    # dense loss_fn.
    return lm_head_loss(params, x, cfg, batch["targets"], mesh)


# ---------------------------------------------------------------------------
# 1F1B schedule
# ---------------------------------------------------------------------------


def _pipeline_1f1b_local(
    stage_params: Any,
    other_params: Any,
    tokens: jax.Array,
    targets: jax.Array,
    *,
    cfg,
    axis_name: str,
    axis_size: int,
    num_microbatches: int,
    batch_axis: Optional[str],
) -> Tuple[jax.Array, Any, Any]:
    """Local 1F1B body — call inside shard_map.

    Schedule: forward of microbatch m runs at stage s during the forward
    phase of tick t = s + m; the last stage computes head+loss and starts
    the backward the SAME tick; backward of m reaches stage s during the
    backward phase of tick t = m + 2(P-1) - s.  Each stage is therefore
    one-forward-one-backward in steady state and holds at most
    min(M, 2(P-1-s)+1) microbatches in flight — the ring depth R below.

    Memory/compute trade: the forward phase collects each LAYER's input
    activation (ring slot = [L_local, mb, S, E]); the backward phase
    walks the stage's layers in reverse, recomputing one layer inside its
    vjp at a time — exactly the per-layer recompute GPipe-with-remat
    pays, so total FLOPs match GPipe-remat while residency is bounded by
    the pipe depth (R slots) instead of the microbatch count.  Bubble
    phases are skipped with lax.cond (no collectives inside), not
    masked.

    Returns (loss, d_stage_params, d_other_params); gradients for
    embed/head params are nonzero only on the stages that own those
    computations and are psum-replicated over the pipeline axis.
    """
    from torchft_tpu.models.transformer import lm_head_loss

    P_ = axis_size
    M = num_microbatches
    R = min(M, 2 * P_ - 1)
    B, S = tokens.shape
    assert B % M == 0, f"batch {B} not divisible into {M} microbatches"
    mb = B // M
    tokens_mb = tokens.reshape(M, mb, S)
    targets_mb = targets.reshape(M, mb, S)
    stage = jax.lax.axis_index(axis_name)
    fwd_perm = [(i, (i + 1) % P_) for i in range(P_)]
    bwd_perm = [((i + 1) % P_, i) for i in range(P_)]

    def embed_fwd(embed, toks):
        return embed.astype(cfg.dtype)[toks]

    one_layer = functools.partial(_layer_body, cfg)

    def stage_fwd(layers, a):
        """-> (out, per-layer input activations [L_local, mb, S, E])."""
        out, inputs = jax.lax.scan(
            lambda a, w: (one_layer(w, a), a), a, layers
        )
        return out, inputs

    def stage_bwd(layers, inputs, cot):
        """Reverse walk: per-layer vjp from the stored layer input — one
        layer's residuals live at a time (the GPipe-remat discipline)."""

        def back(c, xs):
            w, a_in = xs
            _, lvjp = jax.vjp(one_layer, w, a_in)
            dw, da = lvjp(c)
            return da.astype(c.dtype), dw

        da, dws = jax.lax.scan(back, cot, (layers, inputs), reverse=True)
        return dws, da

    def head_loss(head, a, tgt):
        # The shared lm-head + CE helper (fused kernel on a single TPU
        # device, plain XLA otherwise) so the 1F1B loss can never diverge
        # from the dense loss_fn / GPipe path.
        return lm_head_loss(head, a, cfg, tgt)

    head_params = {
        "final_norm": other_params["final_norm"],
        "lm_head": other_params["lm_head"],
    }
    embed = other_params["embed"]
    act0 = jnp.zeros((mb, S, cfg.d_model), cfg.dtype)
    l_local = jax.tree.leaves(stage_params)[0].shape[0]
    inputs0 = jnp.zeros((l_local,) + act0.shape, act0.dtype)

    def tick(carry, t):
        act_in, cot_in, ring, loss_acc, dlayers, dhead, dembed = carry

        # ---- forward phase -------------------------------------------------
        m_f = t - stage
        valid_f = jnp.logical_and(m_f >= 0, m_f < M)
        m_f_c = jnp.clip(m_f, 0, M - 1)
        toks_f = jax.lax.dynamic_index_in_dim(tokens_mb, m_f_c, 0, keepdims=False)
        a_in = jax.lax.cond(
            stage == 0, lambda: embed_fwd(embed, toks_f), lambda: act_in
        )
        out, inputs = jax.lax.cond(
            valid_f,
            lambda: stage_fwd(stage_params, a_in),
            lambda: (jnp.zeros_like(a_in), inputs0),
        )
        # Stash this microbatch's per-layer inputs for the backward phase.
        slot_f = m_f_c % R
        cur = jax.lax.dynamic_index_in_dim(ring, slot_f, 0, keepdims=False)
        ring = jax.lax.dynamic_update_index_in_dim(
            ring, jnp.where(valid_f, inputs, cur), slot_f, axis=0
        )

        # Last stage: head + loss + the cotangent seeding this very tick's
        # backward phase (t_b(P-1, m) == t_f(P-1, m)).
        is_last = stage == P_ - 1
        emit = jnp.logical_and(is_last, valid_f)
        tgt_f = jax.lax.dynamic_index_in_dim(targets_mb, m_f_c, 0, keepdims=False)

        def do_head():
            loss_m, hvjp = jax.vjp(head_loss, head_params, out, tgt_f)
            dh_m, dact, _ = hvjp(jnp.ones((), loss_m.dtype))
            # Accumulate INSIDE the cond: dhead is O(vocab*d_model); adding
            # cond-produced zeros every tick on every stage would be real
            # HBM traffic.
            return (
                loss_acc + loss_m / M,
                jax.tree.map(lambda a, g: a + g / M, dhead, dh_m),
                dact,
            )

        loss_acc, dhead, dact_head = jax.lax.cond(
            emit,
            do_head,
            lambda: (loss_acc, dhead, jnp.zeros_like(out)),
        )

        act_send = jax.lax.ppermute(out, axis_name, fwd_perm)

        # ---- backward phase ------------------------------------------------
        m_b = t - 2 * (P_ - 1) + stage
        valid_b = jnp.logical_and(m_b >= 0, m_b < M)
        m_b_c = jnp.clip(m_b, 0, M - 1)
        cot = jnp.where(is_last, dact_head / M, cot_in).astype(cfg.dtype)

        def do_bwd():
            inputs_b = jax.lax.dynamic_index_in_dim(
                ring, m_b_c % R, 0, keepdims=False
            )
            return stage_bwd(stage_params, inputs_b, cot)

        dw_m, da_m = jax.lax.cond(
            valid_b,
            do_bwd,
            lambda: (
                jax.tree.map(jnp.zeros_like, stage_params),
                jnp.zeros_like(act0),
            ),
        )
        dlayers = jax.tree.map(lambda a, g: a + g, dlayers, dw_m)
        # Stage 0 backprops the embedding gather for this microbatch.
        take_e = jnp.logical_and(stage == 0, valid_b)
        toks_b = jax.lax.dynamic_index_in_dim(tokens_mb, m_b_c, 0, keepdims=False)

        def do_embed():
            _, evjp = jax.vjp(lambda e: embed_fwd(e, toks_b), embed)
            (g,) = evjp(da_m)
            return dembed + g

        dembed = jax.lax.cond(take_e, do_embed, lambda: dembed)

        cot_send = jax.lax.ppermute(da_m, axis_name, bwd_perm)

        return (act_send, cot_send, ring, loss_acc, dlayers, dhead, dembed), None

    init = (
        act0,
        jnp.zeros_like(act0),
        jnp.zeros((R,) + inputs0.shape, act0.dtype),
        jnp.zeros((), jnp.float32),
        jax.tree.map(jnp.zeros_like, stage_params),
        jax.tree.map(jnp.zeros_like, head_params),
        jnp.zeros_like(embed),
    )
    T = M + 2 * (P_ - 1)
    (_, _, _, loss_acc, dlayers, dhead, dembed), _ = jax.lax.scan(
        tick, init, jnp.arange(T)
    )

    # Loss and the embed/head grads live on single stages; replicate.
    loss = jax.lax.psum(loss_acc, axis_name)
    dhead = jax.tree.map(lambda g: jax.lax.psum(g, axis_name), dhead)
    dembed = jax.lax.psum(dembed, axis_name)
    if batch_axis is not None:
        loss = jax.lax.pmean(loss, batch_axis)
        dlayers = jax.tree.map(lambda g: jax.lax.pmean(g, batch_axis), dlayers)
        dhead = jax.tree.map(lambda g: jax.lax.pmean(g, batch_axis), dhead)
        dembed = jax.lax.pmean(dembed, batch_axis)
    d_other = {
        "embed": dembed,
        "final_norm": dhead["final_norm"],
        "lm_head": dhead["lm_head"],
    }
    return loss, dlayers, d_other


def pipeline_1f1b_value_and_grad(
    params: Any,
    batch: Any,
    cfg,
    mesh,
    *,
    num_microbatches: int,
    pipe_axis: str = "pipeline",
    batch_axis: Optional[str] = "data",
) -> Tuple[jax.Array, Any]:
    """(loss, grads) of the flagship transformer under a 1F1B pipeline
    schedule — a drop-in for ``jax.value_and_grad(pipeline_loss_fn)``
    (plug into ``TrainStep(value_and_grad_fn=...)``).

    Unlike the GPipe path, the loss and the full backward are computed
    INSIDE the pipeline, so activation residency is bounded by the
    pipeline depth (a ring of min(M, 2P-1) per-layer input-activation
    sets per stage) instead of growing with the microbatch count; the
    backward recomputes one layer at a time from its stored input, the
    same recompute GPipe-with-remat pays.  Dense configs only.
    """
    from jax.sharding import PartitionSpec as P

    from torchft_tpu.ops._shard_map import shard_map

    assert cfg.moe_experts == 0, "1F1B pipeline supports dense configs only"
    if batch_axis is not None and (
        batch_axis not in mesh.axis_names or mesh.shape[batch_axis] == 1
    ):
        batch_axis = None
    axis_size = mesh.shape[pipe_axis]
    n_layers = jax.tree.leaves(params["layers"])[0].shape[0]
    assert n_layers % axis_size == 0, (
        f"{n_layers} layers not divisible over {axis_size} pipeline stages"
    )

    other = {k: v for k, v in params.items() if k != "layers"}
    layer_specs = jax.tree.map(lambda _: P(pipe_axis), params["layers"])
    other_specs = jax.tree.map(lambda _: P(), other)
    tok_spec = P(batch_axis, None)

    fn = shard_map(
        functools.partial(
            _pipeline_1f1b_local,
            cfg=cfg,
            axis_name=pipe_axis,
            axis_size=axis_size,
            num_microbatches=num_microbatches,
            batch_axis=batch_axis,
        ),
        mesh,
        in_specs=(layer_specs, other_specs, tok_spec, tok_spec),
        out_specs=(P(), layer_specs, other_specs),
        # loss/grads are replicated by explicit psum/pmean, which the
        # static replication checker cannot see.
        check=False,
    )
    loss, dlayers, d_other = fn(
        params["layers"], other, batch["tokens"], batch["targets"]
    )
    grads = dict(d_other)
    grads["layers"] = dlayers
    return loss, grads
