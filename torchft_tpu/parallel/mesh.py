"""FTMesh: static intra-group device mesh × dynamic replica dimension.

Reference parity: torchft/device_mesh.py.  The reference builds a torch
DeviceMesh with the replicate dim *removed* (world-size-1 lie) and splices a
ManagedProcessGroup back in so FSDP sees a dynamic replica dimension
(torchft/device_mesh.py:290-323, 49-251).  On TPU the same split is natural:

  - the *intra-group* axes (data / fsdp / tensor / sequence / expert) form a
    real ``jax.sharding.Mesh`` over the slice's chips — static, compiled
    into the pjit program, collectives ride ICI;
  - the *replica* axis is not an XLA mesh axis at all: its size comes from
    the quorum each step (Manager.num_participants) and its collectives are
    the Manager's fault-tolerant host-level allreduce over DCN.

``FTMesh`` is the object that holds both and answers the questions the
reference answers through ManagedDeviceMesh: axis sizes (with the dynamic
replica dim, torchft/device_mesh.py:158-173), ranks/coordinates, and which
collective to use per axis.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchft_tpu.parallel.sharding import ShardingRules

# Axis names understood by the default sharding rules.
INTRA_GROUP_AXES = ("data", "fsdp", "tensor", "sequence", "expert", "pipeline")
REPLICA_AXIS = "replica"


@dataclasses.dataclass
class FTMesh:
    """A static local mesh plus the managed (dynamic) replica dimension."""

    mesh: Mesh
    manager: Optional[object] = None  # torchft_tpu.manager.Manager
    rules: ShardingRules = dataclasses.field(default_factory=ShardingRules)

    # -- axis queries (ManagedDeviceMesh parity) ----------------------------

    def size(self, axis: Optional[str] = None) -> int:
        """Total size; the replica axis reports the *current quorum* size
        (the dynamic lie, torchft/device_mesh.py:158-173)."""
        if axis is None:
            return int(np.prod([self.size(a) for a in self.axis_names]))
        if axis == REPLICA_AXIS:
            if self.manager is None:
                return 1
            return max(1, self.manager.num_participants())
        return int(self.mesh.shape[axis])

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return (REPLICA_AXIS,) + tuple(self.mesh.axis_names)

    def replica_rank(self) -> Optional[int]:
        if self.manager is None:
            return 0
        return self.manager.participating_rank()

    # -- sharding helpers ----------------------------------------------------

    def sharding(self, *logical_axes: Optional[str]) -> NamedSharding:
        return self.rules.sharding(tuple(logical_axes), self.mesh)

    def spec(self, *logical_axes: Optional[str]) -> P:
        return self.rules.spec(tuple(logical_axes), self.mesh)

    def shard_params(self, params, axes_tree) -> object:
        """Places a parameter pytree onto the mesh per its logical axes."""
        return jax.tree.map(
            lambda p, axes: jax.device_put(p, self.rules.sharding(axes, self.mesh)),
            params,
            axes_tree,
        )


def ft_init_mesh(
    axis_sizes: Dict[str, int],
    manager: Optional[object] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    rules: Optional[ShardingRules] = None,
) -> FTMesh:
    """Builds an FTMesh from {axis: size} over the local devices.

    The "replica" axis, if present in axis_sizes, is ignored for device
    placement (it is the cross-group dimension handled by the Manager) —
    mirroring ft_init_device_mesh's replicate-dim removal
    (torchft/device_mesh.py:290-323).
    """
    sizes = {k: v for k, v in axis_sizes.items() if k != REPLICA_AXIS}
    for name in sizes:
        if name not in INTRA_GROUP_AXES:
            raise ValueError(f"unknown mesh axis {name!r}; use {INTRA_GROUP_AXES}")
    devices = list(devices if devices is not None else jax.devices())
    n = int(np.prod(list(sizes.values()))) if sizes else 1
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(tuple(sizes.values()) or (1,))
    mesh = Mesh(arr, tuple(sizes.keys()) or ("data",))
    return FTMesh(
        mesh=mesh, manager=manager, rules=rules or ShardingRules()
    )
