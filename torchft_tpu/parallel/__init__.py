"""Parallelism layer: mesh composition, logical sharding rules, train step.

TPU-first equivalent of the reference's mesh/parallelism surface
(torchft/device_mesh.py, torchft/process_group.py): intra-group parallelism
(data/fsdp/tensor/sequence/expert) is a static `jax.sharding.Mesh` compiled
into the pjit program over ICI; the fault-tolerant replica dimension is
dynamic and lives at the host layer through the Manager (the analogue of
ManagedDeviceMesh's "replicate dim removed from the torch mesh",
torchft/device_mesh.py:290-323).
"""

from torchft_tpu.parallel.mesh import FTMesh, ft_init_mesh
from torchft_tpu.parallel.sharding import ShardingRules, logical_sharding
from torchft_tpu.parallel.trainer import TrainStep

__all__ = [
    "FTMesh",
    "ft_init_mesh",
    "ShardingRules",
    "logical_sharding",
    "TrainStep",
]
