"""TrainStep: the compiled training step over an FTMesh.

One object owns the pjit-compiled compute for a step:

  - ``full_step``: loss -> grad -> optax update, one XLA program (used when
    there is no cross-group dimension, and by the multichip dry run);
  - ``grads``/``apply``: the split form for fault-tolerant training — the
    gradient program ends at (loss, grads) so the Manager's host-level
    replica allreduce (DCN) can run between compute and update, exactly
    where the reference's DDP comm hook sits in the backward
    (torchft/ddp.py:47-71, torchft/manager.py:262-323).

All intra-group parallelism (data/fsdp/tensor/sequence) is carried by the
arrays' shardings + the model's with_sharding_constraint annotations; XLA
inserts the ICI collectives.  Donation keeps params/opt_state in place in
HBM across steps.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from typing import Any, Callable, Optional

import jax

from torchft_tpu.parallel.mesh import FTMesh

logger = logging.getLogger(__name__)

# Fraction of the remaining HBM the speculative apply may claim; the rest
# is headroom for XLA temporaries inside the update program.
_SPECULATION_HEADROOM = 0.9


def tree_device_bytes(tree: Any) -> int:
    """PER-DEVICE resident bytes of a pytree of (possibly sharded) arrays.

    A sharded leaf costs each device only its shard; a replicated leaf
    costs every device the full array.  Using global sizes here would
    overestimate the speculative-apply cost by the shard factor on
    FSDP-style meshes and wrongly disable the overlap."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", None)
        if itemsize is None:
            continue
        shape = getattr(leaf, "shape", ())
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None:
            try:
                shape = sharding.shard_shape(shape)
            except Exception:  # noqa: BLE001
                pass
        count = 1
        for dim in shape:
            count *= int(dim)
        total += count * int(itemsize)
    return total


def speculation_fits(extra_bytes: int, device: Any) -> Optional[bool]:
    """Whether an extra `extra_bytes` fits the device's free HBM.

    Budgets against the allocator's PEAK (when reported), not the
    current bytes_in_use: callers decide after a full step has executed,
    and the peak is what proves the step's activation/workspace
    footprint coexisted with the resident state.  Returns None when the
    runtime exposes no memory statistics (CPU devices; some TPU
    tunnels) — the caller decides the default."""
    try:
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001
        return None
    if not stats:
        return None
    limit = stats.get("bytes_limit")
    in_use = stats.get("bytes_in_use")
    if limit is None or in_use is None:
        return None
    peak = stats.get("peak_bytes_in_use")
    high_water = max(in_use, peak) if peak is not None else in_use
    return extra_bytes <= (limit - high_water) * _SPECULATION_HEADROOM


@dataclasses.dataclass
class TrainStep:
    """Compiled train step.

    Args:
        ftmesh: mesh + rules (+ optional manager for the replica dim).
        tx: optax GradientTransformation.
        loss_fn: (params, batch) -> scalar loss (model closure).
        bucket_bytes: DCN bucket size for the cross-group averaging path.
        overlap_commit: hide the commit-vote RPC behind a speculatively
            dispatched update (see ft_step).  MEMORY TRADE: the speculative
            apply cannot donate its inputs, so params+opt_state residency
            transiently doubles during the update.  Default None = run the
            FIRST ft_step non-overlapped, then decide from the device's
            post-step memory stats (allocator peak, so the measurement
            includes the step's activation/workspace footprint): overlap
            iff an extra params+opt_state copy fits above the observed
            peak with 10% headroom; when the runtime exposes no
            memory statistics the overlap is kept (its failure mode — an
            allocator OOM — is loud, while silently serializing the vote
            would be an invisible perf cliff).  Pass True/False to force.
    """

    ftmesh: FTMesh
    tx: Any
    # Exactly one of loss_fn / value_and_grad_fn must be provided:
    # value_and_grad_fn replaces jax.value_and_grad(loss_fn) for losses
    # that compute their own backward, e.g. the 1F1B pipeline schedule
    # (parallel.pipeline.pipeline_1f1b_value_and_grad).
    loss_fn: Optional[Callable[[Any, Any], jax.Array]] = None
    bucket_bytes: int = 25 << 20
    overlap_commit: Optional[bool] = None
    value_and_grad_fn: Optional[Callable[[Any, Any], Any]] = None

    def __post_init__(self) -> None:
        if (self.loss_fn is None) == (self.value_and_grad_fn is None):
            raise ValueError(
                "TrainStep needs exactly one of loss_fn / value_and_grad_fn"
            )
        mesh = self.ftmesh.mesh

        def value_and_grad(params, batch):
            if self.value_and_grad_fn is not None:
                return self.value_and_grad_fn(params, batch)
            return jax.value_and_grad(self.loss_fn)(params, batch)

        def apply(params, opt_state, grads):
            import optax

            updates, opt_state = self.tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        def full(params, opt_state, batch):
            loss, grads = value_and_grad(params, batch)
            params, opt_state = apply(params, opt_state, grads)
            return params, opt_state, loss

        del mesh  # shardings are explicit NamedShardings; no ambient mesh needed
        self._grads_fn = jax.jit(value_and_grad)
        self._apply_fn = jax.jit(apply, donate_argnums=(0, 1))
        # Speculative variant for the overlapped commit path: the old
        # params/opt_state must survive a failed vote, so nothing is donated
        # (transiently doubles params+opt residency — disable overlap_commit
        # if that doesn't fit).
        self._apply_spec_fn = jax.jit(apply)
        self._full_fn = jax.jit(full, donate_argnums=(0, 1))
        self._averager = None  # lazy: the manager may be attached post-init
        self._overlap_resolved: Optional[bool] = self.overlap_commit

    # -- pure compute --------------------------------------------------------

    def init_opt_state(self, params: Any) -> Any:
        return self.tx.init(params)

    def full_step(self, params, opt_state, batch):
        """Fused loss+grad+update; no cross-group averaging."""
        return self._full_fn(params, opt_state, batch)

    def grads(self, params, batch):
        return self._grads_fn(params, batch)

    def apply(self, params, opt_state, grads):
        return self._apply_fn(params, opt_state, grads)

    # -- fault-tolerant step -------------------------------------------------

    def _resolve_overlap(self, params: Any, opt_state: Any) -> None:
        """Decide overlap_commit from post-step device memory stats."""
        extra = tree_device_bytes(params) + tree_device_bytes(opt_state)
        device = None
        for leaf in jax.tree.leaves(params):
            devs = getattr(leaf, "devices", None)
            if callable(devs):
                ds = devs()
                if ds:
                    device = next(iter(ds))
                    break
        fits = speculation_fits(extra, device) if device is not None else None
        self._overlap_resolved = True if fits is None else fits
        logger.info(
            "overlap_commit auto: %s (extra %.2f GB for the speculative "
            "apply, post-step device stats %s)",
            self._overlap_resolved,
            extra / 1e9,
            "unavailable" if fits is None else "available",
        )

    def ft_step(self, params, opt_state, batch):
        """One FT step: local grads -> Manager DCN allreduce -> commit-gated
        update.  Returns (params, opt_state, loss, committed).

        Requires ftmesh.manager.  The caller must have called
        manager.start_quorum() (the Optimizer wrapper's step_begin does).

        State-ownership note: a HEALED step delivers weights through the
        Manager's load_state_dict callback, not through this function's
        return value — the (params, opt_state) returned on a step where the
        manager healed are computed from the pre-heal inputs.  Loops that
        enable healing should hold state behind the Manager's state-dict
        callbacks and re-read it after such a step (the Optimizer wrapper's
        pattern; see examples/train_hsdp.py), or run ft_step only on
        up-to-date groups.

        The commit vote (a host RPC barrier across the group's local ranks,
        reference torchft/manager.py:587-663) is hidden behind device work:
        the update is dispatched *speculatively* before the vote — XLA async
        dispatch returns immediately and the device crunches the apply while
        the host blocks in ``should_commit`` — and the new state is adopted
        only when the vote passes.  The reference hides its quorum under
        backward the same way (torchft/manager.py:420); votes are rare-fail,
        so speculation wastes work only on genuinely broken steps.
        """
        manager = self.ftmesh.manager
        assert manager is not None, "ft_step requires an FTMesh with a Manager"
        from torchft_tpu.ddp import GradientAverager

        if self._averager is None or self._averager.manager is not manager:
            self._averager = GradientAverager(manager, self.bucket_bytes)

        # overlap_commit=None: the FIRST step runs non-overlapped, and the
        # decision is made from the device's memory stats AFTER it — deciding
        # before any step executed would read a bytes_in_use that excludes
        # the step's activation/workspace footprint and could green-light a
        # speculative apply that OOMs; after one full step the allocator's
        # peak covers compute + resident state.
        resolve_after = self._overlap_resolved is None

        loss, grads = self._grads_fn(params, batch)
        grads = self._averager.allreduce(grads)
        if self._overlap_resolved:
            new_params, new_opt = self._apply_spec_fn(params, opt_state, grads)
            if manager.should_commit():
                return new_params, new_opt, loss, True
            return params, opt_state, loss, False
        committed = manager.should_commit()
        if committed:
            params, opt_state = self._apply_fn(params, opt_state, grads)
        # Only a COMMITTED step resolves the decision: an aborted vote means
        # _apply_fn never ran, so the allocator peak would exclude the
        # optimizer-apply footprint the budget must cover.
        if resolve_after and committed:
            jax.block_until_ready(jax.tree.leaves(params))
            self._resolve_overlap(params, opt_state)
        return params, opt_state, loss, committed
