"""Crash-isolated collective: the communicator runs in a spawned subprocess.

Reference parity: ProcessGroupBaby* (torchft/process_group.py:1117-1745) and
_MonitoredPipe (torchft/multiprocessing.py:10-32).  The reference's single
biggest robustness layer: a hard wedge, crash, or poisoned thread inside
communication code must not take down the training process.  The real
collective (e.g. TCPCollective) lives in a child process; commands travel
over monitored pipes; results complete parent-side futures via a reader
thread.  If the child dies or wedges, the parent latches an error and the
next ``configure()`` (i.e. the next quorum) respawns a fresh child.

TPU adaptation: tensors are host numpy buffers by the time they reach the
replica-dimension collective (device work stays inside the pjit program), so
arrays cross the process boundary by pickling.  That is one extra memcpy on
a path that is DCN-bandwidth-bound — the price of crash isolation, exactly
the trade the reference makes with its shared-memory queues.
"""

from __future__ import annotations

import multiprocessing
import threading
from concurrent.futures import Future
from typing import Callable, Dict, Optional, Sequence

import numpy as np

from torchft_tpu.collectives import Collective, DummyCollective, TCPCollective, Work
from torchft_tpu.futures import completed_future, failed_future, future_timeout

__all__ = ["MonitoredPipe", "BabyCollective", "BabyTCPCollective"]


class MonitoredPipe:
    """Pipe wrapper: ``recv(timeout)`` via poll; exceptions sent as payloads
    re-raise at the receiver (reference: _MonitoredPipe,
    torchft/multiprocessing.py:10-32)."""

    def __init__(self, pipe) -> None:
        self._pipe = pipe
        self._send_lock = threading.Lock()

    def send(self, obj) -> None:
        with self._send_lock:
            self._pipe.send(obj)

    def recv(self, timeout: Optional[float] = None):
        if timeout is not None and not self._pipe.poll(timeout):
            raise TimeoutError(f"pipe recv timed out after {timeout}s")
        out = self._pipe.recv()
        if isinstance(out, Exception):
            raise out
        return out

    def close(self) -> None:
        # Serialized against send(): Connection._send captures the raw fd
        # once per call, so closing mid-send would free the fd number for
        # reuse while the sender keeps writing to it — onto whatever pipe
        # grabs the number next.  (recv has the same hazard; reader threads
        # therefore own the close of pipes they block on — see
        # BabyCollective._teardown_child.)
        with self._send_lock:
            self._pipe.close()

    def closed(self) -> bool:
        return self._pipe.closed


def _mp_context():
    """Child processes come from a forkserver where available: each child is
    a fork of a small preloaded server process rather than a cold interpreter
    (children do still replay the parent's ``__main__`` as ``__mp_main__``,
    so a heavyweight entrypoint should keep its imports under ``if __name__``
    or extend the preload list).  Measured under the test suite this cuts a
    2-rank configure round from tens of seconds to well under one.  Unlike
    plain fork it is safe with the parent's native/reader threads — the
    server is exec'd fresh.  The reference must use spawn for CUDA re-init
    (torchft/process_group.py:1117); nothing in the TPU child touches a
    device, so the cheap method is correct.
    """
    try:
        ctx = multiprocessing.get_context("forkserver")
        ctx.set_forkserver_preload(["torchft_tpu.baby"])
        return ctx
    except (ValueError, AttributeError):  # platform without forkserver
        return multiprocessing.get_context("spawn")


def _tcp_collective_factory(kwargs: dict) -> Collective:
    return TCPCollective(**kwargs)


def _dummy_collective_factory(kwargs: dict) -> Collective:
    return DummyCollective(**kwargs)


def _send_result(results: MonitoredPipe, op_id: int, exc, value) -> None:
    try:
        results.send(("op", op_id, exc, value))
    except (OSError, BrokenPipeError, ValueError):
        pass  # parent is gone; nothing to report to
    except Exception as send_exc:  # noqa: BLE001  (unpicklable exc OR value)
        try:
            results.send(
                (
                    "op",
                    op_id,
                    RuntimeError(
                        f"result not picklable ({send_exc!r}); "
                        f"original exc={exc!r}"
                    ),
                    None,
                )
            )
        except Exception:  # noqa: BLE001
            pass


def _child_main(factory, factory_kwargs: dict, cmd_pipe, result_pipe) -> None:
    """Child process loop: owns the real collective.  Ops are *submitted* to
    the inner collective and their completions shipped back as they land (a
    done-callback on each Work), so overlapping parent ops — e.g. a ring
    allreduce concurrent with p2p sends — stay concurrent through the process
    boundary instead of serializing in submission order (reference: _worker
    issue/wait split, torchft/process_group.py:1224-1396)."""
    inner: Collective = factory(factory_kwargs)
    cmds = MonitoredPipe(cmd_pipe)
    results = MonitoredPipe(result_pipe)
    try:
        while True:
            msg = cmds.recv()
            kind = msg[0]
            if kind == "shutdown":
                inner.shutdown()
                return
            if kind == "configure":
                _, store_addr, rank, world_size = msg
                try:
                    inner.configure(store_addr, rank, world_size)
                    results.send(("configured", None))
                except Exception as e:  # noqa: BLE001
                    results.send(("configured", e))
                continue
            if kind == "op":
                _, op_id, name, args, kwargs = msg

                def _complete(fut, op_id=op_id) -> None:
                    exc = fut.exception()
                    if exc is not None:
                        _send_result(results, op_id, exc, None)
                    else:
                        _send_result(results, op_id, None, fut.result())

                try:
                    work: Work = getattr(inner, name)(*args, **kwargs)
                except Exception as e:  # noqa: BLE001
                    _send_result(results, op_id, e, None)
                    continue
                # Completion fires on the inner collective's worker thread;
                # MonitoredPipe.send is lock-serialized, so concurrent
                # completions interleave safely on the one result pipe.
                work.add_done_callback(_complete)
                continue
            if kind == "abort":
                inner.abort()
                results.send(("aborted", None))
                continue
    except (EOFError, OSError, KeyboardInterrupt):
        # Parent went away (or is tearing us down): exit quietly.
        try:
            inner.shutdown()
        except Exception:  # noqa: BLE001
            pass


class BabyCollective(Collective):
    """Runs an inner collective in a spawned subprocess so that a crash or
    hard wedge in communication code cannot take down the train process
    (reference: ProcessGroupBaby, torchft/process_group.py:1117-1745)."""

    def __init__(
        self,
        factory: Callable[[dict], Collective] = _tcp_collective_factory,
        factory_kwargs: Optional[dict] = None,
        timeout: float = 60.0,
    ) -> None:
        self._factory = factory
        self._factory_kwargs = factory_kwargs or {}
        self._timeout = timeout
        self._lock = threading.Lock()
        self._proc: Optional[multiprocessing.Process] = None
        self._cmds: Optional[MonitoredPipe] = None
        self._results: Optional[MonitoredPipe] = None
        self._reader: Optional[threading.Thread] = None
        self._futures: Dict[int, Future] = {}
        self._next_op = 0
        self._rank = 0
        self._world_size = 1
        self._error: Optional[Exception] = None

    # -- lifecycle ----------------------------------------------------------

    def configure(self, store_addr: str, rank: int, world_size: int) -> None:
        self._teardown_child()
        ctx = _mp_context()
        cmd_parent, cmd_child = ctx.Pipe()
        res_parent, res_child = ctx.Pipe()
        proc = ctx.Process(
            target=_child_main,
            args=(self._factory, self._factory_kwargs, cmd_child, res_child),
            daemon=True,
            name=f"tpuft_baby_{rank}",
        )
        proc.start()
        cmd_child.close()
        res_child.close()
        with self._lock:
            self._proc = proc
            self._cmds = MonitoredPipe(cmd_parent)
            self._results = MonitoredPipe(res_parent)
            self._futures = {}
            self._error = None
            self._rank = rank
            self._world_size = world_size
        self._cmds.send(("configure", store_addr, rank, world_size))
        kind, exc = self._results.recv(timeout=self._timeout)
        assert kind == "configured", f"unexpected child response {kind}"
        if exc is not None:
            self._latch(exc)
            raise exc
        reader = threading.Thread(
            target=self._read_loop,
            args=(self._results,),
            name="tpuft_baby_reader",
            daemon=True,
        )
        reader.start()
        self._reader = reader

    def _teardown_child(self) -> None:
        with self._lock:
            proc, self._proc = self._proc, None
            cmds, self._cmds = self._cmds, None
            results, self._results = self._results, None
            reader, self._reader = self._reader, None
            futures, self._futures = self._futures, {}
        for fut in futures.values():
            if not fut.done():
                fut.set_exception(RuntimeError("collective reconfigured"))
        if cmds is not None:
            try:
                cmds.send(("shutdown",))
            except (OSError, BrokenPipeError):
                pass
            cmds.close()
        # The results pipe is closed by its READER thread, never here: the
        # reader may be blocked inside Connection.recv(), which captures the
        # raw fd once per call — closing out from under it frees the fd
        # number, the next configure()'s Pipe() immediately reuses it, and
        # the stale reader then consumes (and corrupts) the NEW generation's
        # byte stream.  The reader is guaranteed to wake and self-close:
        # killing the child below closes the peer end, delivering EOF.
        # Only when no reader was ever started (configure failed before
        # spawning one) is the pipe ours to close.
        if results is not None and reader is None:
            results.close()
        if proc is not None:
            proc.join(timeout=2.0)
            if proc.is_alive():
                proc.kill()
                proc.join(timeout=2.0)

    def _read_loop(self, results: MonitoredPipe) -> None:
        """Completes parent-side futures from child results (reference:
        _future_handler, torchft/process_group.py:1369-1396)."""
        while True:
            try:
                msg = results.recv()
            except (EOFError, OSError):
                # Child died (its pipe end closed): fail everything
                # outstanding — unless this reader is stale.
                err = RuntimeError("collective subprocess died")
                with self._lock:
                    stale = self._results is not results
                    if not stale:
                        futures, self._futures = self._futures, {}
                        if self._error is None:
                            self._error = err
                # This thread owns the pipe's lifetime (see _teardown_child):
                # only now that no recv() can ever run on it again is closing
                # (and thereby freeing the fd number for reuse) safe.
                try:
                    results.close()
                except Exception:  # noqa: BLE001
                    pass
                if stale:
                    # A new configure() installed a fresh child; the futures
                    # dict belongs to the new generation and is not ours to
                    # touch (teardown already failed the old generation's
                    # futures with "collective reconfigured").
                    return
                for fut in futures.values():
                    if not fut.done():
                        fut.set_exception(err)
                return
            except Exception:  # noqa: BLE001
                continue
            if msg[0] == "op":
                _, op_id, exc, value = msg
                with self._lock:
                    fut = self._futures.pop(op_id, None)
                if fut is None or fut.done():
                    continue
                if exc is not None:
                    self._latch(exc)
                    fut.set_exception(exc)
                else:
                    fut.set_result(value)

    def _latch(self, exc: Exception) -> None:
        with self._lock:
            if self._error is None:
                self._error = exc

    def errored(self) -> Optional[Exception]:
        with self._lock:
            if self._error is not None:
                return self._error
            if self._proc is not None and not self._proc.is_alive():
                self._error = RuntimeError("collective subprocess died")
                return self._error
        return None

    def abort(self) -> None:
        # The NCCL-abort analogue: kill the child outright; in-flight ops
        # fail via the reader's EOF path, and the next configure respawns.
        with self._lock:
            proc = self._proc
            if self._error is None:
                self._error = RuntimeError("collective aborted")
        if proc is not None and proc.is_alive():
            proc.kill()

    def shutdown(self) -> None:
        self._teardown_child()

    # -- ops ----------------------------------------------------------------

    def _submit(self, name: str, *args, **kwargs) -> Work:
        with self._lock:
            if self._error is not None:
                return Work(failed_future(self._error))
            cmds = self._cmds
            if cmds is None:
                return Work(failed_future(RuntimeError("collective not configured")))
            op_id = self._next_op
            self._next_op += 1
            fut: Future = Future()
            self._futures[op_id] = fut
        try:
            cmds.send(("op", op_id, name, args, kwargs))
        except (OSError, BrokenPipeError) as e:
            with self._lock:
                self._futures.pop(op_id, None)
            self._latch(e)
            return Work(failed_future(e))
        # A wedged child must surface as a timeout, not a hang: this is the
        # isolation contract (the reference arms the same deadline on baby
        # futures, torchft/process_group.py:1497-1504).
        return Work(future_timeout(fut, self._timeout))

    def allreduce(
        self,
        arrays: Sequence[np.ndarray],
        op: str = "sum",
        allow_wire_compression: bool = True,
    ) -> Work:
        return self._submit(
            "allreduce",
            [np.ascontiguousarray(a) for a in arrays],
            op,
            allow_wire_compression,
        )

    def allgather(self, array: np.ndarray) -> Work:
        return self._submit("allgather", np.ascontiguousarray(array))

    def broadcast(self, array: np.ndarray, root: int = 0) -> Work:
        return self._submit("broadcast", np.ascontiguousarray(array), root)

    def reduce_scatter(self, arrays: Sequence[np.ndarray], op: str = "sum") -> Work:
        return self._submit(
            "reduce_scatter", [np.ascontiguousarray(a) for a in arrays], op
        )

    def alltoall(self, arrays: Sequence[np.ndarray]) -> Work:
        return self._submit("alltoall", [np.ascontiguousarray(a) for a in arrays])

    def send(self, array: np.ndarray, dst: int, tag: int = 0) -> Work:
        return self._submit("send", np.ascontiguousarray(array), dst, tag)

    def recv(self, shape: tuple, dtype, src: int, tag: int = 0) -> Work:
        return self._submit("recv", tuple(shape), dtype, src, tag)

    def barrier(self) -> Work:
        if self._world_size == 1:
            return Work(completed_future(None))
        return self._submit("barrier")

    def size(self) -> int:
        return self._world_size

    def rank(self) -> int:
        return self._rank


def BabyTCPCollective(
    timeout: float = 60.0,
    chunk_bytes: int = 4 << 20,
    wire_dtype: str = "f32",
) -> BabyCollective:
    """Crash-isolated TCPCollective (the BabyNCCL analogue)."""
    return BabyCollective(
        factory=_tcp_collective_factory,
        factory_kwargs={
            "timeout": timeout,
            "chunk_bytes": chunk_bytes,
            "wire_dtype": wire_dtype,
        },
        timeout=timeout,
    )
