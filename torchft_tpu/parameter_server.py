"""Fault-tolerant parameter-server prototype over reconfigurable collectives.

Reference parity: torchft/parameter_server.py:31-195.  A threaded HTTP
endpoint hands out sessions: ``GET /new_session`` returns
``{session_id, store_addr}``, then the serving thread is hijacked to
rendezvous a fresh 2-rank collective on that store prefix (server rank 0,
client rank 1) and run the user's ``forward`` loop over it.  A wedged or
crashed session costs one collective, not the server: the client just opens
a new session.  No Lighthouse involved — sessions ARE the membership.

TPU adaptation: the rendezvous store is the native C++ StoreServer (one per
ParameterServer, shared by all sessions via per-session prefixes) and the
data plane is a host-level ``Collective`` (DCN path), since device arrays
are host buffers by the time they cross replica boundaries.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import urllib.request
import uuid
from abc import ABC, abstractmethod
from http.server import BaseHTTPRequestHandler

from torchft_tpu._native import StoreServer
from torchft_tpu.collectives import Collective, TCPCollective
from torchft_tpu.http import ThreadingHTTPServerV6

__all__ = ["ParameterServer", "TCPParameterServer"]

logger = logging.getLogger("torchft_tpu.parameter_server")


class ParameterServer(ABC):
    """Threaded parameter server; subclasses provide the collective factory
    and the per-session ``forward`` body (reference:
    torchft/parameter_server.py:31-195).

    Args:
        port: HTTP bind port (0 = ephemeral).
        store_bind: bind address for the shared rendezvous StoreServer.
    """

    def __init__(self, port: int = 0, store_bind: str = "0.0.0.0:0") -> None:
        self._store = StoreServer(bind=store_bind)
        ps = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt: str, *args: object) -> None:
                logger.debug(fmt % args)

            def do_GET(self) -> None:
                if self.path != "/new_session":
                    self.send_error(400, f"invalid path {self.path}")
                    return
                session_id = str(uuid.uuid4())
                store_addr = f"{ps.store_address()}/session/{session_id}"
                payload = json.dumps(
                    {"session_id": session_id, "store_addr": store_addr}
                ).encode() + b"\n"
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
                # Flush the complete JSON (Content-Length lets the client
                # finish the request) before hijacking this thread for the
                # session; the socket itself stays open harmlessly.
                self.wfile.flush()
                self.close_connection = True
                logger.info("new session %s", session_id)
                try:
                    ps._run_session(session_id, store_addr)
                except Exception:  # noqa: BLE001
                    # Session death frees one collective; the server lives on.
                    logger.exception("session %s failed", session_id)

        self._server = ThreadingHTTPServerV6(("", port), Handler)
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="tpuft_parameter_server",
            daemon=True,
        )
        self._thread.start()
        logger.info("parameter server on %s", self.address())

    # -- addresses -----------------------------------------------------------

    def address(self) -> str:
        """HTTP address clients hit to open a session."""
        return f"http://{socket.gethostname()}:{self._port}/new_session"

    def store_address(self) -> str:
        return self._store.address()

    # -- session plumbing ----------------------------------------------------

    def _run_session(self, session_id: str, store_addr: str) -> None:
        collective = self.new_collective()
        try:
            collective.configure(store_addr, rank=0, world_size=2)
            self.forward(session_id, collective)
        finally:
            collective.shutdown()

    @classmethod
    def new_session(cls, address: str, timeout: float = 60.0) -> Collective:
        """Client side: opens a session and returns a configured collective
        (client is rank 1, server rank 0 — reference:
        torchft/parameter_server.py:148-168)."""
        with urllib.request.urlopen(address, timeout=timeout) as resp:
            data = json.load(resp)
        logger.info(
            "connecting to session %s at %s", data["session_id"], data["store_addr"]
        )
        collective = cls.new_collective()
        collective.configure(data["store_addr"], rank=1, world_size=2)
        return collective

    # -- subclass surface ----------------------------------------------------

    @classmethod
    @abstractmethod
    def new_collective(cls) -> Collective:
        """A fresh, unconfigured collective for one session."""

    @abstractmethod
    def forward(self, session_id: str, collective: Collective) -> None:
        """Runs once per session on a dedicated thread; loop inside for
        multi-request sessions.  Errors tear down this session only."""

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)
        self._store.shutdown()


class TCPParameterServer(ParameterServer):
    """ParameterServer over TCPCollective with a user-supplied forward
    callable — the concrete flavor the prototype tests exercise."""

    def __init__(
        self,
        forward_fn,
        port: int = 0,
        store_bind: str = "0.0.0.0:0",
    ) -> None:
        self._forward_fn = forward_fn
        super().__init__(port=port, store_bind=store_bind)

    @classmethod
    def new_collective(cls) -> Collective:
        return TCPCollective(timeout=60.0)

    def forward(self, session_id: str, collective: Collective) -> None:
        self._forward_fn(session_id, collective)
