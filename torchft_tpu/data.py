"""Data sharding across replica groups × local ranks.

Reference parity: torchft/data.py (DistributedSampler, torchft/data.py:24-77).
The reference composes the two parallel dimensions into one flat shard index:
``global_rank = rank + num_replicas * replica_group`` over
``num_replicas * num_replica_groups`` total shards.  The same arithmetic here
yields index streams for any indexable dataset; like the reference, sharding
is static per run and documented as lossy under membership churn (a group
that leaves takes its shard's remaining samples with it).
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

import numpy as np

__all__ = ["DistributedSampler", "shard_batch"]


class DistributedSampler:
    """Yields dataset indices for one (replica_group, local rank) shard.

    Args:
        dataset_len: number of samples in the dataset.
        replica_group: which replica group this worker belongs to.
        num_replica_groups: total replica groups in the job.
        rank: local rank within the group (default 0).
        num_replicas: local ranks per group (default 1).
        shuffle: reshuffle each epoch with a deterministic seed.
        drop_last: drop the ragged tail so all shards are equal length.
    """

    def __init__(
        self,
        dataset_len: int,
        replica_group: int,
        num_replica_groups: int,
        rank: int = 0,
        num_replicas: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
    ) -> None:
        # Flat composition of the two dimensions (torchft/data.py:62-67).
        self.global_rank = rank + num_replicas * replica_group
        self.global_world_size = num_replicas * num_replica_groups
        self.dataset_len = dataset_len
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last:
            self.num_samples = dataset_len // self.global_world_size
        else:
            self.num_samples = -(-dataset_len // self.global_world_size)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return self.num_samples

    def __iter__(self) -> Iterator[int]:
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            order = rng.permutation(self.dataset_len)
        else:
            order = np.arange(self.dataset_len)
        if self.drop_last:
            # Truncate the ragged tail so every shard matches __len__ —
            # unequal shards would desync lockstep replicas.
            order = order[: self.num_samples * self.global_world_size]
        elif self.dataset_len % self.global_world_size:
            pad = self.global_world_size - self.dataset_len % self.global_world_size
            order = np.concatenate([order, order[:pad]])
        yield from order[self.global_rank :: self.global_world_size].tolist()


def shard_batch(
    batch_indices: Sequence[int],
    replica_group: int,
    num_replica_groups: int,
    rank: int = 0,
    num_replicas: int = 1,
) -> np.ndarray:
    """Shards a single global batch's indices the same way the sampler shards
    the dataset — convenience for synthetic/streaming pipelines."""
    global_rank = rank + num_replicas * replica_group
    global_ws = num_replicas * num_replica_groups
    return np.asarray(batch_indices)[global_rank::global_ws]
