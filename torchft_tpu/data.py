"""Data sharding across replica groups × local ranks.

Reference parity: torchft/data.py (DistributedSampler, torchft/data.py:24-77).
The reference composes the two parallel dimensions into one flat shard index:
``global_rank = rank + num_replicas * replica_group`` over
``num_replicas * num_replica_groups`` total shards.  The same arithmetic here
yields index streams for any indexable dataset; like the reference, sharding
is static per run and documented as lossy under membership churn (a group
that leaves takes its shard's remaining samples with it).
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

import numpy as np

__all__ = ["DistributedSampler", "StatefulDataLoader", "shard_batch"]


class DistributedSampler:
    """Yields dataset indices for one (replica_group, local rank) shard.

    Args:
        dataset_len: number of samples in the dataset.
        replica_group: which replica group this worker belongs to.
        num_replica_groups: total replica groups in the job.
        rank: local rank within the group (default 0).
        num_replicas: local ranks per group (default 1).
        shuffle: reshuffle each epoch with a deterministic seed.
        drop_last: drop the ragged tail so all shards are equal length.
    """

    def __init__(
        self,
        dataset_len: int,
        replica_group: int,
        num_replica_groups: int,
        rank: int = 0,
        num_replicas: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
    ) -> None:
        # Flat composition of the two dimensions (torchft/data.py:62-67).
        self.global_rank = rank + num_replicas * replica_group
        self.global_world_size = num_replicas * num_replica_groups
        self.dataset_len = dataset_len
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last:
            self.num_samples = dataset_len // self.global_world_size
        else:
            self.num_samples = -(-dataset_len // self.global_world_size)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return self.num_samples

    def __iter__(self) -> Iterator[int]:
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            order = rng.permutation(self.dataset_len)
        else:
            order = np.arange(self.dataset_len)
        if self.drop_last:
            # Truncate the ragged tail so every shard matches __len__ —
            # unequal shards would desync lockstep replicas.
            order = order[: self.num_samples * self.global_world_size]
        elif self.dataset_len % self.global_world_size:
            pad = self.global_world_size - self.dataset_len % self.global_world_size
            order = np.concatenate([order, order[:pad]])
        yield from order[self.global_rank :: self.global_world_size].tolist()


class StatefulDataLoader:
    """Checkpointable batch iterator over an indexable dataset.

    Reference parity: the reference example trains from torchdata's
    StatefulDataLoader so a restarted worker resumes mid-epoch instead of
    replaying data (reference train_ddp.py).  The TPU build's equivalent is
    index-based: it drives a DistributedSampler through epochs, yields
    ``np.ndarray`` index batches (the caller gathers arrays — device-side
    gathers belong inside the jit program), and its
    ``state_dict``/``load_state_dict`` round-trip the exact position.
    Because the per-epoch permutation is seeded, resume is O(1): replay
    re-derives the order and skips ``batches_yielded`` batches.

    Pairs with ManagedDiskCheckpoint: put ``loader.state_dict()`` in the
    user state dict.  (The bundled examples instead re-seed a sampler per
    *step* — that pattern is membership-churn-safe and needs no state; use
    this class when epoch-sequential order matters.)
    """

    def __init__(
        self,
        sampler: DistributedSampler,
        batch_size: int,
        drop_last: bool = True,
    ) -> None:
        assert batch_size >= 1
        self._sampler = sampler
        self._batch_size = batch_size
        self._drop_last = drop_last
        self._epoch = 0
        self._batches_yielded = 0
        # Bumped by each __iter__: position state lives on the loader (that
        # is what makes it checkpointable), so a second live iterator would
        # silently interleave with and double-advance the first — fail loud
        # instead.
        self._iter_token = 0

    def _epoch_batches(self) -> int:
        n = len(self._sampler)
        if self._drop_last:
            return n // self._batch_size
        return -(-n // self._batch_size)

    def _roll_if_exhausted(self) -> None:
        # A state saved right after an epoch's last batch (before the
        # iterator's epilogue ran) points one-past-the-end; normalize so the
        # next pass is a real epoch, not an empty one.
        if self._batches_yielded >= self._epoch_batches():
            self._epoch += 1
            self._batches_yielded = 0

    def __iter__(self) -> Iterator[np.ndarray]:
        """One epoch of index batches, resuming from any loaded position;
        advances to the next epoch when exhausted."""
        self._iter_token += 1
        token = self._iter_token
        self._roll_if_exhausted()
        self._sampler.set_epoch(self._epoch)
        idx = np.fromiter(
            self._sampler, dtype=np.int64, count=len(self._sampler)
        )
        batches = self._epoch_batches()
        while self._batches_yielded < batches:
            if self._iter_token != token:
                raise RuntimeError(
                    "a newer iterator was started on this StatefulDataLoader; "
                    "only one live iterator is supported (position state is "
                    "shared so it can be checkpointed)"
                )
            lo = self._batches_yielded * self._batch_size
            self._batches_yielded += 1
            yield idx[lo : lo + self._batch_size]
        self._epoch += 1
        self._batches_yielded = 0

    def state_dict(self) -> dict:
        return {"epoch": self._epoch, "batches_yielded": self._batches_yielded}

    def load_state_dict(self, state: dict) -> None:
        self._epoch = int(state["epoch"])
        self._batches_yielded = int(state["batches_yielded"])
        self._roll_if_exhausted()


def shard_batch(
    batch_indices: Sequence[int],
    replica_group: int,
    num_replica_groups: int,
    rank: int = 0,
    num_replicas: int = 1,
) -> np.ndarray:
    """Shards a single global batch's indices the same way the sampler shards
    the dataset — convenience for synthetic/streaming pipelines."""
    global_rank = rank + num_replicas * replica_group
    global_ws = num_replicas * num_replica_groups
    return np.asarray(batch_indices)[global_rank::global_ws]
