"""Streaming semi-sync DiLoCo: fragment-synced outer rounds that overlap
inner steps.

The blocking DiLoCo port synchronized like DDP: at every ``sync_every``-th
inner step the whole pytree was hosted, pushed through a synchronous
allreduce, and the train loop stalled for the full cross-region
round-trip.  This class is the WAN-native rebuild (DiLoCo,
arXiv:2311.08105; Streaming DiLoCo, arXiv:2501.18512):

  - the outer state is fragmented on the shared bucket planner
    (semisync/fragments.py — ``ddp.plan_buckets`` underneath);
  - each round's quorum is started at the ROUND boundary (sync quorum:
    a healing group has the committed weights before any pseudogradient);
  - each fragment's pseudogradient round — codec encode (int8+EF / bf16 /
    f32, semisync/codec.py) then a quorum-scoped reduce-scatter+allgather
    over the striped multi-lane ring (``ring2d`` at high group counts) —
    runs on the engine's background worker at a staggered inner-step slot,
    so wire time hides behind the remaining inner compute;
  - the per-fragment outer optimizer (one optax state per fragment) is
    applied ONLY after the round's commit vote passes, so a failed sync
    never corrupts the model, the backup, or the outer state — and the
    backup + outer states travel with heals through the same
    ``register_state_dict_fn`` channel the blocking port used;
  - with a ``set_fragment_params`` hook, a committed fragment's outer
    step lands on device the moment it is computed (device transfer
    overlapping the next fragment's outer math) instead of the whole
    tree re-landing at the round boundary.

``torchft_tpu.local_sgd.DiLoCo`` remains as a thin wrapper (stream=False,
codec="auto"): the old API and blocking semantics, now running on this
engine.

Knobs (all overridable per-instance):
  TPUFT_SEMISYNC_CODEC           int8 | int4 | bf16 | f32 | auto  (default int8)
  TPUFT_SEMISYNC_FRAGMENT_BYTES  fragment size              (default 4 MiB)
  TPUFT_SEMISYNC_STREAM          1 = background streaming   (default 1)
  TPUFT_SEMISYNC_METRICS_PORT    serve tpuft_semisync_* /metrics (unset=off)
"""

from __future__ import annotations

import os
from types import TracebackType
from typing import Any, Callable, Dict, List, Optional, Type

import numpy as np

from torchft_tpu.ddp import _env_flag
from torchft_tpu.semisync.codec import (
    CODECS,
    TPUFT_SEMISYNC_CODEC_ENV,
    make_codec,
)
from torchft_tpu.semisync.engine import SyncEngine
from torchft_tpu.semisync.fragments import FragmentPlan
from torchft_tpu.semisync.metrics import SemiSyncMetrics

__all__ = [
    "StreamingDiLoCo",
    "TPUFT_SEMISYNC_STREAM_ENV",
    "TPUFT_SEMISYNC_FRAGMENT_COMMIT_ENV",
]

TPUFT_SEMISYNC_STREAM_ENV = "TPUFT_SEMISYNC_STREAM"
# Fragment-granular commit (default off): every fragment's pseudogradient
# round runs under its OWN quorum + commit vote, so a membership change
# (elastic resize, peer death) mid-round fails only the in-flight
# fragment's vote — the fragments whose votes already passed keep their
# outer steps.  The round-level default keeps one vote for the whole round
# (cheapest; all-or-nothing on churn).
TPUFT_SEMISYNC_FRAGMENT_COMMIT_ENV = "TPUFT_SEMISYNC_FRAGMENT_COMMIT"


def _codec_from_env(explicit: Optional[str]) -> str:
    if explicit is not None:
        if explicit not in CODECS:
            raise ValueError(
                f"unknown semisync codec {explicit!r}; expected one of {CODECS}"
            )
        return explicit
    raw = os.environ.get(TPUFT_SEMISYNC_CODEC_ENV, "").strip().lower()
    if not raw:
        return "int8"
    if raw not in CODECS:
        # Unlike a numeric tuning knob, a typo'd codec name must NOT fall
        # back silently: the default is LOSSY, so "fp32" quietly becoming
        # int8 would be the exact encoding the user tried to disable.
        # Construction time, not step time — failing loud here is safe.
        raise ValueError(
            f"${TPUFT_SEMISYNC_CODEC_ENV}={raw!r} is not a semisync codec; "
            f"expected one of {CODECS}"
        )
    return raw


class StreamingDiLoCo:
    """Fragment-streamed DiLoCo (see module docstring).

    Usage matches the blocking port::

        with StreamingDiLoCo(manager, get_params, set_params,
                             outer_tx=optax.sgd(0.7, momentum=0.9,
                                                nesterov=True),
                             sync_every=100) as diloco:
            for batch in data:
                params = inner_update(params, batch)
                diloco.step()        # counts, streams fragments, maybe syncs

    Requires synchronous quorum (``use_async_quorum=False``) exactly like
    the blocking port: a healing group must hold the committed weights
    before computing its pseudogradient.
    """

    def __init__(
        self,
        manager,
        get_params: Callable[[], Any],
        set_params: Callable[[Any], None],
        outer_tx: Any,
        sync_every: int,
        fragment_bytes: Optional[int] = None,
        codec: Optional[str] = None,
        stream: Optional[bool] = None,
        outer_scope: str = "fragment",
        state_dict_key: str = "diloco",
        set_fragment_params: Optional[
            Callable[[List[int], List[np.ndarray]], None]
        ] = None,
        fragment_commit: Optional[bool] = None,
    ) -> None:
        """``outer_scope``: "fragment" (default) keeps one optax state per
        fragment and applies the outer update fragment-locally — the
        Streaming DiLoCo shape, required so fragments can eventually apply
        independently.  "tree" runs ONE outer_tx over the whole
        pseudogradient tree at the round boundary — the blocking port's
        exact semantics (and its state-dict format), which outer
        transforms with CROSS-LEAF coupling (global-norm clipping) depend
        on; the legacy ``DiLoCo`` wrapper uses this.

        ``set_fragment_params``: optional partial write-back hook,
        ``(leaf_indices, new_leaves) -> None``, landing ONE fragment's
        leaves on device.  When provided (fragment scope only), a
        committed round writes each fragment back the moment its outer
        step is computed — device transfer of fragment ``k`` overlaps the
        outer math of fragment ``k+1``, and the round-boundary whole-tree
        ``set_params`` reset is skipped entirely (it would re-land every
        byte a second time).  Aborted rounds still reset through the
        whole-tree ``set_params`` — inner steps moved ALL leaves, and the
        backup they roll back to predates this round's fragments.

        ``fragment_commit`` (env ``TPUFT_SEMISYNC_FRAGMENT_COMMIT``,
        default off): fragment-granular fault containment for elastic
        fleets.  Each fragment's pseudogradient round becomes its OWN
        Manager step — quorum armed at the fragment's issue slot on the
        train thread (heals and elastic reconfiguration stay off the
        worker), the reduce overlaps inner steps as usual, and the vote +
        outer apply land at the NEXT fragment's slot.  A resize or peer
        death mid-round therefore fails exactly one fragment's vote: that
        fragment's backup stands and its live leaves roll back through the
        write-back hook, while every fragment whose vote already passed
        keeps its outer step (the Streaming DiLoCo partial-updates shape)
        — the round-level default would discard the whole round's wire
        traffic.  Costs one quorum + vote per FRAGMENT instead of per
        round; requires ``set_fragment_params`` (fragment scope).  Replica
        consistency is preserved: votes are collective and write-backs
        land at schedule-identical slots, so all groups' live params stay
        bitwise identical."""
        if manager._use_async_quorum:
            raise ValueError(
                "StreamingDiLoCo requires synchronous quorum: construct the "
                "Manager with use_async_quorum=False"
            )
        assert sync_every >= 1, "sync_every must be >= 1"
        self._manager = manager
        self._get_params = get_params
        self._set_params = set_params
        self._outer_tx = outer_tx
        self._sync_every = sync_every
        self._local_step = 0
        self._armed = False
        self._issued: set = set()
        self._arm_attempted = False
        self._round_closed = False
        self._voted = False
        self._vote_passed = False

        self._codec_name = _codec_from_env(codec)
        self._stream = (
            bool(stream)
            if stream is not None
            else _env_flag(TPUFT_SEMISYNC_STREAM_ENV, True)
        )

        # Host backup of the last-synced params; the flat leaf list is the
        # canonical copy, the tree is derived.  The one jax import here is
        # construction-time, not hot-path.
        import jax

        self._jax = jax
        leaves, self._treedef = jax.tree.flatten(get_params())
        self._leaves: List[np.ndarray] = [
            l if isinstance(l, np.ndarray) else np.asarray(l) for l in leaves
        ]
        metas = [(tuple(l.shape), np.dtype(l.dtype)) for l in self._leaves]
        self._plan = FragmentPlan(metas, fragment_bytes)
        self._schedule = self._plan.schedule(sync_every)

        self._codecs = [
            make_codec(self._codec_name, f) for f in self._plan.fragments
        ]
        for frag, c in zip(self._plan.fragments, self._codecs):
            c.set_backup(frag.pack(self._leaves))

        # One outer optimizer state PER FRAGMENT (a fragment's leaf list is
        # its own optax pytree) in "fragment" scope: the outer update
        # applies fragment-locally after the commit vote, so a
        # partially-failed round can never leave the optimizer state
        # half-advanced.  "tree" scope keeps the blocking port's single
        # whole-tree state.
        if outer_scope not in ("fragment", "tree"):
            raise ValueError(
                f"outer_scope must be 'fragment' or 'tree', got {outer_scope!r}"
            )
        self._outer_scope = outer_scope
        if set_fragment_params is not None and outer_scope != "fragment":
            raise ValueError(
                "set_fragment_params requires outer_scope='fragment' — a "
                "whole-tree outer update has no per-fragment commit moment"
            )
        self._set_fragment_params = set_fragment_params
        self._fragment_commit = (
            bool(fragment_commit)
            if fragment_commit is not None
            else _env_flag(TPUFT_SEMISYNC_FRAGMENT_COMMIT_ENV, False)
        )
        if self._fragment_commit and set_fragment_params is None:
            raise ValueError(
                "fragment_commit requires set_fragment_params: a failed "
                "fragment vote rolls back ONLY that fragment's leaves, "
                "which needs the partial write-back hook"
            )
        # Fragment-commit round state: the fragment whose vote is still
        # outstanding, and how many votes failed this round.
        self._pending_fragment = None
        self._round_failed = 0
        self._round_open = False
        self._post_vote = False
        if outer_scope == "fragment":
            self._outer_states: Any = [
                outer_tx.init([self._leaves[i] for i in f.bucket.indices])
                for f in self._plan.fragments
            ]
        else:
            self._outer_states = outer_tx.init(self.backup_params)

        replica_id = ""
        try:
            replica_id = manager.replica_id()
        except Exception:  # noqa: BLE001 — mocked managers
            pass
        self.metrics = SemiSyncMetrics(
            codec=self._codec_name, replica_id=str(replica_id)
        )
        # Unified worker exposition (obs/prom.py): when the Manager runs
        # the worker /metrics endpoint, the tpuft_semisync_* section folds
        # into it instead of opening a second port; mocked/legacy managers
        # fall back to the standalone exporter (the deprecated
        # TPUFT_SEMISYNC_METRICS_PORT path).
        worker_metrics = getattr(manager, "worker_metrics", None)
        if worker_metrics is not None and getattr(worker_metrics, "serving", False):
            worker_metrics.add_section(self.metrics.render_prometheus)
        else:
            self.metrics.serve()
        self._engine = SyncEngine(
            manager, self._codecs, stream=self._stream, metrics=self.metrics
        )

        # The outer-loop state must travel with the model when a restarted
        # group heals from a peer: a fresh-init backup would make the next
        # sync compute pseudogradients against the wrong base and silently
        # diverge (the divergence mode tests/test_semisync.py pins with a
        # mid-round kill).
        manager.register_state_dict_fn(
            state_dict_key, self._load_outer_state, self._save_outer_state
        )

    # -- context manager ----------------------------------------------------

    def __enter__(self) -> "StreamingDiLoCo":
        return self

    def __exit__(
        self,
        exc_type: Optional[Type[BaseException]],
        exc_value: Optional[BaseException],
        traceback: Optional[TracebackType],
    ) -> bool:
        self._engine.shutdown()
        self.metrics.close()
        return False

    # -- introspection ------------------------------------------------------

    @property
    def backup_params(self) -> Any:
        return self._jax.tree.unflatten(self._treedef, list(self._leaves))

    @backup_params.setter
    def backup_params(self, value: Any) -> None:
        leaves, _ = self._jax.tree.flatten(value)
        self._leaves = [
            l if isinstance(l, np.ndarray) else np.asarray(l) for l in leaves
        ]
        self._refresh_codec_backups()

    @property
    def codec_name(self) -> str:
        return self._codec_name

    @property
    def num_fragments(self) -> int:
        return len(self._plan)

    @property
    def plan(self) -> FragmentPlan:
        return self._plan

    def _refresh_codec_backups(self) -> None:
        for frag, c in zip(self._plan.fragments, self._codecs):
            c.set_backup(frag.pack(self._leaves))

    # -- heal-consistency state ---------------------------------------------

    def _save_outer_state(self) -> Any:
        from torchft_tpu.local_sgd import _tree_to_host

        return {
            "backup": self.backup_params,
            "outer_state": _tree_to_host(self._outer_states),
            # Explicit format marker: a heuristic over the state's pytree
            # shape cannot distinguish a whole-tree optax tuple from a
            # per-fragment list reliably (a 2-transform chain state IS a
            # 2-tuple).  Absent key = a legacy (pre-semisync) checkpoint,
            # which was always whole-tree.
            "outer_scope": self._outer_scope,
        }

    def _load_outer_state(self, state: Any) -> None:
        # Validate BEFORE mutating anything: a mismatched format indexed by
        # the other scope's apply path would raise a confusing optax pytree
        # error at the NEXT commit, after the vote already passed — and a
        # half-applied load (new backup, old outer states) must never be
        # left behind.  The raise latches at the heal site, fails every
        # commit until the deployment mismatch is fixed (or max_retries
        # terminates the loop) — degraded-loud, never silently divergent.
        saved_scope = state.get("outer_scope", "tree")
        if saved_scope != self._outer_scope:
            raise ValueError(
                f"diloco state dict carries outer_scope={saved_scope!r} "
                f"outer state but this instance runs "
                f"outer_scope={self._outer_scope!r}; construct with the "
                "matching scope (the legacy DiLoCo wrapper is 'tree') or "
                "re-checkpoint"
            )
        self.backup_params = state["backup"]
        self._outer_states = state["outer_state"]
        # EF residuals are replica-local transmission state, not model
        # state: a healed group starts with clean residuals (its peers'
        # residuals describe THEIR untransmitted remainders).
        for c in self._codecs:
            c.on_abort()

    # -- train-loop API -----------------------------------------------------

    def step(self) -> None:
        """Call after each inner optimizer step.  In stream mode this arms
        the round's quorum at the first inner step and issues fragments at
        their scheduled slots; the final step of the round runs
        :meth:`sync`."""
        if self._fragment_commit:
            self._step_fragment_commit()
            return
        if (
            self._stream
            and not self._armed
            and not self._arm_attempted
            and len(self._plan)
        ):
            # Arm the round before any fragment leaves: sync quorum applies
            # heals eagerly, so every pseudogradient this round is computed
            # against committed weights.  Latched like every other
            # sync-path error — a transient quorum failure here must not
            # crash the train loop when the same failure at sync() time
            # would not.  ONE attempt per round (_arm_attempted): retrying
            # on every inner step would turn a lighthouse outage into up
            # to sync_every x quorum_timeout of train-thread stall per
            # round; sync() makes the round's second (and last) attempt
            # inside its own latch.
            self._arm_attempted = True
            try:
                self._manager.start_quorum()
                self._armed = True
                self._engine.begin_round()
            except Exception as e:  # noqa: BLE001 — latch, keep cadence
                try:
                    self._manager.report_error(e)
                except Exception:  # noqa: BLE001 — mocked managers
                    pass
        self._local_step += 1
        if self._stream and self._armed:
            due = [
                f
                for f in self._schedule.get(self._local_step, ())
                if f.index not in self._issued
            ]
            if due:
                # One flatten per slot, however many fragments share it —
                # this runs on the train-thread hot path.
                leaves = self._jax.tree.flatten(self._get_params())[0]
                for frag in due:
                    self._issued.add(frag.index)
                    self._engine.submit(frag, leaves)
        if self._local_step >= self._sync_every:
            self.sync()

    def sync(self) -> None:
        """Finishes the round: drains in-flight fragments, votes, and
        applies the per-fragment outer updates only on a passed vote.
        Errors anywhere in the round LATCH on the manager and the counter
        resets in a ``finally`` — every group re-enters the next round on
        the same cadence even when a sync dies mid-quorum."""
        from torchft_tpu.manager import ExceededMaxRetriesError

        if self._fragment_commit:
            self._sync_fragment_commit()
            return
        self._round_closed = False
        self._voted = False
        self._vote_passed = False
        try:
            self._sync_inner()
        except ExceededMaxRetriesError:
            # The give-up contract must still propagate: a loop configured
            # with max_retries relies on this exception to terminate.
            raise
        except Exception as e:  # noqa: BLE001 — latch, never desync cadence
            if self._vote_passed:
                # Peers were already told this round committed; swallowing
                # a post-vote apply failure would leave THIS group on
                # different weights with every later vote passing — crash
                # instead, and heal back to the committed state.
                raise
            try:
                self._manager.report_error(e)
            except Exception:  # noqa: BLE001 — mocked managers
                pass
            # Quiesce the worker BEFORE touching round state: an in-flight
            # fragment round re-sets pending residuals and writes results;
            # aborting under it would race, and a stale result could bleed
            # into the next round's result map.
            try:
                self._engine.drain()
            except Exception:  # noqa: BLE001 — mocked managers
                pass
            if not self._voted:
                # Sibling local ranks are already in the two-phase commit
                # barrier; vote (False, via the latched error) instead of
                # leaving them to time out round after round.
                try:
                    self._manager.should_commit()
                except Exception:  # noqa: BLE001 — vote itself failing
                    pass
            if not self._round_closed:
                self._engine.end_round(committed=False)
            try:
                self._set_params(self.backup_params)
            except Exception:  # noqa: BLE001 — leave local params standing
                pass
        finally:
            self._local_step = 0
            self._armed = False
            self._arm_attempted = False
            self._issued = set()

    def _sync_inner(self) -> None:
        if not self._armed:
            self._manager.start_quorum()
            self._armed = True
            self._engine.begin_round()
        # Any fragment not yet streamed goes now (all of them in blocking
        # mode; stragglers whose slot never ticked in stream mode).
        leaves = None
        for frag in self._plan.fragments:
            if frag.index not in self._issued:
                self._issued.add(frag.index)
                if leaves is None:
                    leaves = self._jax.tree.flatten(self._get_params())[0]
                self._engine.submit(frag, leaves)

        results = self._engine.drain()
        # Summary fields must land BEFORE the vote: should_commit flushes
        # this step's step_summary record.  The round's step is captured
        # here too — a committed vote advances current_step(), and the
        # semisync_round event must join against the SAME step the round's
        # spans and commit records carry.
        stats = self._engine.round_stats()
        self._note_summary(stats)
        try:
            round_step = int(self._manager.current_step())
        except (TypeError, ValueError):  # mocked managers
            round_step = -1
        self._voted = True
        committed = bool(self._manager.should_commit())
        self._vote_passed = committed
        applied_inplace = self._apply(results) if committed else False
        self._engine.end_round(committed=committed)
        self._round_closed = True
        self._emit_round(stats, committed, round_step)
        # Commit or not, the live params reset to the (possibly updated)
        # last-committed weights — the blocking port's contract.  When the
        # per-fragment write-back already landed every leaf as its outer
        # step committed, the whole-tree reset would only re-send the same
        # bytes; skip it.
        if not applied_inplace:
            self._set_params(self.backup_params)

    # -- fragment-granular commit (see __init__ docstring) -------------------

    def _step_fragment_commit(self) -> None:
        """Inner-step tick in fragment-commit mode: at a fragment's slot,
        settle the previous fragment's vote first (its reduce has been
        overlapping inner steps since its own slot), then arm this
        fragment's quorum and issue its reduce."""
        self._local_step += 1
        due = [
            f
            for f in self._schedule.get(self._local_step, ())
            if f.index not in self._issued
        ]
        for frag in due:
            self._finish_pending_fragment()
            self._issue_fragment(frag)
        if self._local_step >= self._sync_every:
            self.sync()

    def _issue_fragment(self, frag) -> None:
        """Arms one fragment's quorum (train thread — heals and elastic
        reconfiguration happen here, never on the worker) and submits its
        reduce.  An arm failure latches; the fragment's vote then fails at
        settle time and only ITS leaves roll back."""
        self._issued.add(frag.index)
        self._pending_fragment = frag
        try:
            self._manager.start_quorum()
            self._armed = True
        except Exception as e:  # noqa: BLE001 — latch, keep cadence
            try:
                self._manager.report_error(e)
            except Exception:  # noqa: BLE001 — mocked managers
                pass
            return
        if not self._round_open:
            self._engine.begin_round()
            self._round_open = True
        leaves = self._jax.tree.flatten(self._get_params())[0]
        self._engine.submit(frag, leaves)

    def _finish_pending_fragment(self) -> None:
        """Settles the outstanding fragment: drain its reduce, vote, and
        apply-or-rollback just that fragment.  A post-vote apply failure
        raises (peers were told the fragment committed — heal back rather
        than diverge silently), same contract as the round-level path."""
        frag = self._pending_fragment
        if frag is None:
            return
        self._pending_fragment = None
        results: Dict[int, np.ndarray] = {}
        if self._armed:
            try:
                results = self._engine.drain()
            except Exception as e:  # noqa: BLE001 — mocked managers
                try:
                    self._manager.report_error(e)
                except Exception:  # noqa: BLE001
                    pass
            # Running round accounting lands on THIS fragment's step
            # record before its vote flushes it.
            self._note_summary(self._engine.round_stats())
        committed = False
        if self._armed:
            self._armed = False
            try:
                committed = bool(self._manager.should_commit())
            except Exception as e:  # noqa: BLE001 — vote itself failing
                from torchft_tpu.manager import ExceededMaxRetriesError

                if isinstance(e, ExceededMaxRetriesError):
                    raise
                try:
                    self._manager.report_error(e)
                except Exception:  # noqa: BLE001
                    pass
        if not committed:
            self._round_failed += 1
        flat = results.get(frag.index) if committed else None
        if committed and flat is not None:
            # Post-vote apply: peers were told this fragment committed, so
            # a failure here must RAISE (heal back to the committed state)
            # — _post_vote marks the window for the sync-level handler.
            self._post_vote = True
            self._apply_one_fragment(frag, flat)
            self._post_vote = False
        else:
            try:
                self._apply_one_fragment(frag, None)
            except Exception:  # noqa: BLE001 — leave local params standing
                pass
        self._engine.promote_fragment(frag, committed)

    def _apply_one_fragment(self, frag, flat: Optional[np.ndarray]) -> None:
        """One fragment's outer step (vote passed, ``flat`` is its averaged
        pseudogradient) or rollback (``flat`` is None): either way exactly
        this fragment's leaves land on device through the write-back hook —
        the surrounding fragments are untouched."""
        import optax

        write_back = self._set_fragment_params
        assert write_back is not None  # enforced at construction
        if flat is None:
            # Failed vote: the backup stands; roll only this fragment's
            # live leaves back to it (inner steps moved them).
            write_back(
                list(frag.bucket.indices),
                [self._leaves[i] for i in frag.bucket.indices],
            )
            return
        k = frag.index
        pg_leaves = [np.ascontiguousarray(arr) for _i, arr in frag.unpack(flat)]
        backup_leaves = [self._leaves[i] for i in frag.bucket.indices]
        updates, self._outer_states[k] = self._outer_tx.update(
            pg_leaves, self._outer_states[k], backup_leaves
        )
        new_leaves = optax.apply_updates(backup_leaves, updates)
        for i, nl in zip(frag.bucket.indices, new_leaves):
            self._leaves[i] = np.asarray(nl)
        write_back(
            list(frag.bucket.indices),
            [self._leaves[i] for i in frag.bucket.indices],
        )
        self._codecs[k].set_backup(frag.pack(self._leaves))

    def _sync_fragment_commit(self) -> None:
        """Round boundary in fragment-commit mode: settle the last
        outstanding fragment, run any never-issued stragglers (all of them
        in blocking mode) as their own mini-rounds, then emit the round's
        accounting.  There is no round-level vote and no whole-tree reset:
        every fragment already landed (or rolled back) at its own commit
        moment."""
        from torchft_tpu.manager import ExceededMaxRetriesError

        try:
            self._finish_pending_fragment()
            for frag in self._plan.fragments:
                if frag.index not in self._issued:
                    self._issue_fragment(frag)
                    self._finish_pending_fragment()
            stats = self._engine.round_stats()
            committed = self._round_failed == 0
            try:
                round_step = int(self._manager.current_step())
            except (TypeError, ValueError):  # mocked managers
                round_step = -1
            if self._round_open:
                self._engine.end_round(committed=committed, promote=False)
            self._emit_round(stats, committed, round_step)
        except ExceededMaxRetriesError:
            raise
        except Exception as e:  # noqa: BLE001 — latch, never desync cadence
            if self._post_vote:
                # A committed fragment's apply failed — peers already
                # advanced; crash and heal rather than silently diverge.
                raise
            try:
                self._manager.report_error(e)
            except Exception:  # noqa: BLE001 — mocked managers
                pass
        finally:
            self._local_step = 0
            self._armed = False
            self._arm_attempted = False
            self._issued = set()
            self._pending_fragment = None
            self._round_failed = 0
            self._round_open = False
            self._post_vote = False

    def _apply(self, results: Dict[int, np.ndarray]) -> bool:
        """Outer optimizer step on the averaged pseudogradients —
        per-fragment or whole-tree per ``outer_scope``.  Deterministic
        given identical inputs, and the ring guarantees bitwise-identical
        averages on every group — so all groups land bitwise-identical
        backups (the replica-consistency property the integration tests
        pin).  Returns True when the per-fragment write-back hook landed
        EVERY leaf on device already (the caller then skips the
        whole-tree reset)."""
        import optax

        if self._outer_scope == "tree":
            # Assemble the full pseudogradient tree and run ONE update —
            # the blocking port's semantics; outer transforms with
            # cross-leaf coupling (global-norm clipping) need this.
            pg_leaves: List[np.ndarray] = [
                np.zeros_like(l) for l in self._leaves
            ]
            for frag in self._plan.fragments:
                flat = results.get(frag.index)
                if flat is None:
                    continue
                for i, arr in frag.unpack(flat):
                    pg_leaves[i] = np.ascontiguousarray(arr)
            pg_tree = self._jax.tree.unflatten(self._treedef, pg_leaves)
            backup_tree = self.backup_params
            updates, self._outer_states = self._outer_tx.update(
                pg_tree, self._outer_states, backup_tree
            )
            new_tree = optax.apply_updates(backup_tree, updates)
            self._leaves = [
                np.asarray(l) for l in self._jax.tree.flatten(new_tree)[0]
            ]
            self._refresh_codec_backups()
            return False
        write_back = self._set_fragment_params
        for k, frag in enumerate(self._plan.fragments):
            flat = results.get(frag.index)
            if flat is None:
                # No averaged pseudogradient for this fragment: its backup
                # stands, but its LIVE leaves moved through sync_every
                # inner steps — the per-fragment path must still roll them
                # back, or skipping the whole-tree reset would leave this
                # fragment's device leaves uncommitted.
                if write_back is not None:
                    write_back(
                        list(frag.bucket.indices),
                        [self._leaves[i] for i in frag.bucket.indices],
                    )
                continue
            pg_leaves = [
                np.ascontiguousarray(arr) for _i, arr in frag.unpack(flat)
            ]
            backup_leaves = [self._leaves[i] for i in frag.bucket.indices]
            updates, self._outer_states[k] = self._outer_tx.update(
                pg_leaves, self._outer_states[k], backup_leaves
            )
            new_leaves = optax.apply_updates(backup_leaves, updates)
            for i, nl in zip(frag.bucket.indices, new_leaves):
                self._leaves[i] = np.asarray(nl)
            if write_back is not None:
                # Land this fragment the moment its outer step committed:
                # the device transfer overlaps fragment k+1's outer math
                # instead of queueing behind the whole tree at the round
                # boundary.
                write_back(
                    list(frag.bucket.indices),
                    [self._leaves[i] for i in frag.bucket.indices],
                )
        self._refresh_codec_backups()
        return write_back is not None

    def _note_summary(self, stats: Dict[str, int]) -> None:
        """Round accounting into the step in flight's step_summary — must
        run before the commit vote flushes that record."""
        note = getattr(self._manager, "note_summary_fields", None)
        if callable(note):
            try:
                note(
                    semisync_fragments=stats["fragments"],
                    semisync_wire_bytes=stats["wire_bytes"],
                    semisync_codec=self._codec_name,
                )
            except Exception:  # noqa: BLE001 — telemetry only
                pass

    def _emit_round(
        self, stats: Dict[str, int], committed: bool, round_step: int
    ) -> None:
        """The per-round metrics event; the int8 residual norm rides as a
        gauge."""
        manager = self._manager
        residual_l2 = 0.0
        # The residual norm costs a per-fragment device reduction; only
        # pay it when somebody can actually read it (the JSONL stream or
        # the Prometheus endpoint).
        want_residual = self.metrics.serving
        try:
            want_residual = want_residual or bool(manager.metrics.enabled)
        except Exception:  # noqa: BLE001 — mocked managers
            pass
        if want_residual:
            for c in self._codecs:
                fn = getattr(c, "residual_l2", None)
                if callable(fn):
                    residual_l2 += float(fn())
            self.metrics.observe_residual(residual_l2)
        try:
            manager.metrics.emit(
                "semisync_round",
                step=round_step,
                committed=committed,
                fragments=stats["fragments"],
                wire_bytes=stats["wire_bytes"],
                d2h_bytes=stats["d2h_bytes"],
                codec=self._codec_name,
                streamed=self._stream,
                writeback=(
                    "fragment" if self._set_fragment_params is not None else "tree"
                ),
                residual_l2=round(residual_l2, 6),
            )
        except Exception:  # noqa: BLE001 — mocked managers / telemetry only
            pass
