"""Fragment planning for the streaming semi-sync data plane.

The outer (DiLoCo) state is partitioned into **fragments** — dtype-
homogeneous flat slices of the parameter pytree — on the exact bucket
machinery the DDP gradient path already uses (:func:`torchft_tpu.ddp.
plan_buckets`): leaves are grouped by dtype, packed greedily up to
``fragment_bytes``, and each fragment remembers which leaves it covers and
where each lives in the flat buffer.  One fragment is the unit of the
background pseudogradient sync (Streaming DiLoCo, arXiv:2501.18512): a
round's fragments are issued at staggered inner-step slots so each
fragment's wire time overlaps the remaining inner compute instead of
stalling the whole round at the sync boundary.

Reusing ``plan_buckets`` (rather than a private re-implementation) keeps
the two data planes' packing semantics identical — 0-d leaves, dtype
grouping, oversized-leaf handling — and means a fix there fixes both.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

from torchft_tpu.ddp import plan_buckets

__all__ = [
    "Fragment",
    "FragmentPlan",
    "pack_flat",
    "TPUFT_SEMISYNC_FRAGMENT_BYTES_ENV",
    "DEFAULT_FRAGMENT_BYTES",
]


def pack_flat(arrs: Sequence[Any], dtype: Any) -> np.ndarray:
    """One contiguous 1-D host array of ``dtype`` from a leaf list — THE
    packing primitive of this plane, shared by :meth:`Fragment.pack` and
    the codecs' host paths so the two cannot drift."""
    parts = [np.asarray(a).reshape(-1) for a in arrs]
    flat = parts[0] if len(parts) == 1 else np.concatenate(parts)
    return flat.astype(np.dtype(dtype), copy=False)

TPUFT_SEMISYNC_FRAGMENT_BYTES_ENV = "TPUFT_SEMISYNC_FRAGMENT_BYTES"
# Default fragment size.  Smaller than DDP's 25 MB gradient buckets: a
# fragment is the granularity of sync/compute overlap within one outer
# round, and a round has only ``sync_every`` slots to hide fragments in —
# 4 MB keeps several fragments per round for typical outer states while
# staying large enough to amortize ring framing.
DEFAULT_FRAGMENT_BYTES = 4 << 20


def fragment_bytes_from_env(explicit: Any = None) -> int:
    """Resolves the fragment size: explicit arg, else
    ``TPUFT_SEMISYNC_FRAGMENT_BYTES``, else the default.  Malformed env
    values fall back to the default — a bad tuning knob must not abort
    training."""
    if explicit is not None:
        return max(1, int(explicit))
    try:
        return max(
            1,
            int(
                os.environ.get(
                    TPUFT_SEMISYNC_FRAGMENT_BYTES_ENV, str(DEFAULT_FRAGMENT_BYTES)
                )
            ),
        )
    except ValueError:
        return DEFAULT_FRAGMENT_BYTES


class Fragment:
    """One flat slice of the outer state: which leaves it packs and how they
    lay out in the fragment's flat buffer (delegated to the shared
    ``ddp._Bucket`` metadata), plus whether the fragment is eligible for
    lossy wire codecs (real floats of >= 4 bytes — the same gate the DDP
    wire compression applies; integer and sub-f32 fragments always ride
    raw full-width)."""

    def __init__(self, index: int, bucket: Any) -> None:
        self.index = index
        self.bucket = bucket
        self.numel = bucket.numel
        self.nbytes = bucket.nbytes
        self.dtype = bucket.dtype
        self.lossy_ok = (
            np.issubdtype(bucket.dtype, np.floating)
            and bucket.dtype.itemsize >= 4
        )

    def pack(self, leaves: Sequence[Any]) -> np.ndarray:
        """Flat host array (fragment dtype) of this fragment's leaves, in
        bucket layout.  ``leaves`` is the FULL tree's leaf list; the
        fragment selects its own by index."""
        return pack_flat(
            [leaves[i] for i in self.bucket.indices], self.dtype
        )

    def unpack(self, flat: np.ndarray) -> List[Tuple[int, np.ndarray]]:
        """(leaf index, reshaped view) pairs — the shared bucket unpack."""
        return self.bucket.unpack(np.asarray(flat).astype(self.dtype, copy=False))


class FragmentPlan:
    """The fragment layout for one tree signature plus the per-round issue
    schedule.

    ``slot(f, sync_every)`` staggers fragment issues across the round's
    inner steps: fragment f of F is due after inner step
    ``1 + floor(f * sync_every / F)`` (clamped to the round), so the first
    fragment leaves the moment the round starts making progress and the
    last still has ``~sync_every/F`` inner steps of compute to hide its
    wire time behind.  Every group derives the identical schedule from
    (tree signature, sync_every) alone — fragment issue order is part of
    the cross-group ring-op alignment contract, exactly like bucket
    submission order in the DDP plane.
    """

    def __init__(
        self, metas: Sequence[Tuple[tuple, Any]], fragment_bytes: Any = None
    ) -> None:
        self.fragment_bytes = fragment_bytes_from_env(fragment_bytes)
        self.fragments = [
            Fragment(i, b)
            for i, b in enumerate(plan_buckets(metas, self.fragment_bytes))
        ]
        self.total_bytes = sum(f.nbytes for f in self.fragments)

    def __len__(self) -> int:
        return len(self.fragments)

    def slot(self, index: int, sync_every: int) -> int:
        """The inner step (1-based) after which fragment ``index`` is
        issued."""
        n = max(1, len(self.fragments))
        return min(sync_every, 1 + (index * sync_every) // n)

    def schedule(self, sync_every: int) -> Dict[int, List[Fragment]]:
        """inner step -> fragments due at that step, covering every
        fragment exactly once."""
        by_slot: Dict[int, List[Fragment]] = {}
        for f in self.fragments:
            by_slot.setdefault(self.slot(f.index, sync_every), []).append(f)
        return by_slot
