"""The background fragment-sync engine of the streaming semi-sync plane.

One engine per :class:`~torchft_tpu.semisync.diloco.StreamingDiLoCo`: the
train loop hands it (fragment, live-leaf snapshot) pairs at the fragment's
scheduled inner-step slot, and the engine runs the fragment's
pseudogradient round — device/host encode through the fragment's codec,
then a quorum-scoped cross-group allreduce via ``Manager.allreduce`` (so
participation zeroing, participant averaging, deadline guarding, error
LATCHING, and the commit-vote drain all behave exactly like the gradient
plane) — on a single background worker thread while inner steps keep
running.

Ordering contract: the one-worker executor serializes fragment rounds in
submission order, and the fragment schedule is derived identically on
every group from (tree signature, sync_every) — so each group issues the
same sequence of ring ops in the same order, which is the cross-rank tag
alignment the striped ring requires (same contract as DDP bucket order).

Observability: each fragment round runs inside an ``outer_sync`` span —
an OVERLAPPED phase (obs/spans.py): it lives on the worker thread,
concurrent with inner compute, so report.py shows it without charging it
against productive time.  The round-end drain (the only part that blocks
the train thread) is charged as ``allreduce_merge``.  Per-round fragment
counts/bytes land in step_summary via ``Manager.note_summary_fields`` and
as a ``semisync_round`` metrics event.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from torchft_tpu.semisync.codec import FragmentCodec
from torchft_tpu.semisync.fragments import Fragment
from torchft_tpu.semisync.metrics import SemiSyncMetrics

__all__ = ["SyncEngine"]


class SyncEngine:
    """Streams fragment pseudogradient rounds in the background.

    ``stream=False`` runs every fragment inline on the caller's thread
    (the blocking legacy-port shape — still fragment-bucketed, still
    codec-encoded, just not overlapped); this is what the thin ``DiLoCo``
    wrapper uses, and what keeps the engine fully functional against
    mocked managers in unit tests.
    """

    def __init__(
        self,
        manager,
        codecs: Sequence[FragmentCodec],
        stream: bool,
        metrics: Optional[SemiSyncMetrics] = None,
    ) -> None:
        self._manager = manager
        self._codecs = list(codecs)
        self._stream = bool(stream)
        self.metrics = metrics if metrics is not None else SemiSyncMetrics()
        self._worker: Optional[ThreadPoolExecutor] = (
            ThreadPoolExecutor(max_workers=1, thread_name_prefix="tpuft_semisync")
            if self._stream
            else None
        )
        self._lock = threading.Lock()
        self._futures: List[Future] = []
        self._results: Dict[int, np.ndarray] = {}
        self._round_wire_bytes = 0
        self._round_d2h_bytes = 0
        self._round_fragments = 0
        self._round_overlap_ms = 0.0

    # -- round lifecycle ----------------------------------------------------

    def begin_round(self) -> None:
        with self._lock:
            self._futures = []
            self._results = {}
            self._round_wire_bytes = 0
            self._round_d2h_bytes = 0
            self._round_fragments = 0
            self._round_overlap_ms = 0.0

    def submit(self, fragment: Fragment, leaves: Sequence[Any]) -> None:
        """Issues one fragment's pseudogradient round.  ``leaves`` is the
        full live leaf list at the fragment's slot (jax arrays are
        immutable, so holding the refs IS the snapshot; host numpy leaves
        are COPIED here — a train loop mutating them in place must not
        race the worker's encode into a torn pseudogradient).  Returns
        immediately in stream mode; runs inline otherwise."""
        if self._worker is not None:
            snap = list(leaves)
            for i in fragment.bucket.indices:
                if isinstance(snap[i], np.ndarray):
                    snap[i] = np.array(snap[i], copy=True)
            fut = self._worker.submit(self._sync_fragment, fragment, snap)
            with self._lock:
                self._futures.append(fut)
        else:
            self._sync_fragment(fragment, leaves)

    def _sync_fragment(self, fragment: Fragment, leaves: Sequence[Any]) -> None:
        manager = self._manager
        codec = self._codecs[fragment.index]
        # Phase attribution follows the THREAD, not the feature: on the
        # worker the round is overlapped with inner compute (outer_sync,
        # never charged); inline (blocking mode) the same work stalls the
        # TRAIN thread and must be charged as FT time — outer_sync here
        # would hide the blocking port's whole stall from report.py and
        # inflate the straggler sentinel's busy-time by exactly that
        # stall.  allreduce_merge is the phase the old blocking port's
        # drain charged.
        phase = "outer_sync" if self._worker is not None else "allreduce_merge"
        with manager.spans.span(
            phase,
            step=manager.current_step(),
            fragment=fragment.index,
            codec=codec.name,
        ) as sp:
            participating = bool(manager.is_participating())
            if participating:
                payload, d2h = codec.encode(leaves)
            else:
                # Healing / spare groups must still ride the ring (the op
                # count AND each rank's payload dtype are part of the
                # cross-rank frame contract — hence the codec's dtype, not
                # a hardcoded f32) but contribute zeros and keep their EF
                # state untouched.
                payload, d2h = codec.zero_payload(), 0
            wire_codec = codec.wire_codec
            if wire_codec is not None and not self._collective_supports(wire_codec):
                # Source-side quantization (+ error feedback) already
                # happened in the codec; the ring just won't re-encode —
                # degrade to the collective's own wire policy.
                wire_codec = None
            # The encoded payload is a fresh per-round buffer the engine
            # never reads again — donate it so the ring may reduce in place
            # (zero working-buffer copy on the native engine).
            if wire_codec is not None:
                fut = manager.allreduce(
                    payload,
                    allow_wire_compression=codec.allow_wire_compression,
                    wire_codec=wire_codec,
                    donate=True,
                )
            else:
                fut = manager.allreduce(
                    payload,
                    allow_wire_compression=codec.allow_wire_compression,
                    donate=True,
                )
            # Block the WORKER (not the train thread) until the averaged
            # fragment lands; failures resolve to the input with the error
            # latched on the manager — the commit vote turns that into a
            # discarded round, never a crash.
            res = fut.result()
            wire = self._wire_nbytes(payload, codec, wire_codec)
            sp.fields["bytes"] = wire
            with self._lock:
                self._results[fragment.index] = np.asarray(res)
                self._round_wire_bytes += wire
                self._round_d2h_bytes += int(d2h)
                self._round_fragments += 1
            if d2h:
                note = getattr(manager, "note_d2h", None)
                if callable(note):
                    try:
                        note(int(d2h))
                    except Exception:  # noqa: BLE001 — telemetry only
                        pass
            self.metrics.observe_fragment(wire_bytes=wire, d2h_bytes=int(d2h))
        # duration_ms is valid once the span's `with` block exits; the sum
        # over the round feeds the tpuft_semisync_round_overlap_ms gauge —
        # sync time that ran CONCURRENT with inner steps, so only the
        # worker path counts (an inline blocking stall is train-thread
        # time, the opposite of overlap).
        if self._worker is not None:
            try:
                with self._lock:
                    self._round_overlap_ms += float(sp.duration_ms)
            except (TypeError, ValueError):  # mocked span trackers
                pass

    def _collective_supports(self, wire_codec: str) -> bool:
        try:
            return wire_codec in getattr(
                self._manager.collective(), "wire_codecs", ()
            )
        except Exception:  # noqa: BLE001 — mocked managers
            return False

    def _wire_nbytes(self, payload, codec: FragmentCodec, wire_codec) -> int:
        """Per-hop wire bytes of one fragment payload, from the
        collective's own probe where available (the same source of truth
        the GB/s gauge uses)."""
        try:
            probe = getattr(self._manager.collective(), "wire_nbytes", None)
            if callable(probe):
                n = (
                    probe(payload, codec.allow_wire_compression, wire_codec)
                    if wire_codec is not None
                    else probe(payload, codec.allow_wire_compression)
                )
                return int(n)
        except Exception:  # noqa: BLE001 — mocked managers
            pass
        return int(np.asarray(payload).nbytes)

    def drain(self) -> Dict[int, np.ndarray]:
        """Blocks the TRAIN thread until every issued fragment round lands;
        returns {fragment index: averaged flat pseudogradient}.  Charged as
        ``allreduce_merge`` — this wait is the streaming plane's only
        train-thread cost, and exactly what the bench's overlap headline
        measures."""
        with self._lock:
            futures = list(self._futures)
        with self._manager.spans.span(
            "allreduce_merge", step=self._manager.current_step()
        ):
            for fut in futures:
                try:
                    fut.result()
                except Exception as e:  # noqa: BLE001 — latch, never raise
                    try:
                        self._manager.report_error(e)
                    except Exception:  # noqa: BLE001 — mocked managers
                        pass
        with self._lock:
            return dict(self._results)

    def round_stats(self) -> Dict[str, int]:
        """The round-so-far accounting.  Read AFTER drain() but BEFORE the
        commit vote when the caller wants the numbers in the same step's
        step_summary (the vote flushes that record)."""
        with self._lock:
            return {
                "fragments": self._round_fragments,
                "wire_bytes": self._round_wire_bytes,
                "d2h_bytes": self._round_d2h_bytes,
            }

    def promote_fragment(self, fragment: Fragment, committed: bool) -> None:
        """Per-fragment codec promotion for fragment-commit mode: promotes
        or discards ONE fragment's pending codec state (EF residuals) at
        its own vote, instead of the round-level sweep in end_round."""
        codec = self._codecs[fragment.index]
        if committed:
            codec.on_commit()
        else:
            codec.on_abort()

    def end_round(self, committed: bool, promote: bool = True) -> Dict[str, int]:
        """Round bookkeeping: promotes or discards every codec's pending
        state and reports the round's accounting.  ``promote=False``
        (fragment-commit mode) skips the codec sweep — each fragment's
        state was already settled at its own vote by promote_fragment."""
        if promote:
            for codec in self._codecs:
                if committed:
                    codec.on_commit()
                else:
                    codec.on_abort()
        self.metrics.observe_round(committed=committed)
        with self._lock:
            self.metrics.observe_overlap_ms(self._round_overlap_ms)
        return self.round_stats()

    def shutdown(self) -> None:
        if self._worker is not None:
            self._worker.shutdown(wait=True)
            self._worker = None
