"""Prometheus-style exposition for the semi-sync plane: ``tpuft_semisync_*``.

The lighthouse's native ``GET /metrics`` covers the control plane; the
semi-sync data plane is per-worker and Python-side, so it exposes its own
gauges the same text-format way: a :class:`SemiSyncMetrics` accumulates
counters from the engine, ``render_prometheus`` produces the exposition,
and ``serve`` (opt-in: ``TPUFT_SEMISYNC_METRICS_PORT``) publishes it on a
tiny stdlib HTTP endpoint at ``/metrics`` for the same scraper that
already hits the lighthouse.

Counters are monotonic since construction (restart = reset, standard
Prometheus counter semantics); gauges are last-observation.

DEPRECATED as a standalone endpoint: the worker-side exposition is unified
on :class:`torchft_tpu.obs.prom.WorkerMetrics` (one ``/metrics`` per
worker, ``TPUFT_WORKER_METRICS_PORT``), where the semisync engine now
registers this exposition as a section when a Manager endpoint is
serving.  ``TPUFT_SEMISYNC_METRICS_PORT`` keeps working as an alias for
the unified endpoint's port (one deprecation warning per process), and
:meth:`SemiSyncMetrics.serve` remains for manager-less embedders.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

__all__ = [
    "SemiSyncMetrics",
    "TPUFT_SEMISYNC_METRICS_PORT_ENV",
    "TPUFT_SEMISYNC_METRICS_BIND_ENV",
]

TPUFT_SEMISYNC_METRICS_PORT_ENV = "TPUFT_SEMISYNC_METRICS_PORT"
TPUFT_SEMISYNC_METRICS_BIND_ENV = "TPUFT_SEMISYNC_METRICS_BIND"


class SemiSyncMetrics:
    """Thread-safe counter/gauge set for one StreamingDiLoCo instance."""

    def __init__(self, codec: str = "", replica_id: str = "") -> None:
        self.codec = codec
        self.replica_id = replica_id
        self._lock = threading.Lock()
        self.fragments_total = 0
        self.rounds_total = 0
        self.commits_total = 0
        self.aborts_total = 0
        self.wire_bytes_total = 0
        self.d2h_bytes_total = 0
        self.last_residual_l2 = 0.0
        self.last_round_overlap_ms = 0.0
        self._server = None

    def observe_fragment(self, wire_bytes: int, d2h_bytes: int) -> None:
        with self._lock:
            self.fragments_total += 1
            self.wire_bytes_total += int(wire_bytes)
            self.d2h_bytes_total += int(d2h_bytes)

    def observe_round(self, committed: bool) -> None:
        with self._lock:
            self.rounds_total += 1
            if committed:
                self.commits_total += 1
            else:
                self.aborts_total += 1

    @property
    def serving(self) -> bool:
        """True while the HTTP exposition is up — consumers can use this
        to skip gauge computations nobody will scrape."""
        return self._server is not None

    def observe_residual(self, l2: float) -> None:
        with self._lock:
            self.last_residual_l2 = float(l2)

    def observe_overlap_ms(self, ms: float) -> None:
        with self._lock:
            self.last_round_overlap_ms = float(ms)

    def render_prometheus(self) -> str:
        """The ``tpuft_semisync_*`` exposition (Prometheus text format)."""
        with self._lock:
            label = ""
            if self.replica_id or self.codec:
                parts = []
                if self.replica_id:
                    parts.append(f'replica="{self.replica_id}"')
                if self.codec:
                    parts.append(f'codec="{self.codec}"')
                label = "{" + ",".join(parts) + "}"
            lines = []

            def metric(name: str, kind: str, help_: str, value) -> None:
                lines.append(f"# HELP {name} {help_}")
                lines.append(f"# TYPE {name} {kind}")
                lines.append(f"{name}{label} {value}")

            metric(
                "tpuft_semisync_fragments_total", "counter",
                "fragment pseudogradient rounds completed",
                self.fragments_total,
            )
            metric(
                "tpuft_semisync_rounds_total", "counter",
                "outer sync rounds finished (committed + aborted)",
                self.rounds_total,
            )
            metric(
                "tpuft_semisync_commits_total", "counter",
                "outer sync rounds that passed the commit vote",
                self.commits_total,
            )
            metric(
                "tpuft_semisync_aborts_total", "counter",
                "outer sync rounds discarded (error latched / vote lost)",
                self.aborts_total,
            )
            metric(
                "tpuft_semisync_wire_bytes_total", "counter",
                "per-hop wire bytes of fragment payloads (codec-encoded)",
                self.wire_bytes_total,
            )
            metric(
                "tpuft_semisync_d2h_bytes_total", "counter",
                "device->host fetch bytes of fragment payloads",
                self.d2h_bytes_total,
            )
            metric(
                "tpuft_semisync_residual_l2", "gauge",
                "L2 norm of the carried int8 error-feedback residual",
                self.last_residual_l2,
            )
            metric(
                "tpuft_semisync_round_overlap_ms", "gauge",
                "last round's background sync time overlapped with inner "
                "steps",
                self.last_round_overlap_ms,
            )
            return "\n".join(lines) + "\n"

    # -- optional HTTP exposition -------------------------------------------

    def serve(
        self, port: Optional[int] = None, bind: Optional[str] = None
    ) -> Optional[int]:
        """Starts a daemon HTTP server answering ``GET /metrics`` with the
        exposition.  ``port=None`` reads ``TPUFT_SEMISYNC_METRICS_PORT``
        (unset/empty = disabled, 0 = ephemeral); ``bind=None`` reads
        ``TPUFT_SEMISYNC_METRICS_BIND`` and defaults to loopback (``::1``
        — the server is the repo-wide dual-stack v6 class) — the endpoint
        is unauthenticated, so listening on every interface must be an
        explicit operator choice (``::``), not the default.  Returns the
        bound port, or None when disabled.  Never raises — metrics must
        not be able to fail training."""
        if port is None:
            raw = os.environ.get(TPUFT_SEMISYNC_METRICS_PORT_ENV, "")
            if not raw.strip():
                return None
            try:
                port = int(raw)
            except ValueError:
                return None
        if bind is None:
            bind = os.environ.get(
                TPUFT_SEMISYNC_METRICS_BIND_ENV, ""
            ).strip() or "::1"
        # The repo's one exposition scaffolding (torchft_tpu/http.py) —
        # every Python-side metrics endpoint shares it, so v6 handling and
        # accept-queue fixes apply uniformly.
        from torchft_tpu.http import serve_text_exposition

        server = serve_text_exposition(
            self.render_prometheus, port, bind,
            thread_name="tpuft_semisync_metrics",
        )
        if server is None:
            return None
        self._server = server
        return server.server_address[1]

    def close(self) -> None:
        server, self._server = self._server, None
        if server is not None:
            try:
                server.shutdown()
                server.server_close()
            except Exception:  # noqa: BLE001
                pass
