"""Per-fragment wire preparation for the semi-sync pseudogradient plane.

A fragment codec owns the step from "the fragment's live leaves + the
last-committed backup" to "the host payload handed to the cross-group
ring", per fragment:

  pseudogradient:  pg = backup - local     (the paper sign, DiLoCo
                                            arXiv:2311.08105 — an outer SGD
                                            *descent* step moves the global
                                            params toward averaged local
                                            progress)

``int8`` — **int8 + error feedback** (the new wire codec this subsystem
introduces): the fragment is quantized at the SOURCE with a per-fragment
scale (amax/127) after adding the residual the previous round failed to
transmit, and the new residual ``x - q*scale`` is carried forward — on
device, inside the same jitted per-fragment epilogue that computes the
pseudogradient (PR 8's device wire-prep hook), so the D2H fetch moves int8
bytes (~0.25x of f32) and the ring then wires scale+int8 frames
(``wire_codec="int8"``, collectives.py).  Pseudogradients tolerate this
because error feedback turns per-round quantization error into a
one-round delay instead of a loss; raw weights do NOT — LocalSGD's
parameter averaging stays full-width, unchanged.

``bf16`` / ``f32`` — the fallback knob (``TPUFT_SEMISYNC_CODEC``): bf16
casts the pseudogradient on device and wires bf16 (0.5x); f32 opts the
sync out of every lossy encoding; ``auto`` defers to the collective's own
wire policy (the legacy DiLoCo port's behavior — bf16 only when the link
profile says bandwidth-bound).

Every codec works on host (numpy) leaves too — the device path engages
only when all of a fragment's leaves are jax arrays, mirroring the DDP
device-bucket eligibility gate.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from torchft_tpu.semisync.fragments import Fragment, pack_flat

__all__ = [
    "CODECS",
    "TPUFT_SEMISYNC_CODEC_ENV",
    "FragmentCodec",
    "make_codec",
]

TPUFT_SEMISYNC_CODEC_ENV = "TPUFT_SEMISYNC_CODEC"
CODECS = ("int8", "int4", "bf16", "f32", "auto")


def _all_jax(leaves: Sequence[Any]) -> bool:
    try:
        import jax

        return all(isinstance(l, jax.Array) for l in leaves)
    except ImportError:
        return False


def _device_flat(leaves: List[Any], dtype):
    """The jit-side counterpart of ``fragments.pack_flat``: one flat device
    array of ``dtype`` from a leaf list — shared by every jitted encoder so
    the three epilogues cannot drift in their flatten prologue."""
    import jax.numpy as jnp

    flat = (
        jnp.concatenate([jnp.ravel(l) for l in leaves])
        if len(leaves) > 1
        else jnp.ravel(leaves[0])
    )
    return flat.astype(dtype)


class FragmentCodec:
    """Base: raw pseudogradient in the fragment dtype, no compression.

    Subclasses override :meth:`_encode_host` / :meth:`_encode_device` and
    the wire-policy properties.  One codec instance per fragment — codecs
    are stateful (the int8 residual) and cache their jitted epilogues.
    """

    name = "f32"
    #: allow the collective's own lossy wire encoding (bf16-if-shaped)?
    allow_wire_compression = False
    #: explicit per-call wire codec for collectives that support it
    wire_codec: Optional[str] = None

    def __init__(self, fragment: Fragment) -> None:
        self.fragment = fragment
        self._backup_dev: Any = None  # device mirror, built lazily
        self._backup_host: Optional[np.ndarray] = None

    @property
    def _work_dtype(self) -> np.dtype:
        """The dtype the codec's pseudogradient math runs in.  The base
        (f32/auto) codecs keep the FRAGMENT dtype — an f64 fragment must
        not be silently downcast by a codec whose whole point is "no lossy
        encoding".  Quantizing codecs override (int8's residual math is
        f32 by construction)."""
        return self.fragment.dtype

    @property
    def payload_dtype(self) -> np.dtype:
        """The dtype of the host payload :meth:`encode` hands the ring.
        Non-participating groups must contribute zeros of EXACTLY this
        dtype: the ring's per-hop frame sizes derive from each rank's
        payload dtype, so a mismatched placeholder breaks the cross-rank
        frame contract."""
        return self._work_dtype

    def zero_payload(self) -> np.ndarray:
        return np.zeros(self.fragment.numel, dtype=self.payload_dtype)

    # -- backup management --------------------------------------------------

    def set_backup(self, flat_host: np.ndarray) -> None:
        """Installs the fragment's last-committed flat backup (host).  The
        device mirror is invalidated and re-uploaded lazily on the next
        device-path encode — callers on the host path never pay the H2D."""
        self._backup_host = np.ascontiguousarray(
            np.asarray(flat_host).astype(self._work_dtype, copy=False)
        )
        self._backup_dev = None

    def _backup_device(self):
        import jax

        if self._backup_dev is None:
            self._backup_dev = jax.device_put(self._backup_host)
        return self._backup_dev

    # -- encode -------------------------------------------------------------

    def encode(self, leaves: Sequence[Any]) -> Tuple[np.ndarray, int]:
        """(host payload for the ring, d2h bytes fetched).  ``leaves`` is
        the FULL tree leaf list; the fragment picks its own.  The d2h
        charge counts only bytes that actually crossed the device boundary
        — a pure-host (numpy) tree fetches nothing, and the telemetry must
        not claim it did."""
        frag_leaves = [leaves[i] for i in self.fragment.bucket.indices]
        if self.fragment.lossy_ok and _all_jax(frag_leaves):
            return self._encode_device(frag_leaves)
        payload = self._encode_host(frag_leaves)
        d2h = 0
        try:
            import jax

            d2h = sum(
                int(getattr(l, "nbytes", 0))
                for l in frag_leaves
                if isinstance(l, jax.Array)
            )
        except ImportError:
            pass
        return payload, d2h

    def _pack_local(self, frag_leaves: Sequence[Any]) -> np.ndarray:
        # The same flatten+cast the fragment's own pack uses — one
        # implementation, so the two packing paths cannot drift.
        return pack_flat(frag_leaves, self._work_dtype)

    def _encode_host(self, frag_leaves: Sequence[Any]) -> np.ndarray:
        local = self._pack_local(frag_leaves)
        return (self._backup_host - local).astype(local.dtype, copy=False)

    def _encode_device(self, frag_leaves: Sequence[Any]) -> Tuple[np.ndarray, int]:
        fn = self._jitted_pg()
        out = fn(frag_leaves, self._backup_device())
        host = np.asarray(out)
        return host, int(host.nbytes)

    def _jitted_pg(self):
        if getattr(self, "_pg_fn", None) is None:
            import jax

            def pg(leaves: List[Any], backup):
                return backup - _device_flat(leaves, backup.dtype)

            self._pg_fn = jax.jit(pg)
        return self._pg_fn

    # -- round lifecycle ----------------------------------------------------

    def on_commit(self) -> None:
        """The round's averaged pseudogradient was applied."""

    def on_abort(self) -> None:
        """The round failed (error latched / commit vote lost): any
        codec-internal state tied to the discarded transmission is reset."""


class _AutoCodec(FragmentCodec):
    """Legacy-port parity: f32 payload, collective decides the wire
    (bf16 only when the link profile says bandwidth-bound)."""

    name = "auto"
    allow_wire_compression = True


class _BF16Codec(FragmentCodec):
    """Pseudogradient cast to bfloat16 on device (or host fallback): the
    D2H fetch and the ring wire both move 2 bytes/element.  The collective
    treats already-bf16 payloads as pre-encoded (f32 accumulation)."""

    name = "bf16"
    allow_wire_compression = True

    @property
    def _work_dtype(self) -> np.dtype:
        # Quantizing codec: math in f32 (the cast to bf16 IS the encoding;
        # doing the subtraction in f64 would buy nothing past the cast).
        return np.dtype(np.float32)

    @property
    def payload_dtype(self) -> np.dtype:
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)

    def _encode_host(self, frag_leaves):
        import ml_dtypes

        local = self._pack_local(frag_leaves)
        return (self._backup_host - local).astype(ml_dtypes.bfloat16)

    def _encode_device(self, frag_leaves):
        if getattr(self, "_bf16_fn", None) is None:
            import jax
            import jax.numpy as jnp

            def enc(leaves: List[Any], backup):
                local = _device_flat(leaves, backup.dtype)
                return (backup - local).astype(jnp.bfloat16)

            self._bf16_fn = jax.jit(enc)
        out = self._bf16_fn(frag_leaves, self._backup_device())
        host = np.asarray(out)
        return host, int(host.nbytes)


class _Int8EFCodec(FragmentCodec):
    """int8 + error feedback (see module docstring).

    Device path: ONE jitted epilogue computes pg, adds the carried
    residual, derives the per-fragment scale, quantizes, and produces the
    next residual — the residual never leaves the device and the D2H fetch
    is int8 + one f32 scale.  Host path mirrors the math in numpy.

    The ring still requantizes per chunk/hop (scale+int8 frames,
    collectives.py ``wire_codec="int8"``); the residual captures the
    SOURCE quantization error, which dominates.  On a failed round the
    pending residual is discarded (on_abort): the transmission it
    described never landed anywhere, and the next round's pseudogradient
    re-derives the full difference from scratch.
    """

    name = "int8"
    allow_wire_compression = True
    wire_codec = "int8"

    @property
    def _work_dtype(self) -> np.dtype:
        # Quantizing codec: residual math and the dequantized payload are
        # f32 by construction (int8's 8-bit mantissa makes wider inputs
        # pointless past the quantizer).
        return np.dtype(np.float32)

    def __init__(self, fragment: Fragment) -> None:
        super().__init__(fragment)
        self._residual_host: Optional[np.ndarray] = None
        self._residual_dev: Any = None
        # Set by encode, promoted to the carried residual on commit,
        # discarded on abort — a failed sync must not corrupt EF state.
        self._pending_residual: Any = None
        self._pending_on_device = False

    def _residual(self, device: bool):
        if device:
            if self._residual_dev is None:
                import jax
                import jax.numpy as jnp

                if self._residual_host is not None:
                    self._residual_dev = jax.device_put(
                        self._residual_host.astype(np.float32)
                    )
                else:
                    self._residual_dev = jnp.zeros(
                        self.fragment.numel, dtype=jnp.float32
                    )
            return self._residual_dev
        if self._residual_host is None:
            self._residual_host = np.zeros(self.fragment.numel, dtype=np.float32)
        return self._residual_host

    def residual_l2(self) -> float:
        """Diagnostic: L2 norm of the carried residual (telemetry only).
        The device-resident residual is reduced ON DEVICE and only the
        scalar is fetched — a full-width D2H here would cost 4x the int8
        payload fetch the codec exists to avoid."""
        if self._residual_host is not None:
            return float(np.linalg.norm(self._residual_host))
        if self._residual_dev is not None:
            import jax.numpy as jnp

            return float(jnp.linalg.norm(self._residual_dev))
        return 0.0

    def _encode_host(self, frag_leaves):
        from torchft_tpu.collectives import quantize_int8

        local = self._pack_local(frag_leaves)
        x = (self._backup_host - local) + self._residual(device=False)
        scale, q = quantize_int8(x)
        deq = q.astype(np.float32) * np.float32(scale)
        # Non-finite elements cannot ride the wire (quantize_int8 encodes
        # NaN as 0, inf saturated); their residual is zeroed, not carried —
        # a NaN residual would force scale=1 garbage on every later round.
        self._pending_residual = np.where(np.isfinite(x), x - deq, 0.0).astype(
            np.float32
        )
        self._pending_on_device = False
        return deq

    def _encode_device(self, frag_leaves):
        import jax

        if getattr(self, "_enc_fn", None) is None:
            import jax.numpy as jnp

            def enc(leaves: List[Any], backup, residual):
                # Mirrors collectives.quantize_int8 (the host twin),
                # including the non-finite rules: NaN encodes as 0, inf
                # saturates, and non-finite elements carry a ZERO residual.
                local = _device_flat(leaves, jnp.float32)
                x = (backup - local) + residual
                amax = jnp.max(jnp.abs(x))
                scale = jnp.where(
                    (amax > 0) & jnp.isfinite(amax), amax / 127.0, 1.0
                ).astype(jnp.float32)
                scaled = jnp.nan_to_num(x / scale, nan=0.0)
                q = jnp.clip(jnp.round(scaled), -127, 127).astype(jnp.int8)
                new_residual = jnp.where(
                    jnp.isfinite(x), x - q.astype(jnp.float32) * scale, 0.0
                )
                return q, scale, new_residual

            self._enc_fn = jax.jit(enc)
        q, scale, new_residual = self._enc_fn(
            frag_leaves, self._backup_device(), self._residual(device=True)
        )
        # Fetch int8 + the scalar scale — the 0.25x D2H the codec exists
        # for; the residual stays resident on device.
        q_host = np.asarray(q)
        s = float(np.asarray(scale))
        self._pending_residual = new_residual
        self._pending_on_device = True
        deq = q_host.astype(np.float32) * np.float32(s)
        return deq, int(q_host.nbytes) + 4

    def on_commit(self) -> None:
        if self._pending_residual is None:
            return
        if self._pending_on_device:
            self._residual_dev = self._pending_residual
            self._residual_host = None
        else:
            self._residual_host = self._pending_residual
            self._residual_dev = None
        self._pending_residual = None

    def on_abort(self) -> None:
        # Discard BOTH the pending and the carried residual: the carried
        # one described a delta relative to a transmission history the
        # failed round just invalidated, and the next round's pg re-derives
        # the full backup-local difference anyway.
        self._pending_residual = None
        self._residual_host = None
        self._residual_dev = None


class _Int4EFCodec(_Int8EFCodec):
    """int4 + error feedback: the Streaming-DiLoCo design point
    (arXiv:2501.18512 wires 4-bit outer gradients) — same source-side
    quantize + residual carry as int8, but the per-fragment scale is
    amax/7 and values clip to [-7, 7], so the ring's ``wire_codec="int4"``
    packs two elements per byte (0.125x the f32 wire per hop).

    The D2H fetch on the device path still moves one int8-typed byte per
    element (nibble packing is a host-side wire concern; a device gather
    into packed nibbles would cost more than the fetch saves) — the 4-bit
    saving is on the CROSS-GROUP WIRE, which is the DiLoCo bottleneck.
    EF semantics are inherited unchanged: pending residual promoted on
    commit, all residual state discarded on abort.
    """

    name = "int4"
    wire_codec = "int4"

    def _encode_host(self, frag_leaves):
        from torchft_tpu.collectives import quantize_int4

        local = self._pack_local(frag_leaves)
        x = (self._backup_host - local) + self._residual(device=False)
        scale, q = quantize_int4(x)
        deq = q.astype(np.float32) * np.float32(scale)
        self._pending_residual = np.where(np.isfinite(x), x - deq, 0.0).astype(
            np.float32
        )
        self._pending_on_device = False
        return deq

    def _encode_device(self, frag_leaves):
        import jax

        if getattr(self, "_enc4_fn", None) is None:
            import jax.numpy as jnp

            def enc(leaves: List[Any], backup, residual):
                # Mirrors collectives.quantize_int4 (the host twin),
                # including the non-finite rules: NaN encodes as 0, inf
                # saturates, non-finite elements carry a ZERO residual.
                local = _device_flat(leaves, jnp.float32)
                x = (backup - local) + residual
                amax = jnp.max(jnp.abs(x))
                scale = jnp.where(
                    (amax > 0) & jnp.isfinite(amax), amax / 7.0, 1.0
                ).astype(jnp.float32)
                scaled = jnp.nan_to_num(x / scale, nan=0.0)
                q = jnp.clip(jnp.round(scaled), -7, 7).astype(jnp.int8)
                new_residual = jnp.where(
                    jnp.isfinite(x), x - q.astype(jnp.float32) * scale, 0.0
                )
                return q, scale, new_residual

            self._enc4_fn = jax.jit(enc)
        q, scale, new_residual = self._enc4_fn(
            frag_leaves, self._backup_device(), self._residual(device=True)
        )
        q_host = np.asarray(q)
        s = float(np.asarray(scale))
        self._pending_residual = new_residual
        self._pending_on_device = True
        deq = q_host.astype(np.float32) * np.float32(s)
        return deq, int(q_host.nbytes) + 4


_CODEC_CLASSES = {
    "f32": FragmentCodec,
    "auto": _AutoCodec,
    "bf16": _BF16Codec,
    "int8": _Int8EFCodec,
    "int4": _Int4EFCodec,
}


def make_codec(name: str, fragment: Fragment) -> FragmentCodec:
    """Codec instance for one fragment.  Fragments ineligible for lossy
    encodings (integer / sub-f32 dtypes) always get the raw base codec,
    whatever was requested — the same full-width guarantee the DDP wire
    compression gate gives scalars and integer buckets."""
    if name not in _CODEC_CLASSES:
        raise ValueError(f"unknown semisync codec {name!r}; expected {CODECS}")
    if not fragment.lossy_ok and name in ("int8", "int4", "bf16"):
        return FragmentCodec(fragment)
    return _CODEC_CLASSES[name](fragment)
