"""torchft_tpu.semisync — the streaming semi-sync (DiLoCo) data plane.

Makes communication-efficient outer-loop synchronization first-class for
the cross-region / low-bandwidth links torchft targets with LocalSGD:
the outer state is fragmented on the shared bucket planner, each
fragment's pseudogradient round streams in the background of inner steps
over the striped multi-lane ring (``ring2d`` at high group counts), the
wire rides an **int8 + error-feedback** codec (bf16/f32 fallback knob),
and the per-fragment outer optimizer applies only after the commit vote —
a failed sync can never corrupt the model, the backup, or the outer
state.

Layout:
  fragments.py  fragment planning (ddp.plan_buckets underneath) + slots
  codec.py      int8+EF / bf16 / f32 / auto wire preparation (jitted)
  engine.py     the background fragment-sync worker
  diloco.py     StreamingDiLoCo (the user-facing algorithm)
  metrics.py    tpuft_semisync_* Prometheus exposition

``torchft_tpu.local_sgd.DiLoCo`` is preserved as a thin blocking wrapper
over this engine; see docs/architecture.md "Streaming semi-sync data
plane".
"""

from torchft_tpu.semisync.codec import (
    CODECS,
    TPUFT_SEMISYNC_CODEC_ENV,
    FragmentCodec,
    make_codec,
)
from torchft_tpu.semisync.diloco import StreamingDiLoCo, TPUFT_SEMISYNC_STREAM_ENV
from torchft_tpu.semisync.engine import SyncEngine
from torchft_tpu.semisync.fragments import (
    DEFAULT_FRAGMENT_BYTES,
    TPUFT_SEMISYNC_FRAGMENT_BYTES_ENV,
    Fragment,
    FragmentPlan,
)
from torchft_tpu.semisync.metrics import (
    TPUFT_SEMISYNC_METRICS_BIND_ENV,
    TPUFT_SEMISYNC_METRICS_PORT_ENV,
    SemiSyncMetrics,
)

__all__ = [
    "StreamingDiLoCo",
    "SyncEngine",
    "Fragment",
    "FragmentPlan",
    "FragmentCodec",
    "make_codec",
    "SemiSyncMetrics",
    "CODECS",
    "DEFAULT_FRAGMENT_BYTES",
    "TPUFT_SEMISYNC_CODEC_ENV",
    "TPUFT_SEMISYNC_FRAGMENT_BYTES_ENV",
    "TPUFT_SEMISYNC_STREAM_ENV",
    "TPUFT_SEMISYNC_METRICS_PORT_ENV",
    "TPUFT_SEMISYNC_METRICS_BIND_ENV",
]
