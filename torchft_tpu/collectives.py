"""Reconfigurable collectives for the fault-tolerant replica dimension.

Reference parity: torchft/process_group.py.  The reference reconfigures torch
c10d ProcessGroups (Gloo/NCCL) on every quorum change; XLA has no notion of a
dynamically sized mesh — a compiled program's collectives are fixed at trace
time — so the cross-replica-group dimension lives at the host layer: a
``Collective`` moves host buffers between replica groups over TCP (the DCN
path), while all intra-group parallelism stays inside the pjit-compiled
program over ICI (see torchft_tpu/parallel/).

Semantics carried over from the reference:
  - ``configure(store_addr, rank, world_size)`` tears down the old
    communicator and rendezvouses a new one; safe to call at every quorum
    change (torchft/process_group.py:253-268).
  - operations return ``Work`` futures; errors are latched and surfaced via
    ``errored()`` rather than raised into the train loop
    (torchft/process_group.py:333-349).
  - ``abort()`` cancels in-flight operations without killing the process —
    the analogue of NCCL abort (torchft/process_group.py:650-727).
"""

from __future__ import annotations

import collections
import math
import mmap
import os
import select
import socket
import struct
import threading
import time
from abc import ABC, abstractmethod
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence, cast

import numpy as np

from torchft_tpu._native import StoreClient
from torchft_tpu.futures import completed_future, failed_future

__all__ = [
    "Work",
    "Collective",
    "DummyCollective",
    "TCPCollective",
    "ErrorSwallowingCollective",
    "ManagedCollective",
    "WIRE_CODECS",
    "quantize_int8",
    "quantize_int4",
    "pack_int4",
    "unpack_int4",
]

# Elementwise combine per reduce op ("avg" divides by world size after the
# sum).  Membership doubles as the validity check for allreduce/
# reduce_scatter op arguments.
_REDUCE_COMBINE = {
    "sum": np.add,
    "avg": np.add,
    "max": np.maximum,
    "min": np.minimum,
}


def _bad_reduce_op(op: str) -> ValueError:
    return ValueError(
        f"unsupported reduce op {op!r}; expected one of {sorted(_REDUCE_COMBINE)}"
    )


# Optional per-call wire codecs (TCPCollective.allreduce(wire_codec=...)).
# "int8": symmetric linear quantization, per-chunk scale = amax/127,
# accumulation in float32 — ~0.25x the f32 wire (plus 4 scale bytes per
# frame).  "int4": the same shape packed two values per byte, per-chunk
# scale = amax/7 — 0.125x the f32 wire, the Streaming-DiLoCo design point
# (arXiv:2501.18512 quantizes outer gradients to 4 bits).  Both are lossy
# per hop exactly like the bf16 wire; meant for payloads with a
# source-side error-feedback loop (the semisync pseudogradient plane,
# torchft_tpu/semisync), never for raw weights.
WIRE_CODECS = ("int8", "int4")


def quantize_int8(x: np.ndarray):
    """``(scale, q)`` — THE symmetric int8 quantizer (host side): scale =
    amax/127, round-to-nearest, clipped to [-127, 127].  One
    implementation shared by the ring codec, the semisync EF codec's host
    path, and the bench's drift cells, so the guard rules cannot drift
    between them.  Non-finite handling: an inf/NaN amax falls back to
    scale 1 (a NaN scale would silently zero the whole chunk); inf
    elements saturate to +/-127; NaN elements encode as 0 EXPLICITLY
    (np.rint(nan).astype(int8) is 0 only by C-cast accident) — the wire
    cannot represent NaN, so divergence must be caught by loss/grad-norm
    monitoring, and the EF codec zeroes those elements' residuals rather
    than carrying NaN forward.  The jitted device twin lives in
    torchft_tpu/semisync/codec.py."""
    x = np.asarray(x)
    if x.dtype != np.float32:
        x = x.astype(np.float32)
    amax = float(np.max(np.abs(x))) if x.size else 0.0
    scale = amax / 127.0 if (amax > 0.0 and math.isfinite(amax)) else 1.0
    q = np.clip(
        np.rint(np.nan_to_num(x / scale, nan=0.0)), -127, 127
    ).astype(np.int8)
    return scale, q


def quantize_int4(x: np.ndarray):
    """``(scale, q)`` — the symmetric int4 quantizer (host side): scale =
    amax/7, round-to-nearest, clipped to [-7, 7].  ``q`` is int8-typed but
    every value fits a signed nibble; :func:`pack_int4` is the wire
    packing.  The same non-finite guard rules as :func:`quantize_int8`
    (one shared contract pinned by the codec tests); the jitted device
    twin lives in torchft_tpu/semisync/codec.py."""
    x = np.asarray(x)
    if x.dtype != np.float32:
        x = x.astype(np.float32)
    amax = float(np.max(np.abs(x))) if x.size else 0.0
    scale = amax / 7.0 if (amax > 0.0 and math.isfinite(amax)) else 1.0
    q = np.clip(
        np.rint(np.nan_to_num(x / scale, nan=0.0)), -7, 7
    ).astype(np.int8)
    return scale, q


def pack_int4(q: np.ndarray) -> np.ndarray:
    """Packs signed-nibble values (int8 in [-7, 7]) two per byte: element
    2i in the LOW nibble, 2i+1 in the HIGH nibble, two's complement — the
    exact frame layout native/src/ring.cc's Int4Encode emits, so both
    engines' int4 wire bytes are bitwise-identical.  An odd tail leaves
    the final high nibble zero."""
    u = (q.astype(np.int16) & 0xF).astype(np.uint8)
    if u.size % 2:
        u = np.concatenate([u, np.zeros(1, dtype=np.uint8)])
    return (u[0::2] | (u[1::2] << 4)).astype(np.uint8)


def unpack_int4(raw, n: int) -> np.ndarray:
    """Inverse of :func:`pack_int4`: ``n`` signed int8 values from the
    packed nibble stream (sign-extended via ``(nib ^ 8) - 8``)."""
    b = np.frombuffer(raw, dtype=np.uint8)
    nib = np.empty(b.size * 2, dtype=np.int16)
    nib[0::2] = b & 0xF
    nib[1::2] = b >> 4
    return ((nib[:n] ^ 8) - 8).astype(np.int8)


def _is_bf16(dtype) -> bool:
    """True for the ml_dtypes bfloat16 dtype.  bf16 does NOT register under
    ``np.issubdtype(..., np.floating)`` — every floating-dtype gate in this
    module that must also admit already-wire-dtype payloads checks this
    explicitly."""
    import ml_dtypes

    return np.dtype(dtype) == np.dtype(ml_dtypes.bfloat16)


class Work:
    """Handle for an async collective operation (the c10d Work analogue)."""

    def __init__(self, future: Future) -> None:
        self._future = future

    def wait(self, timeout: Optional[float] = None):
        return self._future.result(timeout=timeout)

    def result(self, timeout: Optional[float] = None):
        return self._future.result(timeout=timeout)

    def done(self) -> bool:
        return self._future.done()

    def exception(self, timeout: Optional[float] = None):
        return self._future.exception(timeout=timeout)

    def future(self) -> Future:
        return self._future

    def add_done_callback(self, fn: Callable[[Future], None]) -> None:
        self._future.add_done_callback(fn)


class Collective(ABC):
    """Abstract reconfigurable collective over the replica-group dimension.

    The full collective surface of the reference's ProcessGroup
    (torchft/process_group.py:115-251) mapped to host arrays: allreduce,
    allgather, broadcast, reduce_scatter, alltoall, barrier, send/recv.
    """

    @abstractmethod
    def configure(self, store_addr: str, rank: int, world_size: int) -> None:
        """(Re)builds the communicator; aborts any previous one.  store_addr
        is "host:port/prefix" — a unique prefix per quorum round prevents
        rendezvous collisions with stale rounds (torchft/manager.py:503)."""

    @abstractmethod
    def allreduce(
        self,
        arrays: Sequence[np.ndarray],
        op: str = "sum",
        allow_wire_compression: bool = True,
    ) -> Work:
        """Elementwise reduction across ranks; results replace `arrays`
        contents in the returned Work's result list.

        allow_wire_compression=False opts this call out of lossy wire
        encodings (wire_dtype="bf16"): gradient-like payloads tolerate
        per-hop bf16 rounding, but direct PARAMETER averaging (LocalSGD)
        must not accumulate quantization across syncs."""

    @abstractmethod
    def allgather(self, array: np.ndarray) -> Work:
        """Gathers each rank's array; result is a list of world_size arrays."""

    @abstractmethod
    def broadcast(self, array: np.ndarray, root: int = 0) -> Work:
        """Broadcasts root's array to all ranks; result is the array."""

    @abstractmethod
    def reduce_scatter(self, arrays: Sequence[np.ndarray], op: str = "sum") -> Work:
        """Reduces world_size equal chunks and scatters: rank i receives the
        reduction of every rank's arrays[i]."""

    @abstractmethod
    def alltoall(self, arrays: Sequence[np.ndarray]) -> Work:
        """Rank i sends arrays[j] to rank j; result is the received list."""

    @abstractmethod
    def send(self, array: np.ndarray, dst: int, tag: int = 0) -> Work:
        ...

    @abstractmethod
    def recv(self, shape: tuple, dtype, src: int, tag: int = 0) -> Work:
        ...

    @abstractmethod
    def barrier(self) -> Work:
        ...

    @abstractmethod
    def size(self) -> int:
        ...

    @abstractmethod
    def rank(self) -> int:
        ...

    def abort(self) -> None:
        """Cancels in-flight work and poisons the communicator until the next
        configure()."""

    def errored(self) -> Optional[Exception]:
        """Returns the latched error, if any."""
        return None

    def shutdown(self) -> None:
        self.abort()


class DummyCollective(Collective):
    """World-size-1 no-op collective: copies inputs to outputs and completes
    immediately.  Used to soak init-time collectives and as post-error
    placeholder (reference: ProcessGroupDummy, torchft/process_group.py:730-864)."""

    wire_codecs = WIRE_CODECS  # accepted (and ignored: world size 1)

    def __init__(self, rank: int = 0, world_size: int = 1) -> None:
        self._rank = rank
        self._world_size = world_size
        self.configure_count = 0

    def configure(self, store_addr: str, rank: int, world_size: int) -> None:
        self._rank = rank
        self._world_size = world_size
        self.configure_count += 1

    def allreduce(
        self,
        arrays: Sequence[np.ndarray],
        op: str = "sum",
        allow_wire_compression: bool = True,
        wire_codec: Optional[str] = None,
    ) -> Work:
        out = [np.array(a, copy=True) for a in arrays]
        if op == "avg":
            out = [a / 1.0 for a in out]
        return Work(completed_future(out))

    def allgather(self, array: np.ndarray) -> Work:
        return Work(completed_future([np.array(array, copy=True)]))

    def broadcast(self, array: np.ndarray, root: int = 0) -> Work:
        return Work(completed_future(np.array(array, copy=True)))

    def reduce_scatter(self, arrays: Sequence[np.ndarray], op: str = "sum") -> Work:
        return Work(completed_future(np.array(arrays[0], copy=True)))

    def alltoall(self, arrays: Sequence[np.ndarray]) -> Work:
        return Work(completed_future([np.array(a, copy=True) for a in arrays]))

    def send(self, array: np.ndarray, dst: int, tag: int = 0) -> Work:
        return Work(completed_future(None))

    def recv(self, shape: tuple, dtype, src: int, tag: int = 0) -> Work:
        return Work(completed_future(np.zeros(shape, dtype)))

    def barrier(self) -> Work:
        return Work(completed_future(None))

    def size(self) -> int:
        return self._world_size

    def rank(self) -> int:
        return self._rank


# ---------------------------------------------------------------------------
# TCP ring collective — the cross-group (DCN) data plane.
# ---------------------------------------------------------------------------

_HDR = struct.Struct("<IQ")  # tag, nbytes

# Per-chunk scale header for the int8 wire codec (see _codec): one f32
# scale prefixes each quantized frame, so every hop can decode without any
# out-of-band scale exchange and the allgather phase's byte-forwarding
# stays self-contained (replica consistency: every rank decodes the same
# scale+payload bytes).
_INT8_SCALE = struct.Struct("<f")


class LinkShaper:
    """DCN-shaped link emulation for transport validation on localhost.

    Applied at the sender: each frame pays half the RTT (propagation) and
    its bytes are paced at the configured bandwidth (serialization), so a
    loopback TCP link behaves like a latency/bandwidth-bound cross-site
    link.  Enabled for all TCPCollective peers via
    ``TPUFT_SHAPED_LINK="<mbps>:<rtt_ms>"``; wire-byte counters let tests
    assert traffic (e.g. the bf16 wire halving) without timing flakiness.

    The serialization budget is a shared VIRTUAL-TIME pacer: concurrent
    senders (the multi-lane ring shares ONE shaper per peer direction)
    queue on the modeled link, so adding lanes cannot multiply the modeled
    bandwidth — lanes may only win by overlapping propagation (the half-RTT
    per frame) and host-side work with serialization, exactly the physics
    of parallel TCP streams on one bottleneck link.
    """

    def __init__(self, mbps: float, rtt_ms: float) -> None:
        self.bytes_per_s = mbps * 1e6 / 8.0
        self.half_rtt_s = rtt_ms / 2000.0
        self._bytes_sent = 0
        self._frames_sent = 0
        # Time actually slept waiting out the modeled serialization +
        # propagation — the "shaping" bucket of obs.report's
        # link_attribution split.
        self._wait_s = 0.0
        # When the native ring engine owns this direction's sends, its
        # pacer does the counting; the hook keeps the byte-accounting
        # surface (tests, benches) engine-agnostic.
        self._native_read: Optional[Callable[[], tuple]] = None
        self._native_wait: Optional[Callable[[], float]] = None
        self._lock = threading.Lock()
        # Virtual time (monotonic clock) until which the modeled link is
        # busy serializing already-admitted frames.
        self._busy_until = 0.0

    @property
    def bytes_sent(self) -> int:
        if self._native_read is not None:
            return self._native_read()[0]
        return self._bytes_sent

    @property
    def frames_sent(self) -> int:
        if self._native_read is not None:
            return self._native_read()[1]
        return self._frames_sent

    @property
    def wait_s(self) -> float:
        """Seconds senders actually slept in this pacer (shaping time)."""
        if self._native_wait is not None:
            return self._native_wait()
        return self._wait_s

    def set_rate(self, mbps: float, rtt_ms: float) -> None:
        """Mid-run re-shaping (the slow-link bench degrades ONE peer
        direction without a reconfigure).  ``mbps <= 0`` disables the
        pacing — matching the native engine's SetRate contract, and
        avoiding a divide-by-zero in on_send."""
        with self._lock:
            if mbps > 0:
                self.bytes_per_s = mbps * 1e6 / 8.0
                self.half_rtt_s = rtt_ms / 2000.0
            else:
                self.bytes_per_s = float("inf")
                self.half_rtt_s = 0.0

    @classmethod
    def from_env(cls) -> Optional["LinkShaper"]:
        spec = os.environ.get("TPUFT_SHAPED_LINK")
        if not spec:
            return None
        mbps, _, rtt = spec.partition(":")
        return cls(float(mbps), float(rtt or "0"))

    def delay_s(self, nbytes: int) -> float:
        return self.half_rtt_s + nbytes / self.bytes_per_s

    def on_send(self, nbytes: int) -> None:
        with self._lock:
            self._bytes_sent += nbytes
            self._frames_sent += 1
            now = time.monotonic()
            start = max(now, self._busy_until)
            self._busy_until = start + nbytes / self.bytes_per_s
            # Frame is delivered once its bytes clear the shared link plus
            # one-way propagation; a lone sender sees exactly the legacy
            # delay (serialization + half RTT per frame back-to-back).
            wake = self._busy_until + self.half_rtt_s
        remaining = wake - time.monotonic()
        if remaining > 0:
            time.sleep(remaining)
            with self._lock:
                self._wait_s += remaining


# -- data-plane flight recorder (docs/architecture.md "Data-plane
# observability") ----------------------------------------------------------
# Per-hop telemetry from the ring hot loop, recorded IDENTICALLY by both
# engines: the Python loops below feed a HopRecorder, the native engine
# records inside RingPass (native/src/ring.cc RingHopRecord) — same field
# set, same semantics, schema-pinned against each other by
# tests/test_link.py.  ``TPUFT_HOP_SAMPLE`` records every Nth hop into the
# bounded timeline ring (0 keeps only the cheap per-tier aggregates);
# ``TPUFT_HOP_RING`` bounds the retained timeline.
TPUFT_HOP_SAMPLE_ENV = "TPUFT_HOP_SAMPLE"
TPUFT_HOP_RING_ENV = "TPUFT_HOP_RING"
_HOP_RING_DEFAULT = 2048

# The cross-engine hop-record schema: ts = wall-clock seconds at hop
# start; tier 0 flat / 1 row / 2 col; send_s = blocked joining the lane
# sender (includes link pacing); recv_s = blocked on the matching inbound
# frame; comb_s = decode + combine of the received chunk (reduce-scatter
# hops; 0 on allgather forwards); nbytes = frame payload bytes sent.
HOP_RECORD_FIELDS = (
    "ts", "tier", "lane", "tag", "send_s", "recv_s", "comb_s", "nbytes",
)


def _hop_sample_from_env() -> int:
    try:
        return max(0, int(os.environ.get(TPUFT_HOP_SAMPLE_ENV, "1")))
    except ValueError:
        return 1


def _hop_ring_from_env() -> int:
    try:
        return max(16, int(os.environ.get(TPUFT_HOP_RING_ENV, str(_HOP_RING_DEFAULT))))
    except ValueError:
        return _HOP_RING_DEFAULT


class HopRecorder:
    """Bounded, lock-light per-hop recorder — the Python engine's half of
    the data-plane flight recorder.

    Two tiers of cost: per-tier AGGREGATE stall counters (a few float adds
    per hop, always on — ``lane_stats()``'s "hops" feed and the
    link_attribution split's source) and a SAMPLED bounded timeline ring
    (every ``sample``-th hop; 0 disables the timeline) that
    ``obs/trace.py`` renders as the per-lane data-plane Perfetto track.
    Hops are millisecond-scale network operations; the recorder's budget
    is pinned by the bench's healthy control cell (<2% throughput impact).
    """

    def __init__(self, sample: Optional[int] = None, cap: Optional[int] = None) -> None:
        self.sample = sample if sample is not None else _hop_sample_from_env()
        self.cap = cap if cap is not None else _hop_ring_from_env()
        self._lock = threading.Lock()
        self._ring: "collections.deque[dict]" = collections.deque(maxlen=self.cap)
        self._count = 0
        # tier -> [hops, send_s, recv_s, comb_s]
        self._agg: Dict[int, List[float]] = {}

    def record(
        self,
        tier: int,
        lane: int,
        tag: int,
        send_s: float,
        recv_s: float,
        comb_s: float,
        nbytes: int,
        ts: float,
    ) -> None:
        with self._lock:
            agg = self._agg.get(tier)
            if agg is None:
                agg = self._agg[tier] = [0, 0.0, 0.0, 0.0]
            agg[0] += 1
            agg[1] += send_s
            agg[2] += recv_s
            agg[3] += comb_s
            if self.sample <= 0:
                return
            n = self._count
            self._count = n + 1
            if n % self.sample:
                return
            self._ring.append(
                {
                    "ts": ts,
                    "tier": tier,
                    "lane": lane,
                    "tag": tag,
                    "send_s": send_s,
                    "recv_s": recv_s,
                    "comb_s": comb_s,
                    "nbytes": nbytes,
                }
            )

    def stats(self, tier: int) -> dict:
        """Aggregate stall counters for one tier (same keys as the native
        engine's ``hop_stats``)."""
        with self._lock:
            agg = self._agg.get(tier, [0, 0.0, 0.0, 0.0])
            return {
                "hops": int(agg[0]),
                "send_block_s": agg[1],
                "recv_wait_s": agg[2],
                "combine_s": agg[3],
            }

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def keep(self, rec: dict) -> None:
        """Appends an already-recorded hop (e.g. the native engine's
        timeline, banked before the engine is torn down) WITHOUT touching
        the aggregates — it was aggregated where it was recorded."""
        with self._lock:
            self._ring.append(rec)

    def reset_aggregates(self) -> None:
        """Zeroes the aggregate counters, KEEPING the timeline ring: the
        aggregates are banked into lane_totals at abort (re-reading them
        would double-count), but the timeline is the data-plane black box
        — wiping it at abort would empty the hop dump on exactly the
        fault paths it exists to explain."""
        with self._lock:
            self._agg = {}

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._agg = {}
            self._count = 0


class _ShmRing:
    """One attached end of a same-host SPSC byte ring — the Python
    engine's half of the shm lane transport (the native half is
    ShmWriteAll/ShmReadExact in native/src/ring.cc over the SAME segment
    layout, so a Python producer feeds a native consumer and vice versa).

    Exactly one producer and one consumer per segment (ring lane links
    are unidirectional: the dialer only sends, the acceptor only
    receives), so the only synchronization is the pair of monotonic
    byte cursors — head (producer) and tail (consumer) — in the segment
    header.  Python's side relies on the GIL's sequencing plus x86/ARM
    acquire-release-on-aligned-load semantics for the cursor reads, the
    same assumption mmap-based SPSC rings make everywhere.

    Stalls poll the link's kept-open TCP socket for liveness: a dead
    peer's socket reads EOF long before the op timeout, so shm lanes
    fail exactly as fast as tcp lanes do (the crash-cleanup test pins
    this)."""

    _SPINS = 512

    def __init__(self, path: str, token: int, sock: socket.socket) -> None:
        fd = os.open(path, os.O_RDWR)
        try:
            size = os.fstat(fd).st_size
            if size <= _SHM_HDR:
                raise ConnectionError(f"shm segment too small: {size} bytes")
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        magic, tok = struct.unpack_from("<QQ", self._mm, 0)
        if magic != _SHM_MAGIC or tok != token:
            self._mm.close()
            raise ConnectionError(
                "stale shm segment (generation mismatch) — refusing to attach"
            )
        self._cap = size - _SHM_HDR
        self._sock = sock
        self.path = path
        self._closed = False

    def _u64(self, off: int) -> int:
        return struct.unpack_from("<Q", self._mm, off)[0]

    def poison(self) -> None:
        """Marks the segment dead for the peer (cross-process fail-fast,
        the shm analogue of a socket shutdown)."""
        if not self._closed:
            struct.pack_into("<I", self._mm, _SHM_POISON_OFF, 1)

    def _wait_tick(self, spins: List[int], deadline: float,
                   consumer: bool = False) -> None:
        """One no-progress step: spin briefly, then check the deadline,
        the peer's poison flag, and the TCP socket's liveness.  For the
        CONSUMER, peer-death signals (poison, socket EOF) only fail once
        the ring is drained: the producer's final frames land in the ring
        before its close() sets the flag, exactly like bytes sitting in a
        closed TCP socket's buffer."""
        def dead(msg: str) -> None:
            if consumer and self._u64(_SHM_HEAD_OFF) - self._u64(_SHM_TAIL_OFF):
                return  # final frames still in the ring — drain first
            raise ConnectionError(msg)

        if struct.unpack_from("<I", self._mm, _SHM_POISON_OFF)[0]:
            dead("peer connection closed (shm ring poisoned)")
            return
        if spins[0] < self._SPINS:
            spins[0] += 1
            return
        spins[0] = 0
        if time.monotonic() > deadline:
            raise TimeoutError("shm ring timed out")
        try:
            readable, _, _ = select.select([self._sock], [], [], 0)
            eof = bool(readable) and self._sock.recv(1, socket.MSG_PEEK) == b""
        except (OSError, ValueError):
            readable, eof = False, True
        if eof:
            dead("peer connection closed")
            return
        if readable:
            raise ConnectionError("unexpected socket data on shm lane")
        time.sleep(20e-6)

    def write(self, data, timeout: float) -> None:
        """Producer: appends ``data``'s bytes, blocking (with liveness
        polling) while the ring is full.  Frames larger than the capacity
        flow through in pieces."""
        mv = memoryview(data)
        if mv.ndim != 1 or mv.itemsize != 1:
            mv = mv.cast("B")
        deadline = time.monotonic() + timeout
        spins = [0]
        pos, n, cap = 0, len(mv), self._cap
        while pos < n:
            if self._closed:
                raise ConnectionError("shm ring closed")
            h = self._u64(_SHM_HEAD_OFF)
            t = self._u64(_SHM_TAIL_OFF)
            free = cap - (h - t)
            if free == 0:
                self._wait_tick(spins, deadline)
                continue
            take = min(n - pos, free)
            off = h % cap
            first = min(take, cap - off)
            self._mm[_SHM_HDR + off : _SHM_HDR + off + first] = mv[pos : pos + first]
            if take > first:
                self._mm[_SHM_HDR : _SHM_HDR + take - first] = (
                    mv[pos + first : pos + take]
                )
            struct.pack_into("<Q", self._mm, _SHM_HEAD_OFF, h + take)
            pos += take
            deadline = time.monotonic() + timeout
            spins[0] = 0

    def read_into(self, view: memoryview, timeout: float) -> None:
        """Consumer: fills ``view`` from the ring, blocking (with liveness
        polling) while it is empty."""
        deadline = time.monotonic() + timeout
        spins = [0]
        pos, n, cap = 0, len(view), self._cap
        while pos < n:
            if self._closed:
                raise ConnectionError("shm ring closed")
            t = self._u64(_SHM_TAIL_OFF)
            h = self._u64(_SHM_HEAD_OFF)
            avail = h - t
            if avail == 0:
                self._wait_tick(spins, deadline, consumer=True)
                continue
            take = min(n - pos, avail)
            off = t % cap
            first = min(take, cap - off)
            view[pos : pos + first] = self._mm[_SHM_HDR + off : _SHM_HDR + off + first]
            if take > first:
                view[pos + first : pos + take] = (
                    self._mm[_SHM_HDR : _SHM_HDR + take - first]
                )
            struct.pack_into("<Q", self._mm, _SHM_TAIL_OFF, t + take)
            pos += take
            deadline = time.monotonic() + timeout
            spins[0] = 0

    def close(self) -> None:
        if not self._closed:
            try:
                self.poison()
            except ValueError:
                pass
            self._closed = True
            try:
                self._mm.close()
            except Exception:  # noqa: BLE001
                pass


class _Peer:
    """A framed duplex TCP link to one peer rank.

    Frames arriving out of order (concurrent senders on a thread pool) are
    demultiplexed by tag: a frame for a tag nobody asked for yet is stashed
    until the matching recv_msg arrives.

    The demux is leader/follower: exactly one caller (the leader) reads the
    socket at a time, but it publishes every non-matching frame to the
    stash UNDER THE CONDITION and notifies, so a concurrent caller whose
    frame already landed takes it immediately instead of queuing behind the
    leader's blocking read.  The previous design held one mutex across the
    socket read; with three or more ops interleaved on a shared lane the
    two ring directions could form a hold-and-wait cycle — rank A's lock
    holder blocked on a frame rank B can only send after B's lock holder
    receives a frame stashed (unreachable) behind A's holder — a mutual
    stall the striped bf16 e2e bench hit roughly once per dozen steps."""

    def __init__(self, sock: socket.socket, shaper: Optional[LinkShaper] = None) -> None:
        self.sock = sock
        self.send_lock = threading.Lock()
        self.recv_cond = threading.Condition()
        self._reading = False
        self.shaper = shaper if shaper is not None else LinkShaper.from_env()
        self._stash: dict[int, "collections.deque[bytearray]"] = {}
        # Wire-byte counters (headers included), always on — the per-lane
        # throughput accounting the GB/s telemetry reads; ints under the
        # send lock / recv condition, so the cost is a couple of adds per
        # frame.  When the native ring engine owns this link's I/O the
        # hook reads its counter instead, so lane_stats and the tests
        # that sweep peer byte counters stay engine-agnostic.
        self._bytes_out = 0
        self._bytes_in = 0
        self._native_bytes: Optional[Callable[[], int]] = None
        # Same-host shm lane transport (ring channels only).  _shm_pending
        # holds the negotiated (path, token, role) from rendezvous until
        # the engine decision arms it: the native engine maps the segment
        # itself (set_shm); the Python engine arms _shm_tx (dialer,
        # producer) or _shm_rx (acceptor, consumer) below, after which
        # send_msg/_recv_exact move payload bytes through the ring while
        # the socket stays open as the liveness/abort channel.
        self._shm_pending: Optional[tuple] = None
        self._shm_tx: Optional[_ShmRing] = None
        self._shm_rx: Optional[_ShmRing] = None

    @property
    def bytes_out(self) -> int:
        if self._native_bytes is not None:
            return self._native_bytes()
        return self._bytes_out

    @property
    def bytes_in(self) -> int:
        if self._native_bytes is not None:
            return self._native_bytes()
        return self._bytes_in

    def send_msg(self, tag: int, payload) -> None:
        """payload: one buffer, or a list of buffers sent as a single frame
        (scatter-gather — lets callers frame header+raw-array without
        concatenating into yet another copy)."""
        parts = payload if isinstance(payload, (list, tuple)) else [payload]
        total = sum(len(p) for p in parts)
        with self.send_lock:
            if self.shaper is not None:
                self.shaper.on_send(total + _HDR.size)
            if self._shm_tx is not None:
                budget = self.sock.gettimeout() or 60.0
                self._shm_tx.write(_HDR.pack(tag, total), budget)
                for p in parts:
                    self._shm_tx.write(p, budget)
            else:
                self.sock.sendall(_HDR.pack(tag, total))
                for p in parts:
                    self.sock.sendall(p)
            self._bytes_out += total + _HDR.size

    def recv_msg(self, expect_tag: int) -> bytearray:
        with self.recv_cond:
            while True:
                q = self._stash.get(expect_tag)
                if q:
                    payload = q.popleft()
                    if not q:
                        del self._stash[expect_tag]
                    return payload
                if not self._reading:
                    self._reading = True
                    break
                # A leader is on the socket; it will either hand us our
                # frame via the stash (notify below) or step down (finally
                # block), at which point we take over.  The leader's socket
                # timeout bounds this wait — a dead peer surfaces as its
                # error, then ours.
                self.recv_cond.wait()
        try:
            while True:
                hdr = self._recv_exact(_HDR.size)
                tag, nbytes = _HDR.unpack(hdr)
                payload = self._recv_exact(nbytes)
                if tag == expect_tag:
                    return payload
                with self.recv_cond:
                    self._stash.setdefault(tag, collections.deque()).append(payload)
                    self.recv_cond.notify_all()
        finally:
            with self.recv_cond:
                self._reading = False
                self.recv_cond.notify_all()

    def _recv_exact(self, n: int) -> bytearray:
        # Returned as the bytearray itself (writable, no bytes() copy):
        # np.frombuffer over it yields mutable arrays and every ring exchange
        # saves a full payload memcpy.
        buf = bytearray(n)
        view = memoryview(buf)
        if self._shm_rx is not None:
            self._shm_rx.read_into(view, self.sock.gettimeout() or 60.0)
            self._bytes_in += n
            return buf
        got = 0
        while got < n:
            r = self.sock.recv_into(view[got:], n - got)
            if r == 0:
                raise ConnectionError("peer connection closed")
            got += r
        self._bytes_in += n
        return buf

    def close(self) -> None:
        for ring in (self._shm_tx, self._shm_rx):
            if ring is not None:
                ring.close()
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


class _FifoQueue:
    """Submission-order turnstile for one (direction, peer, tag) stream.

    A stream is all-or-nothing: once any op on it fails (timeout or socket
    error) the stream is poisoned and every later op fails immediately.
    Skipping a failed slot instead would let the remote side's matching op
    pair with the *next* op's frame — a silent payload swap that consumers
    outside the commit gate (checkpoint transports) could act on before any
    reconfigure clears the error."""

    def __init__(self) -> None:
        self.cond = threading.Condition()
        self.next_submit = 0
        self.next_serve = 0
        self.poison: Optional[Exception] = None

    def take_ticket(self) -> int:
        with self.cond:
            seq = self.next_submit
            self.next_submit += 1
            return seq

    def wait_turn(self, seq: int, timeout: float) -> None:
        with self.cond:
            ok = self.cond.wait_for(
                lambda: self.poison is not None or self.next_serve >= seq,
                timeout=timeout,
            )
            if self.poison is not None:
                raise RuntimeError(f"channel poisoned by earlier failure: {self.poison}")
            if not ok:
                raise TimeoutError("timed out waiting for earlier op on this channel")

    def done(self) -> None:
        with self.cond:
            self.next_serve += 1
            self.cond.notify_all()

    def poison_with(self, exc: Exception) -> None:
        with self.cond:
            if self.poison is None:
                self.poison = exc
            self.cond.notify_all()


# Parallel ring connections ("lanes") per neighbor.  Lanes stripe ring
# chunks across independent sockets and a per-lane worker pool, so one
# bucket's reduce-scatter *sum* overlaps another bucket's send/recv, and
# per-frame propagation (RTT) overlaps across lanes — the two effects that
# keep a shaped/high-RTT link busy.  Shaped benches stay honest: all lanes
# to one neighbor share a single LinkShaper serialization budget.
TPUFT_RING_LANES_ENV = "TPUFT_RING_LANES"
_MAX_LANES = 8
# Stripes per ring chunk are capped so tag space and frame overhead stay
# bounded; tags are carved as seq * _TAGS_PER_OP + stripe * _TAGS_PER_STRIPE
# + subtag.  The per-stripe block is PARTITIONED BY TIER: the flat ring (and
# the 2D topology's row tier, which reuses its subtags on its own sockets)
# takes the low half, the 2D topology's nested column tier the high half —
# so a hierarchical op's two nested rings can never collide on a tag even
# if a future topology multiplexes tiers onto shared sockets.  The static
# audit in tests/test_collectives.py pins every subtag below
# _TAGS_PER_STRIPE and every stripe block inside its op's _TAGS_PER_OP.
_MAX_STRIPES = 64
_TAGS_PER_STRIPE = 8
_TAGS_PER_OP = _TAGS_PER_STRIPE * (_MAX_STRIPES + 1)
# Subtags within one stripe's block.
_SUB_RS = 1  # reduce-scatter hops (flat ring / row tier)
_SUB_AG = 2  # allgather hops (flat ring / row tier)
_SUB_GATHER = 3  # whole-object circulation (allgather/broadcast/alltoall)
_SUB_COL_RS = 4  # nested column-tier reduce-scatter (ring2d)
_SUB_COL_AG = 5  # nested column-tier allgather (ring2d)

# Cross-group allreduce topology (docs/architecture.md "Topology-aware
# allreduce").  "ring" is the flat single ring over all N groups (latency
# grows as 2(N-1) hops); "ring2d" arranges the N groups on an R x C grid
# (R = largest divisor <= sqrt(N)) and runs reduce-scatter along the row
# ring, a full allreduce along the column ring, and allgather back along
# the row — 2(C-1) + 2(R-1) hops, the latency win that keeps step time flat
# at O(100) groups.  "auto" picks ring2d once the group count reaches
# TPUFT_RING2D_MIN_GROUPS (and the count factors into a real grid).
TPUFT_RING_TOPOLOGY_ENV = "TPUFT_RING_TOPOLOGY"
TPUFT_RING2D_MIN_ENV = "TPUFT_RING2D_MIN_GROUPS"
_RING2D_DEFAULT_MIN = 8
_TOPOLOGIES = ("auto", "ring", "ring2d")


# Ring engine selection (docs/architecture.md "Native data plane").  The
# hot loop — per-hop socket I/O, tag demux, link pacing, wire codecs, the
# f32 combine — can run either in Python threads ("py") or in the native
# GIL-free engine (native/src/ring.cc, "native").  Both produce IDENTICAL
# wire bytes and results (bitwise — pinned by the engine-parity tests), so
# mixed-engine rings interoperate and "auto" (the default) simply picks
# native whenever libtpuft.so exports it, falling back to Python otherwise
# (one warning when native was requested explicitly but the .so is stale).
# Payloads outside the native fast path (non-f32 accumulation: int/f64
# payloads, pickled control traffic) run the Python orchestration over the
# engine's socket layer, so ALL reads of a lane socket share one demux.
TPUFT_RING_ENGINE_ENV = "TPUFT_RING_ENGINE"
_RING_ENGINES = ("auto", "py", "native")

# Native engine op/wire codes (mirrors native/src/ring.h enums).
_NATIVE_OP = {"sum": 0, "avg": 0, "max": 1, "min": 2}
_NATIVE_WIRE_RAW = 0
_NATIVE_WIRE_BF16 = 1
_NATIVE_WIRE_INT8 = 2
_NATIVE_WIRE_INT4 = 3
_NATIVE_PASS_FULL = 0
_NATIVE_PASS_RS = 1
_NATIVE_PASS_AG = 2

# Ring lane transport (docs/architecture.md "Same-host data plane").
# "tcp" (default): every lane frame crosses the kernel socket.  "shm":
# lanes whose two ranks prove same-host at rendezvous (matching
# /proc/sys/kernel/random/boot_id, exchanged right after the connection
# preamble) move their frames through a lock-free SPSC byte ring in a
# /dev/shm segment instead — the TCP socket stays open as the
# liveness/abort channel, and tag demux / abort / reconfigure semantics
# are unchanged (the segment layout is pinned between _ShmRing here and
# native/src/ring.cc, so mixed-engine rings still interoperate).  "auto"
# negotiates shm where it can and silently keeps tcp elsewhere; "shm"
# makes a failed same-host negotiation a hard configure() error.  The
# knob must match on every rank of one collective (like lanes/topology):
# a tcp rank cannot parse the shm handshake bytes.
TPUFT_RING_TRANSPORT_ENV = "TPUFT_RING_TRANSPORT"
_TRANSPORTS = ("tcp", "shm", "auto")

# Incremental reconfiguration (docs/architecture.md "Elastic scale").  A
# membership delta that preserves this rank's flat-ring position reuses
# the surviving lane sockets and shm segments instead of the full
# teardown-and-rendezvous — the dominant per-transition dead-time cost
# under churn.  Default on; "0" forces the full path on every quorum
# transition (the parity baseline the elastic soak compares against).
TPUFT_INCREMENTAL_RECONF_ENV = "TPUFT_INCREMENTAL_RECONF"


def _incremental_from_env() -> bool:
    v = os.environ.get(TPUFT_INCREMENTAL_RECONF_ENV, "1").strip().lower()
    return v not in ("0", "false", "off", "no")

# Per-link SPSC ring capacity (data bytes past the 64-byte header).
# Frames larger than the capacity flow through in pieces, so this bounds
# memory, not payload size.
TPUFT_SHM_RING_BYTES_ENV = "TPUFT_SHM_RING_BYTES"
_SHM_RING_BYTES_DEFAULT = 1 << 20

# Segment header layout — MUST mirror native/src/ring.cc (kShmMagic,
# kShmHdr, kShm*Off): magic u64 @0, generation token u64 @8, head
# (producer cursor) u64 @16, tail (consumer cursor) u64 @24, poisoned
# u32 @32, consumer-parked u32 @40, producer-parked u32 @44, data @64.
# Cursors are monotonic byte counts.  The parked flags belong to the
# native engine's futex wait path; this Python engine polls and never
# sets them (a native waiter paired with a Python peer is bounded by
# its 2 ms park timeout), but the offsets are reserved here so the two
# layouts cannot drift.
_SHM_MAGIC = 0x746675745F736D68
_SHM_HDR = 64
_SHM_TOKEN_OFF = 8
_SHM_HEAD_OFF = 16
_SHM_TAIL_OFF = 24
_SHM_POISON_OFF = 32

# Rendezvous extension blocks (sent on ring channels only, and only when
# the transport knob is not "tcp"): dialer -> 64-byte padded boot-id;
# acceptor -> (flag, token, segment name); dialer -> 1 ack byte.
_SHM_REQ = struct.Struct("<64s")
_SHM_REP = struct.Struct("<BQ64s")


def _transport_from_env() -> str:
    t = os.environ.get(TPUFT_RING_TRANSPORT_ENV, "tcp")
    return t if t in _TRANSPORTS else "tcp"


def _shm_ring_bytes_from_env() -> int:
    try:
        return max(4096, int(os.environ.get(
            TPUFT_SHM_RING_BYTES_ENV, str(_SHM_RING_BYTES_DEFAULT))))
    except ValueError:
        return _SHM_RING_BYTES_DEFAULT


def _boot_id() -> bytes:
    """This host's boot UUID — the same-host proof two ranks compare at
    rendezvous (equal boot-ids => same kernel instance => /dev/shm is
    genuinely shared).  Empty when unreadable, which disables shm."""
    try:
        with open("/proc/sys/kernel/random/boot_id", "rb") as f:
            return f.read().strip()[:64]
    except OSError:
        return b""

_native_fallback_warned = False


def _warn_native_fallback(reason: str) -> None:
    """One clear line per process when TPUFT_RING_ENGINE=native was
    requested but the loaded libtpuft.so predates the ring engine — a
    silent Python fallback here would report CPU-bound numbers as if they
    were the native data plane's."""
    global _native_fallback_warned
    if _native_fallback_warned:
        return
    _native_fallback_warned = True
    import logging

    logging.getLogger("torchft_tpu.collectives").warning(
        "TPUFT_RING_ENGINE=native requested but the native ring engine is "
        "unavailable; running the PYTHON ring engine instead: %s",
        reason,
    )


def _ring_engine_from_env() -> str:
    engine = os.environ.get(TPUFT_RING_ENGINE_ENV, "auto")
    return engine if engine in _RING_ENGINES else "auto"


def _ring_lanes_from_env() -> int:
    try:
        lanes = int(os.environ.get(TPUFT_RING_LANES_ENV, "2"))
    except ValueError:
        return 2
    return max(1, min(_MAX_LANES, lanes))


def _topology_from_env() -> str:
    topo = os.environ.get(TPUFT_RING_TOPOLOGY_ENV, "auto")
    return topo if topo in _TOPOLOGIES else "auto"


def _ring2d_min_from_env() -> int:
    try:
        return max(2, int(os.environ.get(TPUFT_RING2D_MIN_ENV, str(_RING2D_DEFAULT_MIN))))
    except ValueError:
        return _RING2D_DEFAULT_MIN


def _grid_shape(n: int) -> tuple:
    """``(rows, cols)`` with ``rows * cols == n`` and ``rows`` the largest
    divisor <= sqrt(n) — the squarest exact factoring, which minimizes the
    2D hop count 2(C-1) + 2(R-1).  Every rank derives the identical grid
    from the world size alone (no negotiation), and non-square N lands on
    its divisor grid (6 -> 2x3, 8 -> 2x4).  Primes return (1, n): no 2D
    factoring exists, and the caller degrades to the flat ring."""
    rows = int(math.isqrt(n))
    while rows > 1 and n % rows:
        rows -= 1
    rows = max(1, rows)
    return rows, n // rows


class _TierLinks:
    """Connections and metadata for one nested ring tier of the 2D topology.

    A tier is a smaller ring over a subset of the world (a grid row or
    column): ``size`` members, this rank at position ``ring_rank``, one
    socket per lane per direction, and its own per-lane sender pools so a
    shaped row send never heads-of-line-blocks a column send on a different
    physical link."""

    def __init__(self, size: int, ring_rank: int, next_rank: int, prev_rank: int) -> None:
        self.size = size
        self.ring_rank = ring_rank
        self.next_rank = next_rank  # world rank of the tier's next neighbor
        self.prev_rank = prev_rank  # world rank of the tier's prev neighbor
        self.next_lanes: List[_Peer] = []
        self.prev_lanes: List[_Peer] = []
        self.send_pools: List[object] = []

    def peers(self) -> List[_Peer]:
        return list(self.next_lanes) + list(self.prev_lanes)


class TCPCollective(Collective):
    """Striped multi-lane ring collective over TCP sockets between replica
    groups.

    This is the tpu-ft data plane for the *replica* (DCN) dimension: gradients
    have already been reduced over ICI inside the pjit step; what crosses
    groups is one host buffer per ring chunk.  Ring allreduce moves
    2*(n-1)/n of the data per rank — bandwidth optimal, and each group talks
    only to its ring neighbors, matching how DCN links are provisioned.

    Lanes: ``TPUFT_RING_LANES`` (default 2, max 8) parallel connections per
    ring neighbor.  With lanes > 1 each allreduce is split into round-robin
    chunk stripes, every stripe running its own ring on lane ``stripe %
    lanes`` with a unique per-op tag, executed by a per-lane worker pool —
    so stripe k's local *sum* overlaps stripe k+1's bytes on the wire, and
    back-to-back allreduce calls (the GradientAverager's buckets) overlap
    each other instead of serializing on one socket pair.  Submission order
    of ring ops must still be identical on every rank (program order), but
    alignment within that order is carried by tags, not timing.

    Topology: ``topology="auto"`` (``TPUFT_RING_TOPOLOGY``) selects between
    the flat ring and a 2D ring-of-rings per configure().  The flat ring's
    latency term is 2(N-1) sequential hops; at O(dozens) of groups on a
    real (high-RTT) DCN link that term IS the step-time floor.  "ring2d"
    arranges the groups on an R x C grid and runs: reduce-scatter along the
    ROW ring (C-1 hops), a full allreduce of the owned row chunk along the
    COLUMN ring (2(R-1) hops), allgather back along the row (C-1 hops) —
    ~4*sqrt(N) hops total.  Fold order is deterministic per topology (row
    partials then column fold, each in fixed ring-step order), so results
    remain BITWISE-identical across every rank — the replica-consistency
    property the commit protocol depends on — though hierarchical f32/bf16
    results differ from the flat ring's within reassociation/requantization
    rounding.  "auto" keeps the flat ring below TPUFT_RING2D_MIN_GROUPS
    (default 8) and whenever N has no non-trivial divisor (primes).
    allgather/broadcast/alltoall/barrier always use the flat ring (control
    traffic, not the gradient hot path); both tiers' sockets are torn down
    together by abort()/configure().

    Reconfiguration: rendezvous through the group store under a caller-chosen
    prefix; every rank publishes "host:port", rank i dials rank (i+1)%n once
    per lane.  abort() closes the sockets, causing in-flight ops to fail
    fast and latch an error until the next configure() (the NCCL-abort
    analogue, torchft/process_group.py:584-647).
    """

    RENDEZVOUS_TIMEOUT_MS = 60000

    def __init__(
        self,
        timeout: float = 60.0,
        chunk_bytes: int = 4 << 20,
        wire_dtype: str = "auto",
        lanes: Optional[int] = None,
        topology: Optional[str] = None,
        engine: Optional[str] = None,
        transport: Optional[str] = None,
    ) -> None:
        """``wire_dtype="bf16"`` halves allreduce bytes on the wire (DCN is
        the cross-slice bottleneck): ring payloads are cast to bfloat16 per
        hop while local accumulation stays in the input dtype (f32 for
        grads).  Every rank quantizes the reduced chunk identically before
        the allgather phase, so all replicas still receive BITWISE-equal
        results — the property the commit protocol depends on.

        ``"auto"`` (default) picks bf16 when the link is declared
        bandwidth-bound — ``TPUFT_LINK_PROFILE=dcn`` in the environment,
        or a shaped-link emulation is active (``TPUFT_SHAPED_LINK``) —
        and f32 otherwise.  Why not bf16 always: (1) each hop quantizes,
        so error grows with ring size — at the replica dimension's small
        world sizes (2-8 groups) the rounding is well inside gradient
        noise; (2) it trades host CPU (the casts) for wire bytes, so it
        wins only when the network is the bottleneck — on a 200 Mbps /
        20 ms shaped link a 64 MB 2-rank allreduce measured ~1.75x faster
        with bf16 (see TRANSFER_BENCH.json shaped_link), while on
        localhost loopback it measured SLOWER (0.57 s vs 0.46 s f32 on a
        1-core host)."""
        if wire_dtype == "auto":
            wire_dtype = (
                "bf16"
                if os.environ.get("TPUFT_LINK_PROFILE") == "dcn"
                or os.environ.get("TPUFT_SHAPED_LINK")
                else "f32"
            )
        if wire_dtype not in ("f32", "bf16"):
            raise ValueError(
                f"unsupported wire_dtype {wire_dtype!r}; expected 'f32' or 'auto' or 'bf16'"
            )
        topology = topology if topology is not None else _topology_from_env()
        if topology not in _TOPOLOGIES:
            raise ValueError(
                f"unsupported topology {topology!r}; expected one of {_TOPOLOGIES}"
            )
        engine = engine if engine is not None else _ring_engine_from_env()
        if engine not in _RING_ENGINES:
            raise ValueError(
                f"unsupported engine {engine!r}; expected one of {_RING_ENGINES}"
            )
        transport = transport if transport is not None else _transport_from_env()
        if transport not in _TRANSPORTS:
            raise ValueError(
                f"unsupported transport {transport!r}; expected one of {_TRANSPORTS}"
            )
        self._timeout = timeout
        self._chunk_bytes = chunk_bytes
        self._wire_dtype = wire_dtype
        self._lanes = lanes if lanes is not None else _ring_lanes_from_env()
        self._lanes = max(1, min(_MAX_LANES, self._lanes))
        self._topology = topology  # requested; resolved per configure()
        self._ring2d_min = _ring2d_min_from_env()
        self._active_topology = "ring"
        # Native GIL-free ring engine handle (None = Python engine); built
        # per configure() over the freshly rendezvoused lane sockets.
        self._engine_mode = engine
        self._engine = None
        # Lane transport: requested mode, per-configure count of armed shm
        # links, and every segment path this rank negotiated (BOTH sides
        # track, so whichever rank survives a peer crash unlinks).
        self._transport = transport
        self._shm_links = 0
        self._shm_lock = threading.Lock()
        self._shm_paths: set = set()
        self._row_tier: Optional[_TierLinks] = None
        self._col_tier: Optional[_TierLinks] = None
        self._lock = threading.Lock()
        self._executor: Optional[object] = None
        self._ring_executor: Optional[object] = None
        self._lane_executor: Optional[object] = None
        # One single-worker sender pool per lane (see _exchange).
        self._send_pools: List[object] = []
        self._rank = 0
        self._world_size = 1
        self._next_lanes: List[_Peer] = []  # links to (rank+1) % n, one per lane
        self._prev_lanes: List[_Peer] = []  # links to (rank-1) % n, one per lane
        # Ring-op sequence counter: allocated at CALL time on the caller's
        # thread, so identical program order on every rank yields identical
        # tags (the cross-rank alignment contract now that ops overlap).
        self._op_seq = 0
        self._op_seq_lock = threading.Lock()
        # In-flight striped-op result futures, failed fast on abort().
        self._inflight: set = set()
        # Data-plane flight recorder (shared by both engines' Python-
        # orchestrated hops; native ring passes record inside ring.cc and
        # are merged in lane_stats/hop_records).  Reset per configure(),
        # like the lane byte counters.
        self._hops = HopRecorder()
        # Lifetime (cross-configure) counter bank: lane/hop counters zero
        # on every configure(), so any cumulative exposition (the worker
        # /metrics endpoint) would go BACKWARDS across a reconfigure.
        # abort() banks the closing generation's totals here;
        # lane_totals() = banked + live, monotonic by construction (the
        # same reset-aware epoch logic obs.report.data_plane applies to
        # step_summary snapshots, applied at the source).
        self._lifetime: Dict[str, object] = {}
        self._peers: dict[int, _Peer] = {}
        self._accept_cond = threading.Condition()
        self._accept_thread: Optional[threading.Thread] = None
        self._accepted_ring: dict[int, _Peer] = {}
        self._dialing: set[int] = set()
        self._listener: Optional[socket.socket] = None
        self._error: Optional[Exception] = None
        self._op_error: Optional[Exception] = None
        self._generation = 0
        self._store: Optional[StoreClient] = None
        # FIFO tickets so same-(peer, tag) send/recv pairs execute in
        # submission order despite the multi-worker p2p executor; without
        # this, two same-tag ops could be silently swapped by the tag demux.
        self._fifo_lock = threading.Lock()
        self._fifo: dict[tuple, "_FifoQueue"] = {}
        self._p2p_submit_lock = threading.Lock()
        # Incremental (elastic) reconfiguration state.  Each flat-ring
        # neighbor's identity is its published listener address plus an
        # incarnation token minted with the listener — equal identity
        # across a quorum transition proves the SAME process still holds
        # the other end of our lane sockets, so the edge can be reused.
        # The prev-direction shapers live on the instance (not the accept
        # loop's closure) so an accept loop started by one generation can
        # arm peers for a later incremental generation.
        self._incremental = _incremental_from_env()
        self._self_addr: Optional[str] = None
        self._listener_token = ""
        self._neighbor_ids: Dict[str, tuple] = {}
        self._ring_prev_shaper: Optional[LinkShaper] = None
        self._tier_prev_shapers: Dict[int, Optional[LinkShaper]] = {}
        # What the LAST configure() did — the Manager's membership_change
        # event and the elastic bench read this to attribute transition
        # cost to the full vs incremental path.
        self.last_configure: Dict[str, object] = {
            "mode": "none",
            "reused_lanes": 0,
            "opened_lanes": 0,
            "configure_s": 0.0,
        }

    # -- lifecycle ----------------------------------------------------------

    @property
    def _next(self) -> Optional[_Peer]:
        """Lane-0 link to (rank+1) % n — kept as the stable single-lane
        handle (tests and diagnostics); all lanes of one direction share one
        LinkShaper, so its byte counters cover the whole direction."""
        return self._next_lanes[0] if self._next_lanes else None

    @property
    def _prev(self) -> Optional[_Peer]:
        return self._prev_lanes[0] if self._prev_lanes else None

    def _resolve_topology(self, world_size: int) -> str:
        """The topology this configuration actually runs.  ring2d needs a
        non-trivial grid (primes cannot factor: the "remainder" worlds);
        auto additionally keeps the flat ring below the crossover group
        count, where 2(N-1) hops still beats paying two tiers' framing."""
        if self._topology == "ring" or world_size < 4:
            return "ring"
        rows, _cols = _grid_shape(world_size)
        if rows < 2:
            return "ring"
        if self._topology == "ring2d":
            return "ring2d"
        return "ring2d" if world_size >= self._ring2d_min else "ring"

    def configure(self, store_addr: str, rank: int, world_size: int) -> None:
        t0 = time.monotonic()
        if self._configure_incremental(store_addr, rank, world_size, t0):
            return
        self.abort()
        with self._lock:
            self._error = None
            self._op_error = None
            self._rank = rank
            self._world_size = world_size
            self._generation += 1
            self._active_topology = self._resolve_topology(world_size)
            with self._op_seq_lock:
                self._op_seq = 0
            # Abort may have cancelled queued p2p ops that will never call
            # done(); fresh turnstiles avoid cross-generation waits.
            with self._fifo_lock:
                self._fifo = {}
            # Hop AGGREGATES are per-configure like the lane byte counters
            # (abort() just banked the closing generation's totals and
            # reset them; the timeline ring persists across generations —
            # it is the bounded black box, not a counter).
            if world_size == 1:
                self.last_configure = {
                    "mode": "full",
                    "reused_lanes": 0,
                    "opened_lanes": 0,
                    "configure_s": time.monotonic() - t0,
                }
                return
            self._store = StoreClient(store_addr)
            self._rendezvous()
            self._engine = self._create_engine()
            self._arm_shm_links()
            from concurrent.futures import ThreadPoolExecutor

            # Single-lane ring ops share the lane-0 sockets and execute one
            # at a time in submission order on this executor — program
            # order is identical on every rank, which keeps the rings
            # aligned.  Striped ops instead fan out to the per-lane pool
            # below, aligned by per-op tags.  P2P send/recv use per-pair
            # sockets with tag demux and may overlap freely.
            self._ring_executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="tpuft_ring"
            )
            self._send_pools = [
                ThreadPoolExecutor(max_workers=1, thread_name_prefix=f"tpuft_send{ln}")
                for ln in range(self._lanes)
            ]
            for name, tier in (("row", self._row_tier), ("col", self._col_tier)):
                if tier is not None:
                    # Each tier direction gets its own single-worker-per-lane
                    # sender pool: a shaped row frame must not head-of-line
                    # block a column frame headed down a different link.
                    tier.send_pools = [
                        ThreadPoolExecutor(
                            max_workers=1, thread_name_prefix=f"tpuft_{name}{ln}"
                        )
                        for ln in range(self._lanes)
                    ]
            if self._lanes > 1:
                # Depth-2 per lane: a stripe's worker stays occupied through
                # its link-serialization wait (real or shaped), so with only
                # one worker per lane the next bucket's stripes could never
                # enter the wire until the current bucket's cleared it —
                # exactly the bubble lanes exist to remove.  2x lets stripe
                # k+1 overlap stripe k's in-flight time; the shared per-peer
                # shaper still bounds aggregate bandwidth.
                self._lane_executor = ThreadPoolExecutor(
                    max_workers=self._lanes * 2, thread_name_prefix="tpuft_lane"
                )
            self._executor = ThreadPoolExecutor(
                max_workers=4, thread_name_prefix="tpuft_p2p"
            )
            opened = len(self._next_lanes) + len(self._prev_lanes)
            for tier in (self._row_tier, self._col_tier):
                if tier is not None:
                    opened += len(tier.next_lanes) + len(tier.prev_lanes)
            self.last_configure = {
                "mode": "full",
                "reused_lanes": 0,
                "opened_lanes": opened,
                "configure_s": time.monotonic() - t0,
            }

    def _configure_incremental(
        self, store_addr: str, rank: int, world_size: int, t0: float
    ) -> bool:
        """Quorum-transition fast path: when this rank's flat-ring position
        survives the membership delta, reuse the surviving lane sockets and
        shm segments and open only the edges that changed, instead of the
        full teardown-and-rendezvous (the dominant per-transition dead-time
        cost under churn).  Returns False — the caller then runs the full
        path — whenever a precondition fails or any step slips; the
        subsequent abort() reclaims everything a partial attempt registered
        on self.

        Protocol: every configuring rank publishes ``rank_{r}`` (listener
        address — stable here, the listener is kept) and ``cfg_{r}``
        ("inc:<token>" on this path, "full:<token>" on the full path) into
        the NEW quorum's store namespace.  An edge is reused iff the
        neighbor's published (addr, token) identity equals the identity
        recorded at the previous configure AND its mode is "inc" (a "full"
        neighbor's old sockets were closed by its abort()).  Both ends of
        a surviving edge evaluate the same two records, so the decision is
        symmetric.  Once this rank has PUBLISHED it commits to the
        incremental path even when no edge survives (both neighbors
        replaced — it then rebuilds every edge over the kept listener):
        the published address is live the moment the key lands, so a
        fresh neighbor may already hold a connection to it.  The rare
        asymmetric slip (a rank aborts to the full path AFTER publishing
        "inc", e.g. a peer crash mid-configure) leaves the reusing side
        holding a dead socket, which surfaces as an op error and recovers
        on the next quorum — the same contract as the crash itself.
        Late-arriving spares take the full path (nothing of theirs
        survives) and hot-admit by dialing the survivors' kept listeners.
        """
        if not self._incremental:
            return False
        with self._lock:
            try:
                return self._configure_incremental_locked(
                    store_addr, rank, world_size, t0
                )
            except Exception:  # noqa: BLE001 — any slip falls back to full
                return False

    def _configure_incremental_locked(
        self, store_addr: str, rank: int, world_size: int, t0: float
    ) -> bool:
        # Preconditions: a live single-tier ring on BOTH sides of the
        # transition (ring2d crossovers always rebuild — tier membership
        # changes shape, not just neighbors), a kept listener, no latched
        # error, and nothing in flight (the Manager reconfigures at a step
        # boundary; in-flight work means something already failed).
        if (
            self._listener is None
            or self._self_addr is None
            or not self._neighbor_ids
            or self._world_size <= 1
            or world_size <= 1
            or self._error is not None
            or self._op_error is not None
            or self._inflight
            or self._active_topology != "ring"
            or self._resolve_topology(world_size) != "ring"
            or not self._next_lanes
            or not self._prev_lanes
            or self._ring_executor is None
        ):
            return False
        old_next_id = self._neighbor_ids.get("next")
        old_prev_id = self._neighbor_ids.get("prev")
        if old_next_id is None or old_prev_id is None:
            return False
        store = StoreClient(store_addr)
        old_store, self._store = self._store, store
        if old_store is not None:
            try:
                old_store.close()
            except Exception:  # noqa: BLE001
                pass
        # Purge point-to-point links and stale accepted conns BEFORE
        # publishing our address: ranks renumber (p2p can never survive),
        # and a fast new neighbor may dial the moment it reads the key —
        # its lanes must land in _accepted_ring AFTER this sweep, not be
        # closed by it.  Generation bump invalidates in-flight dials,
        # exactly as abort() does.
        with self._accept_cond:
            stale = list(self._peers.values()) + list(self._accepted_ring.values())
            self._peers = {}
            self._accepted_ring = {}
            self._generation += 1
            self._dialing = set()
            self._accept_cond.notify_all()
        for p in stale:
            p.close()
        # Fresh prev-direction shaper installed before the publish for the
        # same reason; if the prev edge ends up reused, the accepted-lane
        # path never reads it and the reused peers keep their own shaper.
        self._ring_prev_shaper = LinkShaper.from_env()
        store.set(f"rank_{rank}", self._self_addr.encode())
        store.set(f"cfg_{rank}", f"inc:{self._listener_token}".encode())
        next_rank = (rank + 1) % world_size
        prev_rank = (rank - 1) % world_size
        # Full rendezvous budget, not the surviving-neighbor short wait: a
        # REPLACED neighbor is a fresh process that may publish late
        # (restart + runtime init), and the full path would wait just as
        # long for its dial.
        ident_ms = self.RENDEZVOUS_TIMEOUT_MS
        next_id = self._peer_identity(next_rank, timeout_ms=ident_ms)
        prev_id = self._peer_identity(prev_rank, timeout_ms=ident_ms)
        if next_id is None or prev_id is None:
            return False
        reuse_next = next_id[2] == "inc" and next_id[:2] == old_next_id
        reuse_prev = prev_id[2] == "inc" and prev_id[:2] == old_prev_id
        # When NOTHING survives (e.g. world 2 and the only neighbor was
        # replaced by a fresh incarnation publishing "full") we still stay
        # on this path and rebuild both edges over the KEPT listener.
        # Falling back to full here would be unsound, not just slow: our
        # address + "inc" marker are already published, and a fresh
        # neighbor may have dialed that listener the moment the key
        # appeared — the fallback's abort() would close it under them,
        # they'd finish their rendezvous holding dead sockets, and our
        # full-path replacement listener would wait out the whole
        # rendezvous timeout for a dial that never comes (a survivor +
        # restarted-peer pair stalled 60 s per transition this way).
        # Bank the closing generation's counters while the native engine
        # (if any) is still readable, then DETACH it: plain close() of its
        # dup'd fds — unlike Close()'s shutdown(), the reused sockets'
        # underlying connections stay alive.  A detach refusal (ops in
        # flight) raises and falls back to the full path.
        self._bank_locked()
        engine, self._engine = self._engine, None
        if engine is not None:
            engine.detach()
        # Close the edges that did not survive; zero the surviving ones'
        # per-generation counters (their totals were just banked) and drop
        # their native hooks until _create_engine rewires them.
        keep_paths: set = set()
        for reused, lanes_list in (
            (reuse_next, self._next_lanes),
            (reuse_prev, self._prev_lanes),
        ):
            sh = lanes_list[0].shaper if lanes_list else None
            if reused and sh is not None:
                sh._native_read = None
                sh._native_wait = None
                with sh._lock:
                    sh._bytes_sent = 0
                    sh._frames_sent = 0
                    sh._wait_s = 0.0
                    sh._busy_until = 0.0
            for p in lanes_list:
                if reused:
                    p._bytes_out = 0
                    p._bytes_in = 0
                    p._native_bytes = None
                    if p._shm_pending is not None:
                        keep_paths.add(p._shm_pending[0])
                else:
                    p.close()
        # Reclaim only the segments whose edges died; surviving segments
        # keep their names (the re-built engine re-attaches them by the
        # unchanged header token).
        with self._shm_lock:
            drop = [sp for sp in self._shm_paths if sp not in keep_paths]
            self._shm_paths = set(keep_paths)
        for sp in drop:
            try:
                os.unlink(sp)
            except OSError:
                pass
        self._error = None
        self._op_error = None
        self._rank = rank
        self._world_size = world_size
        self._active_topology = "ring"
        with self._op_seq_lock:
            self._op_seq = 0
        with self._fifo_lock:
            self._fifo = {}
        # Open only the changed edges.  Executors and the accept loop are
        # generation-agnostic and stay up — that, plus the kept sockets,
        # is the entire dead-time win.
        lanes = self._lanes
        opened = 0
        if not reuse_next:
            next_shaper = LinkShaper.from_env()
            self._next_lanes = []
            for lane in range(lanes):
                self._next_lanes.append(
                    self._dial_rank(
                        next_rank, self._CH_RING, lane=lane, shaper=next_shaper
                    )
                )
            opened += lanes
        if not reuse_prev:
            self._prev_lanes = []
            expected = [(prev_rank, self._CH_RING, lane) for lane in range(lanes)]
            deadline = self.RENDEZVOUS_TIMEOUT_MS / 1000
            with self._accept_cond:
                ok = self._accept_cond.wait_for(
                    lambda: all(key in self._accepted_ring for key in expected),
                    timeout=deadline,
                )
                if not ok:
                    missing = [k for k in expected if k not in self._accepted_ring]
                    raise TimeoutError(
                        f"incremental reconfigure: ring peers never connected: "
                        f"{missing}"
                    )
                self._prev_lanes = [
                    self._accepted_ring.pop((prev_rank, self._CH_RING, lane))
                    for lane in range(lanes)
                ]
            opened += lanes
        self._engine = self._create_engine()
        self._arm_shm_links()
        self._neighbor_ids = {"next": next_id[:2], "prev": prev_id[:2]}
        self.last_configure = {
            "mode": "incremental",
            "reused_lanes": (lanes if reuse_next else 0)
            + (lanes if reuse_prev else 0),
            "opened_lanes": opened,
            "configure_s": time.monotonic() - t0,
        }
        return True

    @property
    def ring_engine(self) -> str:
        """The engine the CURRENT configuration runs the ring hot loop on:
        "native" (GIL-free, native/src/ring.cc) or "py".  "auto" and
        explicit requests resolve here — what the bench's engine A/B
        records and the parity tests pin."""
        return "native" if self._engine is not None else "py"

    @property
    def ring_transport(self) -> str:
        """The transport the CURRENT configuration's ring lanes move
        payload bytes on: "shm" when at least one same-host segment was
        negotiated and armed (all-loopback rings arm every lane), "tcp"
        otherwise — what the bench's transport A/B records and
        test_transport_quick_smoke pins."""
        return "shm" if self._shm_links > 0 else "tcp"

    def _create_engine(self) -> Optional[object]:
        """Builds the native ring engine over this generation's lane fds
        (all tiers), or returns None for the Python engine.  Called under
        _lock right after _rendezvous; any failure degrades to Python."""
        if self._engine_mode == "py":
            return None
        from torchft_tpu import _native

        if not _native.ring_engine_available():
            if self._engine_mode == "native":
                _warn_native_fallback(_native.ring_engine_unavailable_reason())
            return None
        mbps = rtt_ms = 0.0
        spec = os.environ.get("TPUFT_SHAPED_LINK")
        if spec:
            try:
                head, _, tail = spec.partition(":")
                mbps, rtt_ms = float(head), float(tail or "0")
            except ValueError:
                mbps = rtt_ms = 0.0
        tiers = [(_native.RingEngine.TIER_FLAT, self._next_lanes, self._prev_lanes)]
        for tid, tier in ((_native.RingEngine.TIER_ROW, self._row_tier),
                          (_native.RingEngine.TIER_COL, self._col_tier)):
            if tier is not None:
                tiers.append((tid, tier.next_lanes, tier.prev_lanes))
        try:
            eng = _native.RingEngine(self._lanes, mbps, rtt_ms)
            for tid, nexts, prevs in tiers:
                eng.set_tier(
                    tid,
                    [p.sock.fileno() for p in nexts],
                    [p.sock.fileno() for p in prevs],
                )
        except Exception as e:  # noqa: BLE001 — engine is an optimization
            if self._engine_mode == "native":
                _warn_native_fallback(f"engine construction failed: {e}")
            return None
        # The engine's hop recorder follows this collective's sampling /
        # ring-capacity config so both engines' timelines are comparable.
        try:
            eng.set_hop(self._hops.sample, self._hops.cap)
        except Exception:  # noqa: BLE001 — telemetry only
            pass
        # Re-point the byte-accounting surface at the native counters so
        # lane_stats, the shaped-link byte assertions, and the Manager's
        # GB/s telemetry are engine-agnostic.
        for tid, nexts, prevs in tiers:
            for lane, peer in enumerate(nexts):
                peer._native_bytes = (
                    lambda eng=eng, tid=tid, lane=lane: eng.link_bytes(tid, 0, lane)
                )
            for lane, peer in enumerate(prevs):
                peer._native_bytes = (
                    lambda eng=eng, tid=tid, lane=lane: eng.link_bytes(tid, 1, lane)
                )
            for direction, peers in ((0, nexts), (1, prevs)):
                shaper = peers[0].shaper if peers else None
                if shaper is not None:
                    self._wire_native_shaper_hooks(eng, shaper, tid, direction)
        return eng

    @staticmethod
    def _wire_native_shaper_hooks(eng, shaper: LinkShaper, tid: int, direction: int) -> None:
        """Points one LinkShaper's byte/wait reads at the native engine's
        pacer counters — the ONE wiring used at engine creation and by
        set_link_shaping's lazy attach, so the hook shape cannot drift
        between the two paths."""
        shaper._native_read = (
            lambda eng=eng, tid=tid, d=direction: eng.shaper_counters(tid, d)
        )
        shaper._native_wait = (
            lambda eng=eng, tid=tid, d=direction: eng.shaper_wait_s(tid, d)
        )

    # Channel ids in the 12-byte connection preamble (rank, channel, lane).
    # _CH_ROW/_CH_COL are the 2D topology's tier rings — distinct channels
    # (not just distinct tags) so the accept side can route each socket to
    # its tier's lane table and shaper.
    _CH_RING = 0
    _CH_P2P = 1
    _CH_ROW = 2
    _CH_COL = 3
    _PREAMBLE = struct.Struct("<III")

    def _rendezvous(self) -> None:
        listener = socket.create_server(("", 0), family=socket.AF_INET6, dualstack_ipv6=True)
        listener.listen(16 + 6 * self._lanes)
        self._listener = listener
        # Incarnation token: minted with the listener, republished by every
        # incremental configure.  (addr, token) equality across a quorum
        # transition is the proof the SAME process incarnation still holds
        # the far end of our lane sockets — an address alone could be a
        # respawn that recycled the ephemeral port.
        self._listener_token = os.urandom(8).hex()
        port = listener.getsockname()[1]
        host = socket.gethostname()
        self._self_addr = f"{host}:{port}"
        self._store.set(f"rank_{self._rank}", self._self_addr.encode())
        # Mode token: "full" tells neighbors our previous sockets are GONE
        # (abort() closed them) so they must not try to reuse the edge.
        self._store.set(
            f"cfg_{self._rank}", f"full:{self._listener_token}".encode()
        )

        n = self._world_size
        rank = self._rank
        lanes = self._lanes
        next_rank = (rank + 1) % n
        prev_rank = (rank - 1) % n
        # One serialization budget per peer DIRECTION, shared by every lane
        # of that direction: shaped benches cannot widen the modeled link by
        # adding lanes, and the direction's byte counters stay whole.  Each
        # 2D tier direction is a DIFFERENT physical peer link, so it gets
        # its own budget (matching per-neighbor DCN provisioning).
        next_shaper = LinkShaper.from_env()
        prev_shaper = LinkShaper.from_env()

        # 2D grid tiers: rank (r, c) on an R x C grid rendezvouses a row
        # ring (same r, all c) and a column ring (same c, all r) alongside
        # the flat ring.  Grid geometry derives from (world_size, rank)
        # alone, identically on every rank.
        self._row_tier = None
        self._col_tier = None
        tier_specs: List[tuple] = []  # (channel, tier, prev_shaper)
        if self._active_topology == "ring2d":
            rows, cols = _grid_shape(n)
            r, c = divmod(rank, cols)
            self._row_tier = _TierLinks(
                size=cols,
                ring_rank=c,
                next_rank=r * cols + (c + 1) % cols,
                prev_rank=r * cols + (c - 1) % cols,
            )
            self._col_tier = _TierLinks(
                size=rows,
                ring_rank=r,
                next_rank=((r + 1) % rows) * cols + c,
                prev_rank=((r - 1) % rows) * cols + c,
            )
            tier_specs = [
                (self._CH_ROW, self._row_tier, LinkShaper.from_env()),
                (self._CH_COL, self._col_tier, LinkShaper.from_env()),
            ]
        self._ring_prev_shaper = prev_shaper
        self._tier_prev_shapers = {ch: sh for ch, _t, sh in tier_specs}

        # Persistent accept loop: registers the per-lane ring links from
        # prev (flat and tier rings, keyed by channel) and any lazily-dialed
        # point-to-point links (used by checkpoint transports to move
        # weights between arbitrary replica pairs, the reference's
        # pg.send/recv path, torchft/checkpointing/pg_transport.py:197-301).
        # Keyed by LISTENER identity, not generation: an incremental
        # reconfigure bumps the generation but keeps this listener (and
        # this loop) alive across quorum transitions; prev-direction
        # shapers are read off the instance for the same reason.
        def accept_loop() -> None:
            while True:
                try:
                    conn, _ = listener.accept()
                except OSError:
                    return  # listener closed by abort()
                try:
                    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    # Accepted sockets must carry the op timeout too: a recv
                    # from a stalled-but-open peer has to surface as an error,
                    # not block an executor thread forever.
                    conn.settimeout(self._timeout)
                    peer = _Peer(conn)
                    their_rank, channel, lane = self._PREAMBLE.unpack(
                        peer._recv_exact(self._PREAMBLE.size)
                    )
                    if channel != self._CH_P2P and self._transport != "tcp":
                        self._shm_accept_handshake(peer, their_rank, channel, lane)
                    with self._accept_cond:
                        if self._listener is not listener:
                            conn.close()
                            return
                        if channel == self._CH_P2P:
                            self._peers[their_rank] = peer
                        else:
                            if channel == self._CH_RING:
                                peer.shaper = self._ring_prev_shaper
                            else:
                                peer.shaper = self._tier_prev_shapers.get(channel)
                            self._accepted_ring[(their_rank, channel, lane)] = peer
                        self._accept_cond.notify_all()
                except Exception:  # noqa: BLE001
                    conn.close()

        self._accepted_ring: dict[tuple, _Peer] = {}
        self._accept_thread = threading.Thread(target=accept_loop, daemon=True)
        self._accept_thread.start()

        # Dial our next neighbors, one connection per lane per ring.
        self._next_lanes = [
            self._dial_rank(next_rank, self._CH_RING, lane=lane, shaper=next_shaper)
            for lane in range(lanes)
        ]
        for channel, tier, _sh in tier_specs:
            tier_next_shaper = LinkShaper.from_env()
            tier.next_lanes = [
                self._dial_rank(tier.next_rank, channel, lane=lane, shaper=tier_next_shaper)
                for lane in range(lanes)
            ]

        # Wait for every prev-direction lane: the flat ring's, plus each
        # active tier's.
        expected = [(prev_rank, self._CH_RING, lane) for lane in range(lanes)]
        for channel, tier, _sh in tier_specs:
            expected += [(tier.prev_rank, channel, lane) for lane in range(lanes)]
        deadline = self.RENDEZVOUS_TIMEOUT_MS / 1000
        with self._accept_cond:
            ok = self._accept_cond.wait_for(
                lambda: all(key in self._accepted_ring for key in expected),
                timeout=deadline,
            )
            if not ok:
                missing = [key for key in expected if key not in self._accepted_ring]
                raise TimeoutError(
                    f"rendezvous: ring peers never connected: {missing}"
                )
            self._prev_lanes = [
                self._accepted_ring.pop((prev_rank, self._CH_RING, lane))
                for lane in range(lanes)
            ]
            for channel, tier, _sh in tier_specs:
                tier.prev_lanes = [
                    self._accepted_ring.pop((tier.prev_rank, channel, lane))
                    for lane in range(lanes)
                ]
        # Record each flat-ring neighbor's (addr, token) identity: the
        # evidence the NEXT configure compares to decide whether this
        # edge's sockets survived the membership delta.  Flat ring only —
        # ring2d transitions always take the full path.  Best-effort: a
        # missing identity just forces the full path next time.
        self._neighbor_ids = {}
        if self._active_topology == "ring":
            try:
                nxt = self._peer_identity(next_rank)
                prv = self._peer_identity(prev_rank)
                if nxt is not None and prv is not None:
                    self._neighbor_ids = {"next": nxt[:2], "prev": prv[:2]}
            except Exception:  # noqa: BLE001 — reuse hint only
                pass

    def _peer_identity(
        self, peer_rank: int, timeout_ms: int = 10_000
    ) -> Optional[tuple]:
        """``(addr, token, mode)`` published by ``peer_rank`` in the
        current store namespace — both keys are published before that
        rank's lanes could have connected, so the default short wait
        suffices for surviving neighbors; callers expecting a freshly
        restarted peer pass a rendezvous-scale budget."""
        addr = self._store.get(f"rank_{peer_rank}", wait=True, timeout_ms=timeout_ms)
        cfg = self._store.get(f"cfg_{peer_rank}", wait=True, timeout_ms=timeout_ms)
        if addr is None or cfg is None:
            return None
        mode, _, token = cfg.decode().partition(":")
        if not token:
            return None
        return (addr.decode(), token, mode)

    def _dial_rank(
        self,
        peer_rank: int,
        channel: int,
        timeout: Optional[float] = None,
        lane: int = 0,
        shaper: Optional[LinkShaper] = None,
    ) -> _Peer:
        timeout = timeout if timeout is not None else self.RENDEZVOUS_TIMEOUT_MS / 1000
        addr = self._store.get(
            f"rank_{peer_rank}", wait=True, timeout_ms=int(timeout * 1000)
        )
        if addr is None:
            raise TimeoutError(f"rendezvous: rank {peer_rank} never published its address")
        phost, pport = addr.decode().rsplit(":", 1)
        sock = socket.create_connection(
            (phost, int(pport)), timeout=min(self._timeout, timeout)
        )
        # create_connection's timeout would otherwise persist as the socket's
        # recv/send deadline; ops get the full op timeout.
        sock.settimeout(self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        peer = _Peer(sock, shaper=shaper)
        peer.sock.sendall(self._PREAMBLE.pack(self._rank, channel, lane))
        if channel != self._CH_P2P and self._transport != "tcp":
            self._shm_dial_handshake(peer, peer_rank)
        return peer

    # -- same-host shm lane negotiation -------------------------------------

    def _create_shm_segment(self, their_rank: int, channel: int, lane: int) -> tuple:
        """Creates one fresh /dev/shm segment for a same-host lane link:
        O_EXCL create (any stale leftover under the same name is unlinked
        first), sized header + ring capacity, initialized with the magic
        and a FRESH random generation token.  The token is what makes a
        dead peer's stale segment unattachable: the dialer verifies it
        against the value negotiated on THIS connection, so a leftover
        file from a crashed process can never be re-attached."""
        name = (
            f"tpuft-{os.getpid()}-g{self._generation}-r{their_rank}"
            f"to{self._rank}-c{channel}-l{lane}-{os.urandom(4).hex()}"
        )
        path = "/dev/shm/" + name
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
        cap = _shm_ring_bytes_from_env()
        fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_EXCL, 0o600)
        try:
            os.ftruncate(fd, _SHM_HDR + cap)
            token = int.from_bytes(os.urandom(8), "little") | 1
            os.pwrite(fd, struct.pack("<QQQQI", _SHM_MAGIC, token, 0, 0, 0), 0)
        except OSError:
            os.close(fd)
            try:
                os.unlink(path)
            except OSError:
                pass
            raise
        os.close(fd)
        return path, token

    def _shm_accept_handshake(
        self, peer: _Peer, their_rank: int, channel: int, lane: int
    ) -> None:
        """Acceptor side of the shm negotiation (runs in the accept loop,
        right after the preamble): read the dialer's boot-id; when it
        matches ours, create a fresh segment and offer (token, name); a
        positive ack arms this link's consumer role at engine-arm time."""
        (req,) = _SHM_REQ.unpack(bytes(peer._recv_exact(_SHM_REQ.size)))
        their_boot = req.rstrip(b"\x00")
        mine = _boot_id()
        flag, token, name, path = 0, 0, b"", None
        if mine and their_boot == mine:
            try:
                path, token = self._create_shm_segment(their_rank, channel, lane)
                name = os.path.basename(path).encode()
                flag = 1
            except OSError:
                flag, token, name, path = 0, 0, b"", None
        peer.sock.sendall(_SHM_REP.pack(flag, token, name))
        if not flag:
            return
        if bytes(peer._recv_exact(1)) != b"\x01":
            # Dialer could not attach (or refused): stay on tcp, reclaim
            # the segment now.
            try:
                os.unlink(path)
            except OSError:
                pass
            return
        peer._shm_pending = (path, token, "rx")
        with self._shm_lock:
            self._shm_paths.add(path)

    def _shm_dial_handshake(self, peer: _Peer, peer_rank: int) -> None:
        """Dialer side: send our boot-id; on a same-host offer, verify the
        segment's magic + generation token BEFORE acking (a stale segment
        from a dead peer is refused here) and record the producer role."""
        peer.sock.sendall(_SHM_REQ.pack(_boot_id()))
        flag, token, name = _SHM_REP.unpack(bytes(peer._recv_exact(_SHM_REP.size)))
        if not flag:
            if self._transport == "shm":
                raise ConnectionError(
                    f"TPUFT_RING_TRANSPORT=shm but rank {peer_rank} offered no "
                    "same-host segment (different host, unreadable boot-id, or "
                    "segment creation failed); use transport='auto' for mixed "
                    "placements"
                )
            return
        path = "/dev/shm/" + name.rstrip(b"\x00").decode()
        try:
            fd = os.open(path, os.O_RDWR)
            try:
                magic, tok = struct.unpack("<QQ", os.pread(fd, 16, 0))
            finally:
                os.close(fd)
            if magic != _SHM_MAGIC or tok != token:
                raise ConnectionError(
                    "stale shm segment (generation mismatch) — refusing to attach"
                )
        except Exception:
            peer.sock.sendall(b"\x00")
            if self._transport == "shm":
                raise
            return
        peer.sock.sendall(b"\x01")
        peer._shm_pending = (path, token, "tx")
        with self._shm_lock:
            self._shm_paths.add(path)

    def _arm_shm_links(self) -> None:
        """Applies every rendezvous-negotiated segment to whichever engine
        this configuration runs: the native engine maps segments itself
        (set_shm — its WriteAll/ReadExact then route through the ring),
        the Python engine arms the peers' _ShmRing producer/consumer
        halves.  Called under _lock right after _create_engine."""
        specs = [(0, 0, self._next_lanes), (0, 1, self._prev_lanes)]
        for tid, tier in ((1, self._row_tier), (2, self._col_tier)):
            if tier is not None:
                specs += [(tid, 0, tier.next_lanes), (tid, 1, tier.prev_lanes)]
        self._shm_links = 0
        for tid, direction, peers in specs:
            for lane, peer in enumerate(peers):
                if peer._shm_pending is None:
                    continue
                # Reused (incremental-reconfigure) peers on the Python
                # engine are already armed — their _ShmRing halves map the
                # kept segment and stay valid across generations.
                if self._engine is None and (
                    peer._shm_tx is not None or peer._shm_rx is not None
                ):
                    self._shm_links += 1
                    continue
                path, token, role = peer._shm_pending
                try:
                    if self._engine is not None:
                        self._engine.set_shm(tid, direction, lane, path, token)
                    elif role == "tx":
                        peer._shm_tx = _ShmRing(path, token, peer.sock)
                    else:
                        peer._shm_rx = _ShmRing(path, token, peer.sock)
                except Exception:
                    if self._transport == "shm":
                        raise
                    continue
                self._shm_links += 1

    def _dial(self, peer_rank: int) -> _Peer:
        """Point-to-point link for send/recv to an arbitrary rank.  Exactly
        one side dials (the lower rank); concurrent callers on the dialing
        side coalesce onto one socket per pair.  If the elected dialer fails,
        a waiter takes over; a reconfigure mid-dial invalidates the attempt
        (generation guard) so stale sockets never cross quorum boundaries."""
        deadline = time.monotonic() + self._timeout
        while True:
            with self._accept_cond:
                gen = self._generation
                peer = self._peers.get(peer_rank)
                if peer is not None:
                    return peer
                if self._rank < peer_rank and peer_rank not in self._dialing:
                    self._dialing.add(peer_rank)
                    break  # we are the dialer
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"no point-to-point link to rank {peer_rank} within timeout"
                    )
                if self._rank < peer_rank:
                    # Wake when the link lands, the dialer gives up, or a
                    # reconfigure invalidates this generation.
                    pred = lambda: (
                        peer_rank in self._peers
                        or peer_rank not in self._dialing
                        or self._generation != gen
                    )
                else:
                    pred = lambda: (
                        peer_rank in self._peers or self._generation != gen
                    )
                self._accept_cond.wait_for(pred, timeout=remaining)
                if self._generation != gen:
                    raise RuntimeError("collective reconfigured during dial")
        try:
            # Honor the remaining op budget, not the full rendezvous window:
            # a caller's timeout covers election + dial together.
            peer = self._dial_rank(
                peer_rank,
                self._CH_P2P,
                timeout=max(0.1, deadline - time.monotonic()),
            )
        except Exception:
            with self._accept_cond:
                self._dialing.discard(peer_rank)
                self._accept_cond.notify_all()
            raise
        with self._accept_cond:
            if self._generation != gen:
                self._dialing.discard(peer_rank)
                self._accept_cond.notify_all()
                peer.close()
                raise RuntimeError("collective reconfigured during dial")
            self._peers[peer_rank] = peer
            self._dialing.discard(peer_rank)
            self._accept_cond.notify_all()
        return peer

    def abort(self) -> None:
        with self._lock:
            if self._error is None:
                self._error = RuntimeError("collective aborted")
            # Bank the closing generation's wire/hop counters BEFORE the
            # lanes are torn down: lane_stats zeroes on every configure(),
            # and the cumulative exposition (lane_totals / the worker
            # /metrics endpoint) must never go backwards.  The native
            # engine is still alive here, so its counters are readable.
            self._bank_locked()
            with self._accept_cond:
                peers = list(self._peers.values()) + list(self._accepted_ring.values())
                self._peers = {}
                self._accepted_ring = {}
                # Invalidate in-flight dials: a dial completing after this
                # point must not register its socket into the next
                # generation's peer table.
                self._generation += 1
                self._dialing = set()
                self._accept_cond.notify_all()
            tiers = [t for t in (self._row_tier, self._col_tier) if t is not None]
            tier_peers = [p for t in tiers for p in t.peers()]
            for peer in self._next_lanes + self._prev_lanes + tier_peers + peers:
                if peer is not None:
                    peer.close()
            if self._listener is not None:
                self._listener.close()
                self._listener = None
            # The listener (and its incarnation token) is dead: no edge of
            # ours can be reused by the next transition.
            self._neighbor_ids = {}
            self._self_addr = None
            self._next_lanes = []
            self._prev_lanes = []
            # Unlink every negotiated shm segment (both ends track every
            # path, so the survivor of a peer crash reclaims it; a second
            # unlink is a harmless ENOENT).  The native engine's mappings
            # survive until its close() below — unlink only removes the
            # name.
            with self._shm_lock:
                shm_paths, self._shm_paths = list(self._shm_paths), set()
            self._shm_links = 0
            for sp in shm_paths:
                try:
                    os.unlink(sp)
                except OSError:
                    pass
            if self._executor is not None:
                self._executor.shutdown(wait=False, cancel_futures=True)
                self._executor = None
            if self._ring_executor is not None:
                self._ring_executor.shutdown(wait=False, cancel_futures=True)
                self._ring_executor = None
            if self._lane_executor is not None:
                self._lane_executor.shutdown(wait=False, cancel_futures=True)
                self._lane_executor = None
            for pool in self._send_pools:
                pool.shutdown(wait=False, cancel_futures=True)
            self._send_pools = []
            for tier in tiers:
                for pool in tier.send_pools:
                    pool.shutdown(wait=False, cancel_futures=True)
                tier.send_pools = []
                tier.next_lanes = []
                tier.prev_lanes = []
            self._row_tier = None
            self._col_tier = None
            if self._store is not None:
                self._store.close()
                self._store = None
            engine, self._engine = self._engine, None
            inflight, self._inflight = list(self._inflight), set()
        # Outside the lock: the engine close briefly drains in-flight native
        # ops (they wake instantly — every socket was just shut down), and
        # failing a future runs its done-callbacks inline.
        if engine is not None:
            engine.close()
        err = RuntimeError("collective aborted")
        for fut in inflight:
            if not fut.done():
                try:
                    fut.set_exception(err)
                except Exception:  # noqa: BLE001 — racing completion
                    pass

    def errored(self) -> Optional[Exception]:
        """Reports latched operation failures; cleared by configure()."""
        with self._lock:
            return self._op_error

    def _latch(self, exc: Exception) -> None:
        with self._lock:
            if self._op_error is None:
                self._op_error = exc

    def size(self) -> int:
        return self._world_size

    def rank(self) -> int:
        return self._rank

    # -- ops ----------------------------------------------------------------

    def _submit(self, fn: Callable[[], object], ring: bool = True) -> Work:
        if self._world_size == 1:
            try:
                return Work(completed_future(fn()))
            except Exception as e:  # noqa: BLE001
                self._latch(e)
                return Work(failed_future(e))
        with self._lock:
            executor = self._ring_executor if ring else self._executor
        if executor is None:
            err = self._op_error or RuntimeError("collective not configured")
            return Work(failed_future(err))

        def run() -> object:
            try:
                return fn()
            except Exception as e:  # noqa: BLE001
                self._latch(e)
                raise

        return Work(executor.submit(run))

    def _next_seq(self) -> int:
        """Ring-op sequence number, allocated at call time so identical
        program order on every rank yields identical tag blocks."""
        with self._op_seq_lock:
            seq = self._op_seq
            self._op_seq += 1
        return seq

    def _tag_base(self, seq: int, stripe: int = 0) -> int:
        return (seq * _TAGS_PER_OP + stripe * _TAGS_PER_STRIPE) & 0x7FFFFFFF

    @property
    def topology(self) -> str:
        """The topology the CURRENT configuration resolved to ("ring" or
        "ring2d") — "auto" and degenerate worlds (primes, N < crossover)
        report what actually runs."""
        return self._active_topology

    def _tier_id(self, tier: Optional[_TierLinks]) -> int:
        """The native-engine tier id (0 flat / 1 row / 2 col) for a ring
        loop's ``tier`` argument — the tier key hop records carry."""
        if tier is None:
            return 0
        return 1 if tier is self._row_tier else 2

    def _record_hop(self, tier: Optional[_TierLinks], lane: int, tag: int,
                    hop: dict, comb_s: float = 0.0) -> None:
        """Commits one Python-orchestrated hop (the dict ``_exchange``
        filled) into the recorder."""
        self._hops.record(
            self._tier_id(tier),
            lane,
            tag,
            hop.get("send_s", 0.0),
            hop.get("recv_s", 0.0),
            comb_s,
            hop.get("nbytes", 0),
            hop.get("ts", 0.0),
        )

    def _hop_stats_tier(self, tier_id: int) -> dict:
        """Merged per-tier hop aggregates: Python-orchestrated hops from
        the local recorder plus (under the native engine) the ring passes
        recorded inside ring.cc — ONE engine-agnostic surface."""
        s = self._hops.stats(tier_id)
        eng = self._engine
        if eng is not None:
            try:
                ns = eng.hop_stats(tier_id)
                s = {k: s[k] + ns[k] for k in s}
            except Exception:  # noqa: BLE001 — telemetry only
                pass
        return s

    def _tier_shape_s(self, tier: Optional[_TierLinks]) -> float:
        """Shaping sleep charged to one tier's next direction (sends pace
        outbound only)."""
        peers = tier.next_lanes if tier is not None else self._next_lanes
        shaper = peers[0].shaper if peers else None
        return float(shaper.wait_s) if shaper is not None else 0.0

    def hop_records(self) -> List[dict]:
        """The retained data-plane hop timeline (both engines' records
        merged, oldest first) — dicts with exactly HOP_RECORD_FIELDS.
        Bounded by TPUFT_HOP_RING per engine; sampled per
        TPUFT_HOP_SAMPLE.  ``obs/trace.py`` renders this as the per-lane
        data-plane Perfetto track."""
        recs = self._hops.records()
        eng = self._engine
        if eng is not None:
            try:
                recs = recs + eng.hop_records(self._hops.cap)
            except Exception:  # noqa: BLE001 — telemetry only
                pass
        recs.sort(key=lambda r: r.get("ts", 0.0))
        return recs

    def _live_counters(self) -> dict:
        """Current-generation cumulative counters in lane_totals' shape."""
        tiers: Dict[str, dict] = {}
        hops: Dict[str, dict] = {}
        specs = [("flat", None, self._next_lanes, self._prev_lanes)]
        for name, tier in (("row", self._row_tier), ("col", self._col_tier)):
            if tier is not None:
                specs.append((name, tier, tier.next_lanes, tier.prev_lanes))
        for name, tier, nexts, prevs in specs:
            tiers[name] = {
                "sent_bytes": sum(p.bytes_out for p in list(nexts)),
                "recv_bytes": sum(p.bytes_in for p in list(prevs)),
            }
            tid = self._tier_id(tier)
            hops[name] = dict(self._hop_stats_tier(tid))
            hops[name]["shape_s"] = self._tier_shape_s(tier)
        return {
            "sent_bytes": sum(t["sent_bytes"] for t in tiers.values()),
            "recv_bytes": sum(t["recv_bytes"] for t in tiers.values()),
            "tiers": tiers,
            "hops": hops,
        }

    def _bank_locked(self) -> None:
        """Folds the current generation's counters into the lifetime bank
        (caller holds _lock; called by abort() before lane teardown)."""
        if not self._next_lanes:
            return  # nothing configured this generation
        try:
            live = self._live_counters()
        except Exception:  # noqa: BLE001 — telemetry must not fail abort
            return
        bank = self._lifetime
        bank["reconfigures"] = int(bank.get("reconfigures", 0)) + 1
        bank["sent_bytes"] = int(bank.get("sent_bytes", 0)) + live["sent_bytes"]
        bank["recv_bytes"] = int(bank.get("recv_bytes", 0)) + live["recv_bytes"]
        tiers = bank.setdefault("tiers", {})
        for name, t in live["tiers"].items():
            slot = tiers.setdefault(name, {"sent_bytes": 0, "recv_bytes": 0})
            slot["sent_bytes"] += t["sent_bytes"]
            slot["recv_bytes"] += t["recv_bytes"]
        hops = bank.setdefault("hops", {})
        for name, h in live["hops"].items():
            slot = hops.setdefault(
                name,
                {"hops": 0, "send_block_s": 0.0, "recv_wait_s": 0.0,
                 "combine_s": 0.0, "shape_s": 0.0},
            )
            for k in slot:
                slot[k] += h.get(k, 0)
        # The native engine (and its hop timeline) dies with this
        # generation — fold its retained records into the Python ring so
        # a post-abort dump (Manager shutdown after a fault) still holds
        # the hops leading up to the failure.
        eng = self._engine
        if eng is not None:
            try:
                for rec in eng.hop_records(self._hops.cap):
                    self._hops.keep(rec)
            except Exception:  # noqa: BLE001 — telemetry only
                pass
        # The recorder's AGGREGATES are now IN the bank; without this
        # reset a lane_totals() read in the abort->configure window (or
        # after shutdown, forever) would add them a second time — the
        # banked hops would read ~2x and then DROP later, the exact
        # backwards-counter regression the bank exists to prevent.  The
        # TIMELINE stays: it is never summed into the bank, and it is the
        # black box the fault-path hop dump reads.  (The byte counters
        # need no equivalent: the peers carrying them are cleared by
        # abort() itself.)
        self._hops.reset_aggregates()

    def lane_totals(self) -> dict:
        """MONOTONIC cumulative wire/hop counters across reconfigures:
        the lifetime bank (every closed generation, banked at abort())
        plus the live generation.  This is what any scrape-visible
        exposition of lane counters must read — ``lane_stats()`` resets on
        every configure(), so exporting it directly would show Prometheus
        counters going backwards across quorum reconfigurations.

        Never blocks a scrape on the collective's big lock: configure()
        holds it across the full network rendezvous (up to the connect
        timeout when a peer is dead — exactly the fault windows telemetry
        exists to explain), so a contended read degrades to the BANK-ONLY
        snapshot (last closed generations; monotonic, slightly stale)
        instead of hanging the /metrics endpoint."""
        acquired = self._lock.acquire(timeout=0.5)
        try:
            bank = self._lifetime
            if not acquired:
                live = {"sent_bytes": 0, "recv_bytes": 0, "tiers": {}, "hops": {}}
            else:
                try:
                    live = self._live_counters()
                except Exception:  # noqa: BLE001
                    live = {"sent_bytes": 0, "recv_bytes": 0, "tiers": {},
                            "hops": {}}
            out = {
                "reconfigures": int(bank.get("reconfigures", 0)),
                "sent_bytes": int(bank.get("sent_bytes", 0)) + live["sent_bytes"],
                "recv_bytes": int(bank.get("recv_bytes", 0)) + live["recv_bytes"],
                "tiers": {},
                "hops": {},
            }
            names = set(live["tiers"]) | set(bank.get("tiers", {}))
            for name in names:
                b = (bank.get("tiers") or {}).get(name, {})
                l = live["tiers"].get(name, {})
                out["tiers"][name] = {
                    "sent_bytes": int(b.get("sent_bytes", 0)) + int(l.get("sent_bytes", 0)),
                    "recv_bytes": int(b.get("recv_bytes", 0)) + int(l.get("recv_bytes", 0)),
                }
            names = set(live["hops"]) | set(bank.get("hops", {}))
            for name in names:
                b = (bank.get("hops") or {}).get(name, {})
                l = live["hops"].get(name, {})
                out["hops"][name] = {
                    k: (b.get(k, 0) or 0) + (l.get(k, 0) or 0)
                    for k in ("hops", "send_block_s", "recv_wait_s",
                              "combine_s", "shape_s")
                }
            return out
        finally:
            if acquired:
                self._lock.release()

    def set_link_shaping(self, mbps: float, rtt_ms: float,
                         direction: str = "next", tier: str = "flat") -> None:
        """Re-shapes ONE peer direction's modeled link mid-run, in
        whichever engine owns the pacing — the slow-link bench's
        fault injector (a real deployment's analogue is the physical link
        degrading; no reconfigure happens either way)."""
        tid = {"flat": 0, "row": 1, "col": 2}[tier]
        t = {"flat": None, "row": self._row_tier, "col": self._col_tier}[tier]
        if t is None:
            peers = self._next_lanes if direction == "next" else self._prev_lanes
        else:
            peers = t.next_lanes if direction == "next" else t.prev_lanes
        shared: Optional[LinkShaper] = None
        for p in peers:
            if p.shaper is None:
                # mbps <= 0 means "disable pacing"; with no shaper attached
                # there is nothing to disable — and constructing one with a
                # zero rate would divide the next send by zero.
                if mbps <= 0:
                    continue
                if shared is None:
                    shared = LinkShaper(mbps, rtt_ms)
                p.shaper = shared
            else:
                p.shaper.set_rate(mbps, rtt_ms)
        eng = self._engine
        if eng is not None:
            d = 0 if direction == "next" else 1
            try:
                eng.set_shaper(tid, d, mbps, rtt_ms)
                # A collective configured UNSHAPED never wired the
                # native-counter hooks (_create_engine only hooks shapers
                # that existed at configure) — without them the freshly
                # attached Python shaper would read its own zeros while
                # the native pacer does the sleeping, and the shaping
                # bucket of link_attribution would silently read 0.
                sh = peers[0].shaper if peers else None
                if sh is not None and sh._native_wait is None:
                    self._wire_native_shaper_hooks(eng, sh, tid, d)
            except Exception:  # noqa: BLE001
                pass

    def lane_stats(self) -> dict:
        """Per-lane wire-byte counters for the current configuration:
        ``{"lanes": L, "topology": ..., "sent": [bytes per next-lane],
        "recv": [bytes per prev-lane]}``, plus a ``"tiers"`` map with the
        same sent/recv counters per 2D tier ("row"/"col", with each tier's
        ring size) when the hierarchical topology is active — the per-tier
        attribution that keeps step_summary's byte accounting comparable
        across topologies.  Cumulative since the last configure(); feeds
        the Manager's allreduce GB/s telemetry and the bench artifacts."""
        nexts, prevs = list(self._next_lanes), list(self._prev_lanes)
        out = {
            "lanes": self._lanes,
            "topology": self._active_topology,
            "engine": self.ring_engine,
            "sent": [p.bytes_out for p in nexts],
            "recv": [p.bytes_in for p in prevs],
        }
        tiers = {}
        for name, tier in (("row", self._row_tier), ("col", self._col_tier)):
            if tier is not None:
                tiers[name] = {
                    "size": tier.size,
                    "sent": [p.bytes_out for p in list(tier.next_lanes)],
                    "recv": [p.bytes_in for p in list(tier.prev_lanes)],
                }
        if tiers:
            out["tiers"] = tiers
        # Data-plane hop telemetry: per-tier stall aggregates (both
        # engines merged) + shaping sleep — rides step_summary's
        # allreduce_lanes into obs.report's link_attribution split and the
        # Manager's per-neighbor link health estimate.
        hops = {"flat": dict(self._hop_stats_tier(0))}
        hops["flat"]["shape_s"] = self._tier_shape_s(None)
        for name, tier in (("row", self._row_tier), ("col", self._col_tier)):
            if tier is not None:
                hops[name] = dict(self._hop_stats_tier(self._tier_id(tier)))
                hops[name]["shape_s"] = self._tier_shape_s(tier)
        out["hops"] = hops
        return out

    # Wire codecs this collective's allreduce accepts (see WIRE_CODECS).
    wire_codecs = WIRE_CODECS

    def allreduce(
        self,
        arrays: Sequence[np.ndarray],
        op: str = "sum",
        allow_wire_compression: bool = True,
        wire_codec: Optional[str] = None,
        donate: bool = False,
    ) -> Work:
        """``donate=True`` hands the input buffers to the op: the caller
        promises not to read them again, so the native engine may reduce IN
        PLACE over them (zero-copy — no defensive working-buffer memcpy)
        and the results may alias the inputs.  Safe for temporaries and for
        staging buffers overwritten before the next round (the DDP wire
        stage); the Python engine ignores the hint (it never mutates
        inputs), so results are bitwise-identical either way."""
        # Validate BEFORE the world-size-1 fast path: a typo'd op must fail
        # on a single-replica config too, not only after scaling up.
        if op not in _REDUCE_COMBINE:
            return Work(failed_future(_bad_reduce_op(op)))
        if wire_codec is not None:
            if wire_codec not in WIRE_CODECS:
                return Work(
                    failed_future(
                        ValueError(
                            f"unsupported wire_codec {wire_codec!r}; expected "
                            f"one of {WIRE_CODECS}"
                        )
                    )
                )
            # int8 quantization of integer payloads would corrupt them the
            # same way the bf16 gate guards against — codecs are float-only.
            # (_is_bf16: bfloat16 is floating but not an np.floating
            # subtype — see the helper's docstring.)
            if not all(
                np.issubdtype(np.asarray(a).dtype, np.floating)
                or _is_bf16(np.asarray(a).dtype)
                for a in arrays
            ):
                return Work(
                    failed_future(
                        ValueError(
                            f"wire_codec={wire_codec!r} requires floating "
                            "inputs"
                        )
                    )
                )
        arrays = [np.ascontiguousarray(a) for a in arrays]
        if self._world_size == 1:
            return Work(completed_future(list(arrays)))
        seq = self._next_seq()
        if self._active_topology == "ring2d":
            if self._lanes > 1:
                return self._striped_hier_allreduce(
                    arrays, op, allow_wire_compression, seq, codec=wire_codec,
                    donate=donate,
                )
            return self._submit(
                lambda: self._hier_allreduce(
                    arrays, op, allow_wire_compression, seq, codec=wire_codec,
                    donate=donate,
                )
            )
        if self._lanes > 1:
            return self._striped_allreduce(
                arrays, op, allow_wire_compression, seq, codec=wire_codec,
                donate=donate,
            )
        return self._submit(
            lambda: self._ring_allreduce(
                arrays, op, allow_wire_compression, seq, codec=wire_codec,
                donate=donate,
            )
        )

    def _exchange(self, tag: int, payload, lane: int = 0,
                  tier: Optional[_TierLinks] = None,
                  hop: Optional[dict] = None) -> bytes:
        """Sends to the next neighbor while receiving from the previous one,
        on the given lane's socket pair (of the flat ring, or of ``tier``
        when a 2D tier ring is passed).  Full-duplex is required: with
        payloads larger than the kernel socket buffers, blocking
        send-then-recv deadlocks the ring.  The send runs on the lane's
        persistent sender worker — a striped allreduce makes hundreds of
        hops per op, and a fresh thread per hop is pure scheduler churn.
        One worker per lane serializes sends exactly like the peer's
        send_lock already does, so ordering is unchanged.

        ``hop`` (optional, a mutable dict) is filled with the hop's
        timing split — ``ts`` (wall clock at start), ``recv_s`` (blocked
        on the inbound frame), ``send_s`` (additional wait joining the
        send after the recv returned), ``nbytes`` (payload bytes sent) —
        the data-plane flight recorder's feed.  Over the native socket
        layer the engine's exchange blocks for recv AND send together, so
        the whole wait lands in ``recv_s`` (documented coarse split for
        Python-orchestrated control ops; the ring hot loop's native hops
        are split natively inside ring.cc)."""
        if hop is not None:
            hop["ts"] = time.time()
        engine = self._engine
        if engine is not None:
            # Native path: the engine's per-link sender thread + demux do
            # the full-duplex work GIL-free; all ring-lane socket reads go
            # through its one stash, so native ring passes and Python-
            # orchestrated ops (this path) can interleave on one lane.
            tier_id = 0 if tier is None else (1 if tier is self._row_tier else 2)
            if isinstance(payload, (list, tuple)):
                payload = b"".join(bytes(p) for p in payload)
            elif not isinstance(payload, bytes):
                payload = bytes(payload)
            t0 = time.monotonic()
            out = engine.exchange(tier_id, lane, tag, payload, self._timeout)
            if hop is not None:
                hop["recv_s"] = time.monotonic() - t0
                hop["send_s"] = 0.0
                hop["nbytes"] = len(payload)
            return out
        if tier is not None:
            nxt = tier.next_lanes[lane]
            prv = tier.prev_lanes[lane]
            pools = tier.send_pools
        else:
            nxt = self._next_lanes[lane]
            prv = self._prev_lanes[lane]
            pools = self._send_pools
        if not pools:
            raise RuntimeError("collective aborted")
        if isinstance(payload, (bytes, bytearray)):
            payload = memoryview(payload)
        nbytes = (
            sum(len(p) for p in payload)
            if isinstance(payload, (list, tuple))
            else len(payload)
        )
        sent = pools[lane].submit(nxt.send_msg, tag, payload)
        # A recv error propagates as-is (matching the old join-then-drop
        # behavior); the in-flight send fails on its own when _fail_ring /
        # abort closes the lane sockets.
        t0 = time.monotonic()
        received = prv.recv_msg(tag)
        t1 = time.monotonic()
        sent.result(timeout=self._timeout)
        if hop is not None:
            hop["recv_s"] = t1 - t0
            hop["send_s"] = time.monotonic() - t1
            hop["nbytes"] = nbytes
        return received

    @property
    def wire_dtype(self) -> str:
        """The resolved wire encoding ("f32" or "bf16").  Public so the
        data-plane layers above (GradientAverager's device wire prep) can
        cast payloads to the wire dtype ON DEVICE and fetch half the bytes
        — planning that cast requires knowing what this collective would
        put on the wire anyway."""
        return self._wire_dtype

    def wire_nbytes(
        self,
        array,
        allow_wire_compression: bool = True,
        wire_codec: Optional[str] = None,
    ) -> int:
        """Bytes ``array`` would occupy PER HOP on the ring wire — the
        single source of truth for wire-byte telemetry (the Manager's
        allreduce_gb_per_s gauge), so a change to ``_wire_for``'s gating
        cannot silently diverge from what the accounting counts.  With
        ``wire_codec="int8"`` floating payloads count 1 byte per element
        plus the per-frame scale header (~0.25x the f32 wire); with
        ``"int4"`` they count the PACKED nibble bytes — ceil(n/2) plus
        the scale header (~0.125x) — never the int8 frame width."""
        array = np.asarray(array)
        is_float = (
            np.issubdtype(array.dtype, np.floating) or _is_bf16(array.dtype)
        )
        if wire_codec == "int8" and is_float:
            return int(array.size) + _INT8_SCALE.size
        if wire_codec == "int4" and is_float:
            return (int(array.size) + 1) // 2 + _INT8_SCALE.size
        wire, _ = self._wire_for([array], array.dtype, allow_wire_compression)
        if wire is not None:
            return int(array.size) * wire.itemsize
        return int(array.nbytes)

    def _wire_for(
        self, arrays: Sequence[np.ndarray], flat_dtype, allow_wire_compression: bool
    ):
        """``(wire, acc_dtype)`` for one allreduce.

        ``wire`` is bfloat16 when compression is allowed, configured, and
        EVERY input array is floating (not just the promoted buffer dtype)
        — a mixed [f32, int64] call promotes flat to float64, and
        quantizing the integer values would corrupt them.  ``acc_dtype`` is
        the local accumulation dtype (the input dtype normally).

        Inputs that arrive ALREADY in the wire dtype (a device-wire-prepped
        bucket fetched as bf16) keep bf16 on the wire but accumulate in
        float32: per-hop bytes are identical to the host-cast path, and the
        reduction runs at the same precision — only the quantization point
        moved from host CPU to the device epilogue.  Without the explicit
        ``_is_bf16`` branch these payloads would fall through the
        ``np.issubdtype(..., np.floating)`` gate (bf16 is not a numpy
        floating subtype) into raw-bytes framing with bf16 accumulation."""
        if allow_wire_compression and self._wire_dtype == "bf16":
            if np.issubdtype(flat_dtype, np.floating) and all(
                np.issubdtype(a.dtype, np.floating) for a in arrays
            ):
                import ml_dtypes

                return np.dtype(ml_dtypes.bfloat16), np.dtype(flat_dtype)
            if _is_bf16(flat_dtype) and all(_is_bf16(a.dtype) for a in arrays):
                import ml_dtypes

                return np.dtype(ml_dtypes.bfloat16), np.dtype(np.float32)
        return None, np.dtype(flat_dtype)

    def _codec(self, wire, acc_dtype, codec: Optional[str] = None):
        """(encode, decode) for one ring pass: encode casts to the wire
        dtype and frames raw bytes (as_u8, not memoryview.cast, so
        ml_dtypes payloads like bfloat16 frame correctly); decode upcasts
        back to the accumulation dtype.

        ``codec="int8"`` supersedes ``wire``: each frame is a 4-byte f32
        scale followed by int8 values (scale = chunk amax / 127, symmetric
        round-to-nearest).  Accumulation stays in ``acc_dtype`` — each
        reduce-scatter hop decodes, sums full-width, and requantizes with
        its own scale, exactly the bf16 wire's per-hop quantization shape;
        the allgather phase quantizes each owned chunk once and forwards
        the scale+payload bytes verbatim, so every rank decodes
        bitwise-identical results (the commit protocol's premise)."""
        from torchft_tpu.checkpointing.serialization import as_u8

        if codec == "int8":
            def encode(chunk: np.ndarray):
                scale, q = quantize_int8(chunk)
                return [_INT8_SCALE.pack(scale), memoryview(as_u8(q))]

            def decode(raw, n: Optional[int] = None) -> np.ndarray:
                (scale,) = _INT8_SCALE.unpack_from(raw, 0)
                q = np.frombuffer(raw, dtype=np.int8, offset=_INT8_SCALE.size)
                return (q.astype(np.float32) * np.float32(scale)).astype(
                    acc_dtype, copy=False
                )

            return encode, decode

        if codec == "int4":
            # Same frame shape as int8 (4-byte f32 scale + payload) with
            # the payload packed two signed nibbles per byte — 0.125x the
            # f32 wire, bitwise-identical to native/src/ring.cc's
            # Int4Encode frames.  A packed frame of k bytes holds 2k-1 or
            # 2k elements, so decode takes the expected element count from
            # the caller (the ring always knows its chunk geometry).
            def encode(chunk: np.ndarray):
                scale, q = quantize_int4(chunk)
                return [_INT8_SCALE.pack(scale), memoryview(pack_int4(q))]

            def decode(raw, n: Optional[int] = None) -> np.ndarray:
                nbytes = len(raw) - _INT8_SCALE.size
                if n is None:
                    n = nbytes * 2
                (scale,) = _INT8_SCALE.unpack_from(raw, 0)
                q = unpack_int4(memoryview(raw)[_INT8_SCALE.size:], n)
                return (q.astype(np.float32) * np.float32(scale)).astype(
                    acc_dtype, copy=False
                )

            return encode, decode

        def encode(chunk: np.ndarray) -> memoryview:
            if wire is not None:
                chunk = chunk.astype(wire)
            return memoryview(as_u8(chunk))

        def decode(raw, n: Optional[int] = None) -> np.ndarray:
            if wire is not None:
                return np.frombuffer(raw, dtype=wire).astype(acc_dtype)
            return np.frombuffer(raw, dtype=acc_dtype)

        return encode, decode

    # -- native engine dispatch --------------------------------------------

    def _native_wire_mode(
        self, flat_dtype, wire, acc_dtype, codec: Optional[str]
    ) -> Optional[int]:
        """The native engine's wire mode for one allreduce, or None when
        this payload stays on the Python orchestration (no engine, or a
        payload outside the native fast path: integer/f64 accumulation,
        bf16 raw framing, codecs over non-f32 buffers).  The fallback is
        per-op and silent — it still rides the engine's socket layer via
        _exchange, so the demux stays unified."""
        if self._engine is None:
            return None
        if codec is not None:
            if codec not in ("int8", "int4"):
                return None
            return (
                (_NATIVE_WIRE_INT8 if codec == "int8" else _NATIVE_WIRE_INT4)
                if np.dtype(flat_dtype) == np.float32
                and np.dtype(acc_dtype) == np.float32
                else None
            )
        if wire is not None:
            # bf16 wire: f32 accumulation covers both f32 inputs and
            # device-prepped bf16 inputs (upcast is lossless).
            return _NATIVE_WIRE_BF16 if np.dtype(acc_dtype) == np.float32 else None
        return _NATIVE_WIRE_RAW if np.dtype(flat_dtype) == np.float32 else None

    def _native_buffer(self, flat: np.ndarray, fresh: bool = False) -> np.ndarray:
        """The f32 working buffer a native pass mutates IN PLACE — never a
        caller input (the ring never mutates its inputs); bf16 payloads
        upcast losslessly and _unflatten's astype casts back.  ``fresh``
        marks a flat buffer _flatten just ALLOCATED (the multi-array
        concatenate path), which the pass may therefore mutate directly —
        skipping the defensive copy saves a full memcpy per bucket on the
        hot path."""
        if flat.dtype == np.float32:
            return flat if fresh else flat.copy()
        return flat.astype(np.float32)

    def _native_pass_views(
        self,
        views: List[np.ndarray],
        tier_id: int,
        lane: int,
        n: int,
        rank: int,
        tag_base: int,
        rs_sub: int,
        ag_sub: int,
        pass_mode: int,
        op: str,
        wire_mode: int,
    ) -> None:
        """One GIL-free ring pass over contiguous f32 views of the working
        buffer.  The views' addresses go straight to the engine (zero-copy
        scatter-gather I/O over them); the GIL is released for the whole
        pass — this call IS the native hot loop."""
        engine = self._engine
        if engine is None:
            raise RuntimeError("collective aborted")
        engine.ring_pass(
            tier_id,
            lane,
            n,
            rank,
            tag_base,
            rs_sub,
            ag_sub,
            pass_mode,
            _NATIVE_OP[op],
            wire_mode,
            [int(v.ctypes.data) for v in views],
            [int(v.size) for v in views],
            self._timeout,
        )

    def _native_flat_pass(
        self, buf: np.ndarray, lane: int, tag_base: int, op: str, wire_mode: int
    ) -> None:
        """Full flat-ring pass (reduce-scatter + allgather) over ``buf`` in
        place — the native counterpart of one _ring_rs_ag over
        np.array_split(buf, world)."""
        self._native_pass_views(
            list(np.array_split(buf, self._world_size)),
            0,
            lane,
            self._world_size,
            self._rank,
            tag_base,
            _SUB_RS,
            _SUB_AG,
            _NATIVE_PASS_FULL,
            op,
            wire_mode,
        )

    def _native_hier_pass(
        self, buf: np.ndarray, lane: int, tag_base: int, op: str, wire_mode: int
    ) -> None:
        """Hierarchical (ring2d) pass over ``buf`` in place: row
        reduce-scatter, column full pass over the owned row chunk, row
        allgather — the same three phases (and the same tag subspaces) as
        _hier_rs_ag_flat, each phase one GIL-free native call."""
        row = cast(_TierLinks, self._row_tier)
        col = cast(_TierLinks, self._col_tier)
        C, crank = row.size, row.ring_rank
        chunks = list(np.array_split(buf, C))
        self._native_pass_views(
            chunks, 1, lane, C, crank, tag_base, _SUB_RS, _SUB_AG,
            _NATIVE_PASS_RS, op, wire_mode,
        )
        own = (crank + 1) % C
        if col.size > 1:
            self._native_pass_views(
                list(np.array_split(chunks[own], col.size)),
                2, lane, col.size, col.ring_rank, tag_base,
                _SUB_COL_RS, _SUB_COL_AG, _NATIVE_PASS_FULL, op, wire_mode,
            )
        self._native_pass_views(
            chunks, 1, lane, C, crank, tag_base, _SUB_RS, _SUB_AG,
            _NATIVE_PASS_AG, op, wire_mode,
        )

    def _ring_rs_ag(
        self,
        chunks: List[np.ndarray],
        combine,
        wire,
        acc_dtype,
        lane: int,
        tag_base: int,
        tier: Optional[_TierLinks] = None,
        rs_sub: int = _SUB_RS,
        ag_sub: int = _SUB_AG,
        codec: Optional[str] = None,
    ) -> List[np.ndarray]:
        """One complete ring pass (reduce-scatter then allgather) over
        ``chunks`` — one 1-D array per rank slot — on the given lane, over
        the flat ring or a 2D ``tier`` ring.  Returns the fully reduced
        chunk list.  ``tag_base + rs_sub`` / ``+ ag_sub`` pick this pass's
        tags inside the stripe's block so concurrent stripes, back-to-back
        ops, AND nested tier rings demux cleanly (the column tier passes
        its own subtags from the high half of the block).

        Wire compression: floating payloads travel as bfloat16 per hop with
        accumulation in ``acc_dtype`` (or as scale+int8 frames when
        ``codec="int8"``); in the allgather phase each rank quantizes its
        OWNED chunk exactly once and every other rank forwards the received
        WIRE BYTES untouched — no per-hop decode/re-encode, so all ranks
        decode bitwise-identical values (replica consistency — the commit
        protocol's premise).  For the bf16 wire, quantization and
        accumulation are elementwise in fixed ring-step order, so striping
        a chunk across lanes reproduces the single-lane result BIT FOR
        BIT.  The int8 codec's scale is per-FRAME (amax over the encoded
        chunk), so different lane/stripe configs produce slightly
        different values — every rank must run the same config (already
        the collective-wide contract), and a striped run is NOT
        bit-comparable to a single-lane golden run under int8.
        """
        n = tier.size if tier is not None else self._world_size
        rank = tier.ring_rank if tier is not None else self._rank
        chunks = list(chunks)
        encode, decode = self._codec(wire, acc_dtype, codec)

        # Reduce-scatter phase: after n-1 steps, chunk (rank+1)%n holds the
        # full reduction on this rank.
        for step in range(n - 1):
            send_idx = (rank - step) % n
            recv_idx = (rank - step - 1) % n
            hop: dict = {}
            raw = self._exchange(
                tag_base + rs_sub, encode(chunks[send_idx]), lane, tier, hop=hop
            )
            t_comb = time.monotonic()
            incoming = decode(raw, chunks[recv_idx].size)
            chunks[recv_idx] = combine(chunks[recv_idx], incoming)
            self._record_hop(
                tier, lane, tag_base + rs_sub, hop,
                comb_s=time.monotonic() - t_comb,
            )

        return self._ring_ag_phase(
            chunks, wire, acc_dtype, lane, tag_base + ag_sub, tier, codec=codec
        )

    def _ring_ag_phase(
        self,
        chunks: List[np.ndarray],
        wire,
        acc_dtype,
        lane: int,
        tag: int,
        tier: Optional[_TierLinks] = None,
        codec: Optional[str] = None,
    ) -> List[np.ndarray]:
        """Allgather circulation over a ring (flat or a 2D tier): each rank
        owns chunk (rank+1)%n and the owned chunks circulate until every
        rank holds all n.  The ONE implementation of this phase — shared by
        _ring_rs_ag and the hierarchical pass's row allgather, so the wire
        framing and replica-consistency mechanics cannot diverge between
        topologies.  With wire compression (bf16 wire or an int8 codec)
        each owner quantizes its chunk exactly once and every other rank
        forwards the received WIRE BYTES untouched, so all ranks decode
        bitwise-identical values."""
        n = tier.size if tier is not None else self._world_size
        rank = tier.ring_rank if tier is not None else self._rank
        chunks = list(chunks)
        encode, decode = self._codec(wire, acc_dtype, codec)
        if wire is not None or codec is not None:
            own = (rank + 1) % n
            raw_chunks: List[Optional[bytes]] = [None] * n
            enc = encode(chunks[own])
            raw_chunks[own] = (
                b"".join(bytes(p) for p in enc)
                if isinstance(enc, (list, tuple))
                else bytes(enc)
            )
            for step in range(n - 1):
                send_idx = (rank - step + 1) % n
                recv_idx = (rank - step) % n
                hop: dict = {}
                raw_chunks[recv_idx] = self._exchange(
                    tag, memoryview(cast(bytes, raw_chunks[send_idx])), lane, tier,
                    hop=hop,
                )
                self._record_hop(tier, lane, tag, hop)
            return [
                decode(cast(bytes, raw_chunks[i]), chunks[i].size)
                for i in range(n)
            ]
        for step in range(n - 1):
            send_idx = (rank - step + 1) % n
            recv_idx = (rank - step) % n
            hop2: dict = {}
            chunks[recv_idx] = decode(
                self._exchange(tag, encode(chunks[send_idx]), lane, tier, hop=hop2)
            ).copy()
            self._record_hop(tier, lane, tag, hop2)
        return chunks

    def _hier_rs_ag_flat(
        self,
        flat: np.ndarray,
        combine,
        wire,
        acc_dtype,
        lane: int,
        tag_base: int,
        codec: Optional[str] = None,
    ) -> np.ndarray:
        """One hierarchical (2D ring-of-rings) allreduce pass over a flat
        1-D buffer: reduce-scatter along the ROW ring, full allreduce of
        the owned row chunk along the COLUMN ring, allgather back along the
        row.  Returns the fully reduced flat buffer.

        Hops: (C-1) + 2(R-1) + (C-1) versus the flat ring's 2(N-1) — the
        latency term that keeps step time flat as the group count grows.

        Replica consistency: after the column allreduce every member of a
        column holds BITWISE-identical bytes for its owned chunk
        (_ring_rs_ag's allgather forwards the owner's wire bytes), and the
        row allgather forwards those bytes verbatim (each owner re-encodes
        a value that is already exactly representable on the wire), so ALL
        N ranks decode identical results.  Fold order — row partials summed
        in row-ring-step order, then folded across rows in column-ring-step
        order — is fixed by (world_size, rank) alone, hence deterministic
        per topology."""
        row = cast(_TierLinks, self._row_tier)
        col = cast(_TierLinks, self._col_tier)
        C, crank = row.size, row.ring_rank
        chunks = list(np.array_split(flat, C))
        encode, decode = self._codec(wire, acc_dtype, codec)

        # Phase 1: row reduce-scatter — after C-1 hops this rank's owned
        # chunk holds the full reduction over its row.
        for step in range(C - 1):
            send_idx = (crank - step) % C
            recv_idx = (crank - step - 1) % C
            hop: dict = {}
            raw = self._exchange(
                tag_base + _SUB_RS, encode(chunks[send_idx]), lane, row, hop=hop
            )
            t_comb = time.monotonic()
            incoming = decode(raw, chunks[recv_idx].size)
            chunks[recv_idx] = combine(chunks[recv_idx], incoming)
            self._record_hop(
                row, lane, tag_base + _SUB_RS, hop,
                comb_s=time.monotonic() - t_comb,
            )
        own = (crank + 1) % C

        # Phase 2: column allreduce of the owned row chunk, on the column
        # tier's sockets with the tier partition's subtags.  Every member
        # of this column ends with bitwise-identical bytes.
        if col.size > 1:
            sub = self._ring_rs_ag(
                list(np.array_split(chunks[own], col.size)),
                combine, wire, acc_dtype, lane, tag_base,
                tier=col, rs_sub=_SUB_COL_RS, ag_sub=_SUB_COL_AG, codec=codec,
            )
            chunks[own] = np.concatenate(sub) if len(sub) > 1 else sub[0]

        # Phase 3: row allgather of the owned chunks — the SAME shared
        # circulation as the flat ring's allgather phase (with wire
        # compression each owner quantizes once; after phase 2 already
        # decoded wire values that re-encode is an identity, so forwarded
        # bytes stay bitwise-identical everywhere).
        chunks = self._ring_ag_phase(
            chunks, wire, acc_dtype, lane, tag_base + _SUB_AG, tier=row,
            codec=codec,
        )
        return np.concatenate(chunks) if C > 1 else chunks[0]

    def _flatten(self, arrays: Sequence[np.ndarray]) -> np.ndarray:
        """One contiguous working buffer of the common dtype.  A single
        input is viewed, not copied — the ring never mutates its inputs
        (every combine allocates), so the zero-copy view is safe and saves
        a full memcpy per gradient bucket."""
        if len(arrays) > 1:
            return np.concatenate([a.reshape(-1) for a in arrays])
        return arrays[0].reshape(-1)

    def _unflatten(
        self, out_flat: np.ndarray, arrays: Sequence[np.ndarray], op: str
    ) -> List[np.ndarray]:
        if op == "avg":
            out_flat = out_flat / self._world_size
        out: List[np.ndarray] = []
        pos = 0
        for a in arrays:
            out.append(
                out_flat[pos : pos + a.size].reshape(a.shape).astype(a.dtype, copy=False)
            )
            pos += a.size
        return out

    def _ring_allreduce(
        self,
        arrays: List[np.ndarray],
        op: str,
        allow_wire_compression: bool = True,
        seq: Optional[int] = None,
        codec: Optional[str] = None,
        donate: bool = False,
    ) -> List[np.ndarray]:
        """Single-lane whole-chunk ring allreduce (the lanes=1 path, and the
        building block reduce_scatter/barrier reuse)."""
        if seq is None:
            seq = self._next_seq()
        n = self._world_size
        combine = _REDUCE_COMBINE[op]
        flat = self._flatten(arrays)
        wire, acc_dtype = self._wire_for(
            arrays, flat.dtype, allow_wire_compression and codec is None
        )
        wire_mode = self._native_wire_mode(flat.dtype, wire, acc_dtype, codec)
        if wire_mode is not None:
            buf = self._native_buffer(flat, fresh=donate or len(arrays) > 1)
            self._native_flat_pass(buf, 0, self._tag_base(seq), op, wire_mode)
            return self._unflatten(buf, arrays, op)
        chunks = np.array_split(flat, n)
        chunks = self._ring_rs_ag(
            chunks, combine, wire, acc_dtype, lane=0,
            tag_base=self._tag_base(seq), codec=codec,
        )
        return self._unflatten(np.concatenate(chunks), arrays, op)

    def _hier_allreduce(
        self,
        arrays: List[np.ndarray],
        op: str,
        allow_wire_compression: bool = True,
        seq: Optional[int] = None,
        codec: Optional[str] = None,
        donate: bool = False,
    ) -> List[np.ndarray]:
        """Single-lane hierarchical (ring2d) allreduce — the lanes=1
        counterpart of _ring_allreduce, running one 2D pass over the whole
        flattened payload."""
        if seq is None:
            seq = self._next_seq()
        combine = _REDUCE_COMBINE[op]
        flat = self._flatten(arrays)
        wire, acc_dtype = self._wire_for(
            arrays, flat.dtype, allow_wire_compression and codec is None
        )
        wire_mode = self._native_wire_mode(flat.dtype, wire, acc_dtype, codec)
        if wire_mode is not None:
            buf = self._native_buffer(flat, fresh=donate or len(arrays) > 1)
            self._native_hier_pass(buf, 0, self._tag_base(seq), op, wire_mode)
            return self._unflatten(buf, arrays, op)
        out = self._hier_rs_ag_flat(
            flat, combine, wire, acc_dtype, lane=0,
            tag_base=self._tag_base(seq), codec=codec,
        )
        return self._unflatten(out, arrays, op)

    def _stripe_count(self, max_chunk_nbytes: int) -> int:
        """Stripes per ring chunk: enough to keep every lane busy, sized at
        ~chunk_bytes so stripe k's combine overlaps stripe k+1's wire time,
        rounded to a lane multiple for balance, capped for tag/frame
        overhead."""
        per = max(1, self._chunk_bytes)
        s = max(self._lanes, -(-max_chunk_nbytes // per))
        s = -(-s // self._lanes) * self._lanes
        # The cap must stay a lane multiple AND come after the rounding: a
        # post-cap round-up (e.g. 64 -> 66 at 6 lanes) would spill stripe
        # tags past this seq's _TAGS_PER_OP block into the next op's.
        return min(s, _MAX_STRIPES - _MAX_STRIPES % self._lanes)

    def _run_striped(self, nstripes: int, stripe_body, assemble) -> Work:
        """Shared striped-op scaffolding (flat and hierarchical topologies):
        runs ``stripe_body(s)`` for every stripe on the per-lane worker
        pool, fails the whole op fast on the first stripe error — latch +
        _fail_ring, which closes the flat lanes AND both 2D tiers' lanes of
        this generation so sibling stripes blocked on any tier fail
        immediately — and resolves the returned Work with
        ``assemble(results)`` when the last stripe lands."""
        with self._lock:
            lane_exec = self._lane_executor
            gen = self._generation
        if lane_exec is None:
            err = self._op_error or RuntimeError("collective not configured")
            return Work(failed_future(err))

        results: List[Optional[object]] = [None] * nstripes
        out: Future = Future()
        state_lock = threading.Lock()
        state = {"pending": nstripes, "failed": False}
        with self._lock:
            self._inflight.add(out)

        def settle_err(e: Exception) -> None:
            self._latch(e)
            # Close the ring lanes of THIS generation so sibling stripes
            # blocked in send/recv fail fast instead of burning the full op
            # timeout; the op is already doomed and errors latch until the
            # next configure() rebuilds every lane.
            self._fail_ring(gen)
            with self._lock:
                self._inflight.discard(out)
            if not out.done():
                try:
                    out.set_exception(e)
                except Exception:  # noqa: BLE001 — racing abort()
                    pass

        def finish() -> None:
            try:
                outs = assemble(results)
            except Exception as e:  # noqa: BLE001
                settle_err(e)
                return
            with self._lock:
                self._inflight.discard(out)
            if not out.done():
                try:
                    out.set_result(outs)
                except Exception:  # noqa: BLE001 — racing abort()
                    pass

        def make_stripe(s: int):
            def run() -> None:
                try:
                    res = stripe_body(s)
                except Exception as e:  # noqa: BLE001
                    with state_lock:
                        first = not state["failed"]
                        state["failed"] = True
                    if first:
                        settle_err(e)
                    return
                results[s] = res
                with state_lock:
                    state["pending"] -= 1
                    done = state["pending"] == 0 and not state["failed"]
                if done:
                    finish()

            return run

        try:
            for s in range(nstripes):
                lane_exec.submit(make_stripe(s))
        except RuntimeError as e:  # executor shut down by a concurrent abort
            settle_err(e)
        return Work(out)

    def _striped_allreduce(
        self,
        arrays: List[np.ndarray],
        op: str,
        allow_wire_compression: bool,
        seq: int,
        codec: Optional[str] = None,
        donate: bool = False,
    ) -> Work:
        """Lanes > 1: stripe the ring chunks round-robin across lanes and run
        each stripe as an independent tagged ring on the per-lane worker
        pool.  Stripes of one op overlap each other (sum vs wire), and
        back-to-back ops (gradient buckets) overlap too — the Work future
        resolves when every stripe lands."""
        n = self._world_size
        combine = _REDUCE_COMBINE[op]
        try:
            flat = self._flatten(arrays)
            chunks = np.array_split(flat, n)
            wire, acc_dtype = self._wire_for(
                arrays, flat.dtype, allow_wire_compression and codec is None
            )
            # Stripe sizing from the ORIGINAL flat chunks (not the native
            # f32 working copy) so both engines carve identical stripe
            # boundaries and tag blocks — the cross-engine interop contract.
            nstripes = self._stripe_count(max(c.nbytes for c in chunks))
            wire_mode = self._native_wire_mode(flat.dtype, wire, acc_dtype, codec)
            if wire_mode is not None:
                buf = self._native_buffer(flat, fresh=donate or len(arrays) > 1)
                # sub[i][s]: stripe s of rank-chunk i, a view into buf the
                # engine reduces in place — assembly is just _unflatten.
                sub = [
                    np.array_split(c, nstripes)
                    for c in np.array_split(buf, n)
                ]
            else:
                sub = [np.array_split(c, nstripes) for c in chunks]
        except Exception as e:  # noqa: BLE001
            self._latch(e)
            return Work(failed_future(e))

        if wire_mode is not None:
            engine = self._engine

            def stripe_body(_s: int) -> None:
                # ONE capi crossing for the whole stripe set: per-stripe
                # fan-out runs on the engine's internal worker pool
                # (ring.cc RingPassMulti), with identical stripe/lane/tag
                # geometry to the per-stripe path — so this rank
                # interoperates with peers still making one ring_pass per
                # stripe, and with the Python engine.
                if engine is None:
                    raise RuntimeError("collective aborted")
                engine.ring_pass_multi(
                    0,
                    nstripes,
                    n,
                    self._rank,
                    [s % self._lanes for s in range(nstripes)],
                    [self._tag_base(seq, s) for s in range(nstripes)],
                    _SUB_RS,
                    _SUB_AG,
                    _NATIVE_PASS_FULL,
                    _NATIVE_OP[op],
                    wire_mode,
                    [
                        int(sub[i][s].ctypes.data)
                        for s in range(nstripes)
                        for i in range(n)
                    ],
                    [
                        int(sub[i][s].size)
                        for s in range(nstripes)
                        for i in range(n)
                    ],
                    self._timeout,
                )

            def assemble(results: List[Optional[object]]) -> List[np.ndarray]:
                return self._unflatten(buf, arrays, op)

            # One "stripe" from _run_striped's perspective — the whole
            # batched pass; back-to-back ops still overlap on the lane
            # executor's other workers.
            return self._run_striped(1, stripe_body, assemble)

        def stripe_body(s: int) -> List[np.ndarray]:
            return self._ring_rs_ag(
                [sub[i][s] for i in range(n)],
                combine,
                wire,
                acc_dtype,
                lane=s % self._lanes,
                tag_base=self._tag_base(seq, s),
                codec=codec,
            )

        def assemble(results: List[Optional[object]]) -> List[np.ndarray]:
            # One concatenate in (chunk, stripe) order — a per-chunk
            # concat followed by a cross-chunk concat would memcpy the
            # whole reduced payload twice on the hot path.
            segs = [
                cast(list, results[s])[i]
                for i in range(n)
                for s in range(nstripes)
            ]
            return self._unflatten(np.concatenate(segs), arrays, op)

        return self._run_striped(nstripes, stripe_body, assemble)

    def _striped_hier_allreduce(
        self,
        arrays: List[np.ndarray],
        op: str,
        allow_wire_compression: bool,
        seq: int,
        codec: Optional[str] = None,
        donate: bool = False,
    ) -> Work:
        """Lanes > 1 under the 2D topology: split the flat payload into
        stripes directly (stripe-major — each stripe runs the COMPLETE
        hierarchical pass, cutting its own row/column chunks), so stripes
        overlap on the wire exactly like the flat striped path while tag
        blocks and lane assignment stay per-stripe.  Stripe boundaries
        derive from the identical flat length on every rank."""
        combine = _REDUCE_COMBINE[op]
        try:
            flat = self._flatten(arrays)
            wire, acc_dtype = self._wire_for(
                arrays, flat.dtype, allow_wire_compression and codec is None
            )
            row_cols = cast(_TierLinks, self._row_tier).size
            # Size stripes so each stripe's ROW chunk (its per-hop exchange
            # unit) lands near chunk_bytes, mirroring the flat path's
            # per-rank-chunk sizing.  Sized from the ORIGINAL flat payload
            # so both engines carve identical stripes (interop contract).
            nstripes = self._stripe_count(-(-flat.nbytes // max(1, row_cols)))
            wire_mode = self._native_wire_mode(flat.dtype, wire, acc_dtype, codec)
            if wire_mode is not None:
                buf = self._native_buffer(flat, fresh=donate or len(arrays) > 1)
                stripes = np.array_split(buf, nstripes)
            else:
                stripes = np.array_split(flat, nstripes)
        except Exception as e:  # noqa: BLE001
            self._latch(e)
            return Work(failed_future(e))

        if wire_mode is not None:

            def stripe_body(s: int) -> None:
                self._native_hier_pass(
                    stripes[s], s % self._lanes, self._tag_base(seq, s), op,
                    wire_mode,
                )

            def assemble(results: List[Optional[object]]) -> List[np.ndarray]:
                return self._unflatten(buf, arrays, op)

            return self._run_striped(nstripes, stripe_body, assemble)

        def stripe_body(s: int) -> np.ndarray:
            return self._hier_rs_ag_flat(
                stripes[s],
                combine,
                wire,
                acc_dtype,
                lane=s % self._lanes,
                tag_base=self._tag_base(seq, s),
                codec=codec,
            )

        def assemble(results: List[Optional[object]]) -> List[np.ndarray]:
            parts = [cast(np.ndarray, r) for r in results]
            return self._unflatten(
                np.concatenate(parts) if len(parts) > 1 else parts[0], arrays, op
            )

        return self._run_striped(nstripes, stripe_body, assemble)

    def _fail_ring(self, gen: int) -> None:
        """Closes this generation's ring lane sockets — flat AND both 2D
        tiers — so every stripe/op blocked on any of them fails fast: a
        hierarchical stripe can be mid-hop in either tier when a sibling
        fails, and a survivor blocked in the column ring must not ride out
        the full op timeout because only the row sockets died.  The
        generation guard keeps a stale failure from touching the next
        quorum's fresh lanes."""
        with self._lock:
            if self._generation != gen:
                return
            peers = list(self._next_lanes) + list(self._prev_lanes)
            for tier in (self._row_tier, self._col_tier):
                if tier is not None:
                    peers += tier.peers()
            engine = self._engine
        for p in peers:
            p.close()
        # The native engine's dup'd lane fds die with the generation too
        # (the fd-sweep contract); counters stay readable, ops fail fast.
        if engine is not None:
            engine.close()

    def allgather(self, array: np.ndarray) -> Work:
        array = np.ascontiguousarray(array)
        if self._world_size == 1:
            return Work(completed_future([array.copy()]))
        seq = self._next_seq()
        return self._submit(lambda: self._ring_allgather(array, self._tag_base(seq) + _SUB_GATHER))

    def _ring_allgather(self, array: np.ndarray, tag: int) -> List[np.ndarray]:
        import pickle

        n = self._world_size
        rank = self._rank
        slots: List[Optional[bytes]] = [None] * n
        slots[rank] = pickle.dumps(array)
        for step in range(n - 1):
            send_idx = (rank - step) % n
            recv_idx = (rank - step - 1) % n
            slots[recv_idx] = self._exchange(tag, slots[send_idx])
        return [pickle.loads(s) for s in slots]

    def broadcast(self, array: np.ndarray, root: int = 0) -> Work:
        array = np.ascontiguousarray(array)
        if self._world_size == 1:
            return Work(completed_future(array.copy()))
        seq = self._next_seq()

        def run() -> np.ndarray:
            out = self._ring_allgather(array, self._tag_base(seq) + _SUB_GATHER)[root]
            return out

        return self._submit(run)

    def reduce_scatter(self, arrays: Sequence[np.ndarray], op: str = "sum") -> Work:
        if op not in _REDUCE_COMBINE:
            return Work(failed_future(_bad_reduce_op(op)))
        arrays = [np.ascontiguousarray(a) for a in arrays]
        if self._world_size == 1:
            return Work(completed_future(arrays[0].copy()))
        if len(arrays) != self._world_size:
            return Work(
                failed_future(
                    ValueError(
                        f"reduce_scatter needs world_size={self._world_size} inputs, "
                        f"got {len(arrays)}"
                    )
                )
            )
        seq = self._next_seq()

        def run() -> np.ndarray:
            # Implemented over ring allreduce of the stacked buffer; rank i
            # keeps slice i.  Adequate for the replica dim's small world sizes.
            stacked = np.stack(arrays)
            reduced = self._ring_allreduce([stacked], op, seq=seq)[0]
            return reduced[self._rank]

        return self._submit(run)

    def alltoall(self, arrays: Sequence[np.ndarray]) -> Work:
        arrays = [np.ascontiguousarray(a) for a in arrays]
        if self._world_size == 1:
            return Work(completed_future([arrays[0].copy()]))
        seq = self._next_seq()

        def run() -> List[np.ndarray]:
            import pickle

            n = self._world_size
            rank = self._rank
            # Route through the ring: circulate everyone's full payload list.
            slots: List[Optional[bytes]] = [None] * n
            slots[rank] = pickle.dumps(list(arrays))
            tag = self._tag_base(seq) + _SUB_GATHER
            for step in range(n - 1):
                send_idx = (rank - step) % n
                recv_idx = (rank - step - 1) % n
                slots[recv_idx] = self._exchange(tag, slots[send_idx])
            lists = [pickle.loads(s) for s in slots]
            return [lists[src][rank] for src in range(n)]

        return self._submit(run)

    def _fifo_queue(self, key: tuple) -> _FifoQueue:
        with self._fifo_lock:
            q = self._fifo.get(key)
            if q is None:
                q = self._fifo[key] = _FifoQueue()
            return q

    def _sever_peer(self, peer_rank: int, gen: int, used: Optional[_Peer]) -> None:
        """Closes the p2p socket a failed op was using so its in-flight or
        matching remote ops fail fast instead of pairing with a later op's
        frame.  Guards: the generation check keeps a failure that straddles a
        reconfigure from touching the NEW quorum's socket, and the identity
        check keeps a stale failure (op blocked on an already-severed socket)
        from closing a freshly re-dialed healthy replacement."""
        if used is None:
            return
        with self._accept_cond:
            if self._generation != gen or self._peers.get(peer_rank) is not used:
                used = None  # registered peer is not the one that failed
            else:
                del self._peers[peer_rank]
        if used is not None:
            used.close()

    def _p2p_op(
        self, q: _FifoQueue, peer_rank: int, body: Callable[[List[_Peer]], object]
    ) -> Work:
        # Ticket + submit must be atomic: with 4 p2p workers, an inverted
        # executor order could park every worker in wait_turn on later
        # tickets while the earliest is still queued behind them, stalling
        # the stream for the whole timeout window.  (Dedicated lock:
        # _fifo_lock nests inside _lock in configure(), and _submit takes
        # _lock, so reusing _fifo_lock here would invert that order.)
        with self._p2p_submit_lock:
            seq = q.take_ticket()
            gen = self._generation

            def run() -> object:
                # Never advance the turnstile past a never-executed slot:
                # poison the stream so the remote side's matching op errors
                # instead of silently pairing with the next frame.
                try:
                    q.wait_turn(seq, self._timeout)
                except Exception as e:  # noqa: BLE001
                    # Queue stall: poison only.  Severing here would kill a
                    # healthy transfer still progressing on the shared socket
                    # (its per-syscall timeouts never fired); the remote's
                    # matching op simply times out on its own socket.
                    q.poison_with(e)
                    raise
                used: List[_Peer] = []
                try:
                    out = body(used)
                except Exception as e:  # noqa: BLE001
                    # Body failure may have left a partial frame on the wire:
                    # sever the exact link this op used so both sides fail fast.
                    q.poison_with(e)
                    self._sever_peer(peer_rank, gen, used[0] if used else None)
                    raise
                q.done()
                return out

            return self._submit(run, ring=False)

    # p2p frame: u32 meta_len | pickled (np.dtype, shape) | raw array bytes.
    # The array body crosses the wire without pickling — on the GB-scale
    # healing path a pickle.dumps is a full extra memcpy of the state dict.
    # The dtype OBJECT is pickled (not .str): custom dtypes like bfloat16
    # stringify as '<V2' and would round-trip as void16.
    _P2P_META = struct.Struct("<I")

    def send(self, array: np.ndarray, dst: int, tag: int = 0) -> Work:
        array = np.ascontiguousarray(array)
        q = self._fifo_queue(("send", dst, tag))

        def body(used: List[_Peer]) -> None:
            import pickle

            from torchft_tpu.checkpointing.serialization import as_u8

            peer = self._dial(dst)
            used.append(peer)
            meta = pickle.dumps((array.dtype, array.shape))
            # as_u8 handles ml_dtypes (bfloat16) that memoryview cannot cast.
            peer.send_msg(
                100 + tag,
                [self._P2P_META.pack(len(meta)), meta, memoryview(as_u8(array))],
            )

        return self._p2p_op(q, dst, body)

    def recv(self, shape: tuple, dtype, src: int, tag: int = 0) -> Work:
        q = self._fifo_queue(("recv", src, tag))

        def body(used: List[_Peer]) -> np.ndarray:
            import pickle

            peer = self._dial(src)
            used.append(peer)
            raw = peer.recv_msg(100 + tag)
            (mlen,) = self._P2P_META.unpack_from(raw, 0)
            rdtype, rshape = pickle.loads(
                bytes(raw[self._P2P_META.size : self._P2P_META.size + mlen])
            )
            body_off = self._P2P_META.size + mlen
            # raw is a writable bytearray: the returned array is mutable and
            # copy-free, matching the old pickle path's contract.
            return (
                np.frombuffer(raw, dtype=np.uint8, offset=body_off)
                .view(rdtype)
                .reshape(rshape)
            )

        return self._p2p_op(q, src, body)

    def barrier(self) -> Work:
        if self._world_size == 1:
            return Work(completed_future(None))
        token = np.zeros(1, dtype=np.int32)
        seq = self._next_seq()
        return self._submit(
            lambda: (self._ring_allreduce([token], "sum", seq=seq), None)[1]
        )


class ErrorSwallowingCollective(Collective):
    """Latches the first error and turns subsequent operations into immediate
    no-ops until the next configure() (reference:
    ErrorSwallowingProcessGroupWrapper, torchft/process_group.py:906-960)."""

    def __init__(self, inner: Collective) -> None:
        self._inner = inner
        self._error: Optional[Exception] = None

    def configure(self, store_addr: str, rank: int, world_size: int) -> None:
        self._error = None
        self._inner.configure(store_addr, rank, world_size)

    # Wire-policy probes proxy to the wrapped collective: layers above
    # (GradientAverager's device wire prep, the semisync engine's codec
    # gate, the Manager's wire-byte telemetry) discover capabilities via
    # getattr — a wrapper that hides them would silently degrade the wire
    # and fork the byte accounting.

    @property
    def wire_codecs(self):
        return getattr(self._inner, "wire_codecs", ())

    @property
    def wire_dtype(self):
        return getattr(self._inner, "wire_dtype", None)

    def wire_nbytes(
        self,
        array,
        allow_wire_compression: bool = True,
        wire_codec: Optional[str] = None,
    ) -> int:
        probe = getattr(self._inner, "wire_nbytes", None)
        if callable(probe):
            # Forward the codec arg only when set, like every other call
            # site — an inner collective with the pre-codec 2-arg probe
            # signature must keep working for plain calls.
            if wire_codec is not None:
                return probe(array, allow_wire_compression, wire_codec)
            return probe(array, allow_wire_compression)
        return int(np.asarray(array).nbytes)

    def errored(self) -> Optional[Exception]:
        return self._error or self._inner.errored()

    def report_error(self, exc: Exception) -> None:
        if self._error is None:
            self._error = exc

    def _guard(self, fn: Callable[[], Work], fallback) -> Work:
        if self.errored() is not None:
            return Work(completed_future(fallback))
        work = fn()

        def on_done(f: Future) -> None:
            exc = f.exception()
            if exc is not None:
                self.report_error(exc)

        work.add_done_callback(on_done)
        # Swallow: map failure to the fallback value.
        out: Future = Future()

        def settle(f: Future) -> None:
            if f.exception() is not None:
                out.set_result(fallback)
            else:
                out.set_result(f.result())

        work.future().add_done_callback(settle)
        return Work(out)

    def allreduce(
        self,
        arrays: Sequence[np.ndarray],
        op: str = "sum",
        allow_wire_compression: bool = True,
        wire_codec: Optional[str] = None,
        donate: bool = False,
    ) -> Work:
        # Optional kwargs forwarded only when set (mock-compat: an inner
        # collective with the bare 3-arg signature must keep working).
        extra: Dict[str, Any] = {}
        if wire_codec is not None:
            extra["wire_codec"] = wire_codec
        if donate:
            extra["donate"] = True
        return self._guard(
            lambda: self._inner.allreduce(
                arrays, op, allow_wire_compression, **extra
            ),
            list(arrays),
        )

    def allgather(self, array: np.ndarray) -> Work:
        return self._guard(lambda: self._inner.allgather(array), [array])

    def broadcast(self, array: np.ndarray, root: int = 0) -> Work:
        return self._guard(lambda: self._inner.broadcast(array, root), array)

    def reduce_scatter(self, arrays: Sequence[np.ndarray], op: str = "sum") -> Work:
        return self._guard(lambda: self._inner.reduce_scatter(arrays, op), arrays[0])

    def alltoall(self, arrays: Sequence[np.ndarray]) -> Work:
        return self._guard(lambda: self._inner.alltoall(arrays), list(arrays))

    def send(self, array: np.ndarray, dst: int, tag: int = 0) -> Work:
        return self._guard(lambda: self._inner.send(array, dst, tag), None)

    def recv(self, shape: tuple, dtype, src: int, tag: int = 0) -> Work:
        return self._guard(
            lambda: self._inner.recv(shape, dtype, src, tag), np.zeros(shape, dtype)
        )

    def barrier(self) -> Work:
        return self._guard(lambda: self._inner.barrier(), None)

    def size(self) -> int:
        return self._inner.size()

    def rank(self) -> int:
        return self._inner.rank()

    def abort(self) -> None:
        self._inner.abort()


class ManagedCollective(Collective):
    """Collective facade bound to a Manager: operations wait for quorum, report
    errors to the manager, and size() reflects the dynamic participant count.
    This is what makes mesh/array code see the fault-tolerant replica
    dimension (reference: ManagedProcessGroup, torchft/process_group.py:963-1028)."""

    def __init__(self, manager) -> None:  # Manager; untyped to avoid cycle
        self._manager = manager

    def configure(self, store_addr: str, rank: int, world_size: int) -> None:
        self._manager._collective.configure(store_addr, rank, world_size)

    def allreduce(
        self,
        arrays: Sequence[np.ndarray],
        op: str = "sum",
        allow_wire_compression: bool = True,
    ) -> Work:
        # Manager.allreduce implements exactly the fault-tolerant gradient
        # semantic: sum over participants / num_participants (an average).
        # Other reduce ops must not silently return averaged data — use the
        # raw collective (manager.collective()) for those.
        if op not in ("sum", "avg"):
            return Work(
                failed_future(
                    ValueError(
                        f"ManagedCollective.allreduce implements the "
                        f"participant-averaged gradient reduction; op={op!r} "
                        "is not expressible through it"
                    )
                )
            )
        futs = [self._manager.allreduce(a) for a in arrays]
        out: Future = Future()

        def gather(_f: Future) -> None:
            if all(f.done() for f in futs) and not out.done():
                out.set_result([f.result() for f in futs])

        for f in futs:
            f.add_done_callback(gather)
        return Work(out)

    def allgather(self, array: np.ndarray) -> Work:
        self._manager.wait_quorum()
        return self._manager._collective.allgather(array)

    def broadcast(self, array: np.ndarray, root: int = 0) -> Work:
        self._manager.wait_quorum()
        return self._manager._collective.broadcast(array, root)

    def reduce_scatter(self, arrays: Sequence[np.ndarray], op: str = "sum") -> Work:
        self._manager.wait_quorum()
        return self._manager._collective.reduce_scatter(arrays, op)

    def alltoall(self, arrays: Sequence[np.ndarray]) -> Work:
        self._manager.wait_quorum()
        return self._manager._collective.alltoall(arrays)

    def send(self, array: np.ndarray, dst: int, tag: int = 0) -> Work:
        self._manager.wait_quorum()
        return self._manager._collective.send(array, dst, tag)

    def recv(self, shape: tuple, dtype, src: int, tag: int = 0) -> Work:
        self._manager.wait_quorum()
        return self._manager._collective.recv(shape, dtype, src, tag)

    def barrier(self) -> Work:
        self._manager.wait_quorum()
        return self._manager._collective.barrier()

    def size(self) -> int:
        return self._manager.num_participants()

    def rank(self) -> int:
        return self._manager.participating_rank() or 0

    def errored(self) -> Optional[Exception]:
        return self._manager.errored()

    def abort(self) -> None:
        self._manager._collective.abort()
